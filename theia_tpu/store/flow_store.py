"""In-memory columnar flow database — the framework's L1 storage tier.

Plays the role ClickHouse plays in the reference (tables declared in
build/charts/theia/provisioning/datasources/create_table.sh): a `flows`
table receiving high-rate inserts, three streaming materialized views
(pod/node/policy — create_table.sh:92-351), result tables for the analytics
jobs (`tadetector` create_table.sh:363-384, `recommendations` :353-360),
TTL-based eviction (:87-88) and a retention monitor that trims the oldest
fraction of rows when a capacity threshold is exceeded (reference:
plugins/clickhouse-monitor/main.go:258-320).

Design (TPU-first): tables are append-logs of equal-schema `ColumnarBatch`es
sharing one dictionary set owned by the table, so any time-window selection
is a zero-copy concat + boolean mask over fixed-width arrays, ready for
`jax.device_put`. Materialized views are maintained *incrementally* on
insert as integer-keyed segment sums (the SummingMergeTree equivalent),
keeping the read path for dashboards O(view rows), not O(flow rows).
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import os
import tempfile
import threading
import time
import zlib
from typing import Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..schema import (
    DROPDETECTION_SCHEMA,
    FLOW_SCHEMA,
    FLOWPATTERNS_SCHEMA,
    RECOMMENDATIONS_SCHEMA,
    SPATIALNOISE_SCHEMA,
    TADETECTOR_SCHEMA,
    ColumnarBatch,
    DictionaryMapper,
    StringDictionary,
)

#: analytics result tables, in declaration order — the single list the
#: store, sharded facade, stats, persistence, and job GC iterate
RESULT_TABLE_SCHEMAS = (
    ("tadetector", TADETECTOR_SCHEMA),
    ("recommendations", RECOMMENDATIONS_SCHEMA),
    ("dropdetection", DROPDETECTION_SCHEMA),
    ("flowpatterns", FLOWPATTERNS_SCHEMA),
    ("spatialnoise", SPATIALNOISE_SCHEMA),
)
from ..obs import metrics as _metrics
from ..utils.backoff import capped_backoff
from ..utils.env import env_float
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from .views import MATERIALIZED_VIEWS, ViewTable

_logger = get_logger("store")

_M_INS_ROWS = _metrics.counter(
    "theia_store_inserted_rows_total",
    "Flow rows inserted, cumulative over every physical store in the "
    "process (a replicated fan-out counts once per replica)")
_M_INS_BYTES = _metrics.counter(
    "theia_store_inserted_bytes_total",
    "Column bytes of inserted flow rows (store-coded), cumulative per "
    "physical store")
_M_DEL_ROWS = _metrics.counter(
    "theia_store_deleted_rows_total",
    "Flow rows deleted by TTL eviction or retention trims",
    labelnames=("reason",))
_M_MV_FANOUT = _metrics.histogram(
    "theia_store_mv_fanout_seconds",
    "Materialized-view fan-out time per inserted block (all views)")
_M_RET_ROUNDS = _metrics.counter(
    "theia_retention_rounds_total",
    "Retention-monitor rounds, by outcome",
    labelnames=("result",))
_M_RET_DELETED = _metrics.counter(
    "theia_retention_rows_deleted_total",
    "Flow rows trimmed by capacity-based retention rounds")
_M_SNAP_FALLBACK = _metrics.counter(
    "theia_snapshot_fallbacks_total",
    "Snapshot loads that failed verification on the primary file and "
    "fell back to the previous good snapshot (<path>.prev)")

#: snapshot payload keys outside the table namespace
WAL_LSNS_KEY = "__wal__/lsns"
INTEGRITY_KEY = "__integrity__/crc32"


class SnapshotCorruption(Exception):
    """A snapshot file failed integrity verification."""


def _view_pool() -> concurrent.futures.ThreadPoolExecutor:
    """Shared pool for parallel MV fan-out (native group-sum releases
    the GIL, so the three aggregations genuinely overlap)."""
    return get_pool("mv-fanout", 4)


class Table:
    """Append-only columnar table with store-owned dictionaries.

    All inserted batches are re-encoded (if necessary) against the table's
    dictionaries, so codes are comparable across the whole table and string
    predicates compile to integer comparisons.
    """

    def __init__(self, name: str, schema) -> None:
        self.name = name
        self.schema = schema
        self.dicts: Dict[str, StringDictionary] = {
            c.name: StringDictionary() for c in schema if c.is_string}
        self._batches: List[ColumnarBatch] = []
        self._lock = threading.Lock()
        #: monotonic mutation counter (inserts AND deletes) — the
        #: checkpointer's change detector; row counts alone can't see
        #: same-size churn (TTL evicts N, ingest adds N)
        self.generation = 0
        # Cumulative insert totals (rows / store-coded column bytes),
        # maintained under the table lock. Unlike net table size these
        # never decrease, so insert-rate stats based on them survive
        # retention trims (deletes used to mask real throughput).
        self.rows_inserted_total = 0
        self.bytes_inserted_total = 0
        # Cached source-dict → table-dict code mappings: a producer
        # streaming blocks with its own dictionaries pays string
        # re-encode only for NEW entries, not per block (the 6.6x
        # per-block store overhead of BENCH_r04).
        self._adopt_maps: Dict[str, DictionaryMapper] = {
            name: DictionaryMapper(d) for name, d in self.dicts.items()}
        self._adopt_lock = threading.Lock()
        # Durability hook, installed by FlowDatabase.attach_wal:
        # called as hook(table_name, adopted, apply_fn) so the WAL can
        # journal the store-coded batch BEFORE apply_fn makes it
        # visible (and the caller acknowledges it). None = no WAL.
        self._wal_hook: Optional[Callable] = None

    def __len__(self) -> int:
        return sum(len(b) for b in self._batches)

    @property
    def nbytes(self) -> int:
        return sum(v.nbytes for b in self._batches
                   for v in b.columns.values())

    def _adopt(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Re-encode a batch against this table's dictionaries
        (cached incremental mappings: amortized O(new dict entries)
        per block, not O(dictionary))."""
        cols: Dict[str, np.ndarray] = {}
        for col in self.schema:
            arr = batch[col.name]
            if col.is_string:
                src = batch.dicts.get(col.name)
                if src is None:
                    raise ValueError(
                        f"string column {col.name} has no dictionary")
                if src is not self.dicts[col.name]:
                    with self._adopt_lock:
                        arr = self._adopt_maps[col.name].remap(arr, src)
            else:
                arr = np.asarray(arr, dtype=col.host_dtype)
            cols[col.name] = arr
        return ColumnarBatch(cols, self.dicts)

    def insert(self, batch: ColumnarBatch,
               dedup: Optional[tuple] = None) -> Optional[ColumnarBatch]:
        """Insert a batch; returns the adopted (store-coded) batch, or
        None when empty, so callers can fan out the exact inserted block
        without re-reading the append log under concurrency. With a
        WAL attached, the record is journaled before the rows become
        visible — a failed append fails the insert (no ack without
        durability). `dedup=(stream, seq[, total_rows])` stamps the
        producer's batch identity (and the logical batch size — a
        sharded insert journals per-slice) into the WAL record
        (wal.pack_dedup_tag), making the acknowledgement itself
        crash-durable: recovery replays the rows AND restores the
        dedup-window entry from the same frame, so a retried batch is
        idempotent across kill -9."""
        if len(batch) == 0:
            return None
        adopted = self._adopt(batch)
        hook = self._wal_hook
        if hook is None:
            self._append_adopted(adopted)
        else:
            name = self.name
            if dedup is not None:
                from .wal import pack_dedup_tag
                stream, seq = dedup[0], int(dedup[1])
                # the LOGICAL batch total (callers that know it pass
                # it; a bare slice defaults to its own length)
                total = (int(dedup[2]) if len(dedup) > 2
                         and dedup[2] is not None else len(batch))
                name = pack_dedup_tag(self.name, stream, seq, total)
            hook(name, adopted, self._append_adopted)
        return adopted

    def _append_adopted(self, adopted: ColumnarBatch) -> None:
        """Make an already-adopted batch visible (the memory apply)."""
        nbytes = sum(a.nbytes for a in adopted.columns.values())
        with self._lock:
            self._batches.append(adopted)
            self.generation += 1
            self.rows_inserted_total += len(adopted)
            self.bytes_inserted_total += nbytes

    def insert_rows(self, rows: Sequence[Mapping[str, object]]) -> int:
        if not rows:
            return 0
        adopted = self.insert(
            ColumnarBatch.from_rows(rows, self.schema, self.dicts))
        return 0 if adopted is None else len(adopted)

    def scan(self) -> ColumnarBatch:
        """Whole-table view as one batch (concat of the append log).

        Compacts the log as a side effect; the swap only happens if no
        insert raced in between (otherwise the next scan compacts)."""
        with self._lock:
            batches = list(self._batches)
        if not batches:
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype) for c in self.schema},
                self.dicts)
        if len(batches) == 1:
            return batches[0]
        merged = ColumnarBatch.concat(batches)
        with self._lock:
            if len(self._batches) == len(batches) and \
                    self._batches[-1] is batches[-1]:
                self._batches = [merged]
        return merged

    def select(self, start_time: Optional[int] = None,
               end_time: Optional[int] = None,
               time_column: str = "flowStartSeconds",
               end_column: str = "flowEndSeconds") -> ColumnarBatch:
        """Time-window select, mirroring the jobs' SQL predicates
        (`flowStartSeconds >= start AND flowEndSeconds < end`, reference
        policy_recommendation_job.py:796-798)."""
        data = self.scan()
        if start_time is None and end_time is None:
            return data
        mask = np.ones(len(data), dtype=bool)
        if start_time is not None:
            mask &= data[time_column] >= start_time
        if end_time is not None:
            mask &= data[end_column] < end_time
        return data.filter(mask)

    def delete_where(self, mask: np.ndarray) -> int:
        """Delete rows matching `mask` over the current table contents.
        Runs entirely under the table lock so a concurrent insert can
        neither be dropped nor half-filtered."""
        with self._lock:
            return self._delete_where_locked(mask)

    def _delete_where_locked(self, mask: np.ndarray) -> int:
        """Body of delete_where; caller must hold self._lock (the
        sharded store holds every shard's lock to apply one logical
        mask atomically across shards)."""
        if not self._batches:
            if len(mask) != 0:
                raise ValueError(
                    f"mask length {len(mask)} != table length 0")
            return 0
        data = (self._batches[0] if len(self._batches) == 1
                else ColumnarBatch.concat(self._batches))
        if len(mask) != len(data):
            raise ValueError(
                f"mask length {len(mask)} != table length {len(data)}")
        if not mask.any():
            # No mutation → no generation bump: a spurious bump makes
            # the checkpointer rewrite an unchanged snapshot.
            return 0
        kept = data.filter(~mask)
        self._batches = [kept] if len(kept) else []
        self.generation += 1
        return int(mask.sum())

    def delete_ids(self, ids, column: str = "id",
                   invert: bool = False) -> int:
        """Value-based delete: rows whose `column` decodes into `ids`
        (or does NOT, with invert=True). Safe wherever a positional
        mask is not — replicas and shards hold the same logical rows
        in different physical orders. Computed under the table lock."""
        with self._lock:
            if not self._batches:
                return 0
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            mask = np.isin(data.strings(column), list(ids))
            if invert:
                mask = ~mask
            return self._delete_where_locked(mask)

    def delete_older_than(self, boundary: int,
                          column: str = "timeInserted") -> int:
        """Atomic `column < boundary` delete (mask computed under the
        lock, so it cannot race with inserts)."""
        with self._lock:
            if not self._batches:
                return 0
            data = (self._batches[0] if len(self._batches) == 1
                    else ColumnarBatch.concat(self._batches))
            mask = np.asarray(data[column]) < boundary
            if not mask.any():
                self._batches = [data]
                return 0
            kept = data.filter(~mask)
            self._batches = [kept] if len(kept) else []
            self.generation += 1
        return int(mask.sum())

    def min_value(self, column: str = "timeInserted") -> Optional[int]:
        """Min over a column without concatenating (None when empty)."""
        with self._lock:
            batches = list(self._batches)
        mins = [int(b[column].min()) for b in batches if len(b)]
        return min(mins) if mins else None

    def truncate(self) -> None:
        with self._lock:
            self._batches = []
            self.generation += 1


class RetentionMonitor:
    """Capacity-based retention, one round per `tick()` call.

    Reference semantics (plugins/clickhouse-monitor/main.go:258-320 and
    Helm defaults values.yaml:16-30): every interval, if used/total >
    threshold, find the timeInserted boundary below which the oldest
    `delete_percentage` of rows fall, delete rows older than the boundary
    from the flows table and all materialized views, then skip
    `skip_rounds` rounds after a successful deletion.
    """

    def __init__(self, db: "FlowDatabase", capacity_bytes: int,
                 threshold: float = 0.5, delete_percentage: float = 0.5,
                 skip_rounds: int = 3) -> None:
        self.db = db
        self.capacity_bytes = capacity_bytes
        self.threshold = threshold
        self.delete_percentage = delete_percentage
        self.skip_rounds = skip_rounds
        self._remaining_skip = 0

    def usage(self) -> float:
        return self.db.flows.nbytes / float(self.capacity_bytes)

    def tick(self) -> int:
        """Run one monitor round; returns number of flow rows deleted."""
        if self._remaining_skip > 0:
            self._remaining_skip -= 1
            return 0
        if self.usage() <= self.threshold:
            return 0
        flows = self.db.flows.scan()
        n = len(flows)
        if n == 0:
            return 0
        delete_n = int(n * self.delete_percentage)
        if delete_n == 0:
            return 0
        t = np.sort(np.asarray(flows["timeInserted"]))
        # timeInserted of the latest row to delete (LIMIT 1 OFFSET n-1,
        # main.go:301-318); delete strictly-older rows like the reference's
        # `timeInserted < boundary`.
        boundary = t[delete_n - 1]
        deleted = self.db.delete_flows_older_than(int(boundary))
        if deleted:
            self._remaining_skip = self.skip_rounds
            _M_RET_DELETED.inc(deleted)
            _M_DEL_ROWS.labels(reason="retention").inc(deleted)
        return deleted


class RetentionLoop:
    """Supervised background driver for RetentionMonitor — the role of
    the reference's clickhouse-monitor sidecar loop
    (plugins/clickhouse-monitor/main.go:83-101: a ticker that runs a
    monitor round forever). The monitor itself stays a pure
    one-round-per-tick object; this loop owns the thread, the
    schedule, and the failure policy:

      * one `tick()` per THEIA_RETENTION_INTERVAL seconds (injectable
        for tests via `interval`/`run_once()` — no sleeping tests);
      * a FAILED round (e.g. every replica down mid-trim) backs off
        with the shared `capped_backoff` schedule instead of hammering
        a broken store every interval; the first clean round resets
        the cadence;
      * rounds / rows-deleted / failures are counted here (and as
        metrics), surfaced through `stats()` on GET /healthz.
    """

    def __init__(self, monitor: RetentionMonitor,
                 interval: Optional[float] = None,
                 backoff_cap: float = 300.0) -> None:
        self.monitor = monitor
        self.interval = (env_float("THEIA_RETENTION_INTERVAL", 60.0)
                         if interval is None else float(interval))
        self.backoff_cap = backoff_cap
        self.rounds = 0
        self.rows_deleted = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-retention")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.current_delay):
            self.run_once()

    def run_once(self) -> int:
        """One supervised round; returns rows deleted (0 on a failed
        round). Public so tests drive the schedule synchronously."""
        try:
            deleted = self.monitor.tick()
        except Exception as e:   # a bad round must not kill the loop
            self.failures += 1
            self.consecutive_failures += 1
            self.current_delay = capped_backoff(
                max(self.interval, 0.001) * 2, self.backoff_cap,
                self.consecutive_failures)
            _M_RET_ROUNDS.labels(result="error").inc()
            _logger.error(
                "retention round failed (%d consecutive): %s; "
                "backing off %.1fs", self.consecutive_failures, e,
                self.current_delay)
            return 0
        if self.consecutive_failures:
            _logger.info("retention recovered after %d failed rounds",
                         self.consecutive_failures)
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self.rounds += 1
        self.rows_deleted += deleted
        _M_RET_ROUNDS.labels(
            result="trimmed" if deleted else "idle").inc()
        if deleted:
            _logger.info("retention trimmed %d rows (usage %.1f%%)",
                         deleted, self.monitor.usage() * 100)
        return deleted

    def stats(self) -> Dict[str, object]:
        """Operator view (merged into GET /healthz)."""
        try:
            usage = self.monitor.usage()
        except Exception:
            usage = float("nan")
        return {
            "rounds": self.rounds,
            "rowsDeleted": self.rows_deleted,
            "failures": self.failures,
            "intervalSeconds": self.interval,
            "capacityBytes": self.monitor.capacity_bytes,
            "usagePercent": round(usage * 100, 2),
        }


def payload_digest(payload: Mapping[str, np.ndarray]) -> int:
    """Content checksum over a snapshot payload (every key except the
    integrity stamp itself) — defense in depth over the zip
    container's per-member CRCs: one whole-payload value that covers
    cross-member consistency (a member replaced or dropped with the
    container left valid) and survives a future non-zip snapshot
    format. Object (string-table) arrays hash their joined utf-8
    contents in one pass, so the digest is stable across a save/load
    round trip and costs far less than the compression beside it."""
    crc = 0
    for key in sorted(payload):
        if key == INTEGRITY_KEY:
            continue
        arr = np.asarray(payload[key])
        crc = zlib.crc32(key.encode("utf-8"), crc)
        if arr.dtype == object:
            blob = "\x1f".join(map(str, arr.reshape(-1).tolist()))
            crc = zlib.crc32(blob.encode("utf-8", "surrogatepass"),
                             crc)
        else:
            crc = zlib.crc32(arr.dtype.str.encode("ascii"), crc)
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


def write_snapshot(path: str, payload: Dict[str, np.ndarray],
                   compress: bool = True,
                   wal_lsns: Optional[Sequence[int]] = None) -> None:
    """Publish a snapshot: stamp schema version, WAL LSNs, and an
    integrity footer; write to a same-directory temp file; keep the
    previous good snapshot as `<path>.prev`; then atomically replace.
    A crash at ANY point leaves either the previous or the new
    complete snapshot reachable (possibly only as .prev — the loader
    falls back)."""
    from .migration import CURRENT_SCHEMA_VERSION, force
    force(payload, CURRENT_SCHEMA_VERSION)
    if wal_lsns is not None:
        payload[WAL_LSNS_KEY] = np.asarray(list(wal_lsns), np.int64)
    payload[INTEGRITY_KEY] = np.asarray(payload_digest(payload),
                                        np.int64)
    writer = np.savez_compressed if compress else np.savez
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=".npz")
    os.close(fd)
    try:
        writer(tmp, **payload)
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def read_snapshot(path: str) -> Dict[str, np.ndarray]:
    """Load + verify a snapshot. A primary that fails verification
    (bad zip, short file, digest mismatch) falls back — loudly, with
    a metric — to `<path>.prev` instead of crashing or silently
    starting empty; FileNotFoundError propagates only when neither
    file exists (the caller's fresh-start signal)."""
    def _load(p: str) -> Dict[str, np.ndarray]:
        with np.load(p, allow_pickle=True) as z:
            payload = {k: z[k] for k in z.files}
        stored = payload.get(INTEGRITY_KEY)
        if stored is not None and \
                int(np.asarray(stored)) != payload_digest(payload):
            raise SnapshotCorruption(
                f"snapshot {p} failed integrity verification "
                f"(digest mismatch)")
        return payload

    prev = path + ".prev"
    try:
        return _load(path)
    except FileNotFoundError:
        if os.path.exists(prev):
            _logger.error(
                "snapshot %s missing but %s exists (crash between "
                "prev-rotation and publish?) — loading the previous "
                "snapshot", path, prev)
            _M_SNAP_FALLBACK.inc()
            return _load(prev)
        raise
    except Exception as e:
        if os.path.exists(prev):
            _logger.error(
                "snapshot %s failed verification (%s: %s) — falling "
                "back to previous good snapshot %s",
                path, type(e).__name__, e, prev)
            _M_SNAP_FALLBACK.inc()
            try:
                return _load(prev)
            except Exception:
                raise e
        raise


class FlowDatabase:
    """The full database: flows + views + result tables + retention.

    `ttl_seconds` mirrors the reference's `TTL timeInserted + INTERVAL ...`
    (default 12 HOUR, values.yaml:80); eviction runs opportunistically on
    insert (the MergeTree merge equivalent).
    """

    def __init__(self, ttl_seconds: Optional[int] = None) -> None:
        self.flows = Table("flows", FLOW_SCHEMA)
        self.result_tables: Dict[str, Table] = {
            name: Table(name, schema)
            for name, schema in RESULT_TABLE_SCHEMAS}
        self.tadetector = self.result_tables["tadetector"]
        self.recommendations = self.result_tables["recommendations"]
        self.dropdetection = self.result_tables["dropdetection"]
        self.flowpatterns = self.result_tables["flowpatterns"]
        self.spatialnoise = self.result_tables["spatialnoise"]
        self.views: Dict[str, ViewTable] = {
            name: ViewTable(name, spec, self.flows.dicts)
            for name, spec in MATERIALIZED_VIEWS.items()}
        self.ttl_seconds = ttl_seconds
        #: attached WriteAheadLog (None = snapshot-only durability)
        self._wal = None
        #: per-log WAL stamps read from the loaded snapshot (empty =
        #: fresh store or pre-WAL snapshot); attach_wal replays above
        #: these
        self._snapshot_lsns: List[int] = []
        #: (stream, seq, rows) dedup tags recovered from replayed WAL
        #: records — the ingest layer seeds its dedup window from
        #: these so a producer retrying across a crash stays
        #: exactly-once
        self._recovered_acks: List[tuple] = []

    # -- ingest ------------------------------------------------------------

    def insert_flows(self, batch: ColumnarBatch,
                     now: Optional[int] = None,
                     dedup: Optional[tuple] = None) -> int:
        """Insert a flow batch; fan out to materialized views; evict
        TTL. `dedup=(stream, seq)` journals the producer's batch
        identity with the rows (see Table.insert)."""
        # fires once per PHYSICAL store: once per replica in a
        # replicated fan-out, once per resync re-insert
        _fire_fault("store.insert", table="flows")
        adopted = self.flows.insert(batch, dedup=dedup)
        if adopted is None:
            return 0
        # Views consume the adopted (store-coded) batch so their group
        # keys share the store dictionaries. The three aggregations are
        # independent and the native group-sum releases the GIL, so fan
        # out in parallel for large blocks (ClickHouse runs MV pipelines
        # per insert block concurrently too).
        views = list(self.views.values())
        t_mv = time.perf_counter()
        if (len(adopted) >= 16384 and len(views) > 1
                and (os.cpu_count() or 1) > 2):
            # Parallel only where cores exist (TPU hosts); on small
            # boxes the three aggregations just fight over one core.
            list(_view_pool().map(
                lambda v: v.apply_insert_block(adopted), views))
        else:
            for view in views:
                view.apply_insert_block(adopted)
        _M_MV_FANOUT.observe(time.perf_counter() - t_mv)
        _M_INS_ROWS.inc(len(adopted))
        _M_INS_BYTES.inc(sum(a.nbytes
                             for a in adopted.columns.values()))
        if self.ttl_seconds is not None:
            now = int(now if now is not None
                      else np.max(adopted["timeInserted"]))
            self.evict_ttl(now)
        return len(adopted)

    def insert_flow_rows(self, rows, now: Optional[int] = None) -> int:
        return self.insert_flows(
            ColumnarBatch.from_rows(rows, FLOW_SCHEMA, self.flows.dicts),
            now=now)

    @property
    def rows_inserted_total(self) -> int:
        """Cumulative flow rows ever inserted (monotone — deletes do
        not decrease it); the insert-rate substrate."""
        return self.flows.rows_inserted_total

    @property
    def bytes_inserted_total(self) -> int:
        return self.flows.bytes_inserted_total

    # -- write-ahead log ---------------------------------------------------

    def attach_wal(self, wal_dir: str, sync: Optional[str] = None,
                   segment_bytes: Optional[int] = None
                   ) -> Dict[str, object]:
        """Recover from and then journal into a WAL at `wal_dir`:
        replay surviving records above the loaded snapshot's stamp,
        open the append side, install the insert-path hooks, and adopt
        any log content left by a different store topology. Returns
        the replay stats."""
        stamps = self._snapshot_lsns
        stats = self._attach_wal_at(
            wal_dir, stamps[0] if stamps else 0, sync, segment_bytes)
        from .wal import adopt_foreign_wal_dirs
        adopted = adopt_foreign_wal_dirs(self, wal_dir, [wal_dir],
                                         stamps)
        if adopted:
            stats["adoptedRows"] = adopted
        return stats

    def _attach_wal_at(self, wal_dir: str, stamp: int,
                       sync: Optional[str] = None,
                       segment_bytes: Optional[int] = None
                       ) -> Dict[str, object]:
        """Core attach (no foreign-topology scan): replay → open →
        hook. Split out so ShardedFlowDatabase can attach one log per
        shard with per-shard stamps."""
        from .wal import WriteAheadLog, orphan_segments
        if self._wal is not None:
            raise RuntimeError("WAL already attached")
        if stamp <= 0 and (len(self.flows) or any(
                len(t) for t in self.result_tables.values())):
            # Lineage break: this store holds rows from a snapshot
            # that carries NO WAL stamp (saved by a run with the WAL
            # off), yet segments survive here. No LSN can partition
            # those records into in-snapshot vs to-replay — replaying
            # them would duplicate rows — so quarantine them for the
            # operator instead.
            orphaned = orphan_segments(wal_dir)
            if orphaned:
                _logger.error(
                    "WAL %s: %d segments predate an UNSTAMPED "
                    "snapshot (a run without --wal-dir saved over a "
                    "journaled store); renamed to *.orphaned instead "
                    "of replaying them into rows the snapshot may "
                    "already hold", wal_dir, len(orphaned))
        wal = WriteAheadLog(wal_dir, sync=sync,
                            segment_bytes=segment_bytes)
        stats = wal.replay(self._replay_record, above_lsn=stamp)
        wal.open(min_next_lsn=stamp + 1)
        self._wal = wal
        for t in (self.flows, *self.result_tables.values()):
            t._wal_hook = wal.logged_apply
        return stats

    def _replay_record(self, table: str, batch) -> None:
        """Apply one recovered WAL record. Runs before the hooks are
        installed, so nothing re-journals; flows go through the full
        insert path (views, TTL) exactly like live ingest. A dedup tag
        in the record's table field restores the producer's ack to
        `_recovered_acks` — rows and idempotency recover together."""
        from .wal import split_dedup_tag
        table, tag = split_dedup_tag(table)
        if tag is not None:
            self._recovered_acks.append((tag[0], tag[1], len(batch),
                                         tag[2]))
        if table == "flows":
            self.insert_flows(batch)
        elif table in self.result_tables:
            self.result_tables[table].insert(batch)
        else:
            _logger.error("WAL record for unknown table %r dropped "
                          "(%d rows)", table, len(batch))

    def note_recovered_ack(self, stream: str, seq: int, rows: int,
                           total: Optional[int] = None) -> None:
        """Record an acknowledged (stream, seq) recovered outside the
        normal replay path (foreign-topology WAL adoption)."""
        self._recovered_acks.append((stream, int(seq), int(rows),
                                     total))

    def recovered_acks(self) -> List[tuple]:
        """(stream, seq, recovered_rows, logical_total) tags restored
        from WAL replay — the ingest layer's dedup-window seed after a
        crash. recovered_rows < logical_total means part of the batch
        was not durable at the crash (possible for sharded stores
        under interval sync — slices fsync independently); the seeder
        logs that loudly."""
        return list(self._recovered_acks)

    def wal_lag(self) -> int:
        """Records appended but not yet fsynced (0 without a WAL) —
        the admission plane's syncedLsn-lag pressure signal."""
        wal = self._wal
        return 0 if wal is None else wal.lag_records

    @contextlib.contextmanager
    def wal_suspended(self):
        """Temporarily disable journaling (replica resync re-inserts
        state that is already durable on the peer — re-logging it
        would corrupt the LSN sequence)."""
        tables = (self.flows, *self.result_tables.values())
        saved = [t._wal_hook for t in tables]
        for t in tables:
            t._wal_hook = None
        try:
            yield
        finally:
            for t, hook in zip(tables, saved):
                t._wal_hook = hook

    def wal_stats(self) -> Optional[Dict[str, object]]:
        wal = self._wal
        return None if wal is None else wal.stats()

    def wal_position(self) -> Optional[int]:
        """Last appended LSN (None when no WAL attached)."""
        wal = self._wal
        return None if wal is None else wal.last_lsn

    def wal_reposition(self, position) -> None:
        """Jump the log forward to a resync peer's position."""
        wal = self._wal
        if wal is not None and position is not None:
            if isinstance(position, (list, tuple)):
                position = position[0] if position else 0
            wal.reposition(int(position))

    def wal_sync(self) -> None:
        wal = self._wal
        if wal is not None:
            wal.sync()

    def wal_gc(self, stamp) -> int:
        """GC segments wholly covered by a snapshot stamped at
        `stamp` (the value save() returned)."""
        wal = self._wal
        if wal is None or stamp is None:
            return 0
        if isinstance(stamp, (list, tuple)):
            stamp = stamp[0] if stamp else 0
        return wal.gc_below(int(stamp))

    def close_wal(self) -> None:
        """Final fsync + detach (part of graceful shutdown)."""
        wal = self._wal
        if wal is None:
            return
        for t in (self.flows, *self.result_tables.values()):
            t._wal_hook = None
        self._wal = None
        wal.close()

    # -- retention ---------------------------------------------------------

    def evict_ttl(self, now: int) -> int:
        if self.ttl_seconds is None:
            return 0
        boundary = now - self.ttl_seconds
        # Fast path: nothing evictable — min() over parts is O(parts),
        # not a full-table concat, so steady ingest stays O(batch).
        oldest = self.flows.min_value("timeInserted")
        if oldest is None or oldest >= boundary:
            return 0
        deleted = self.delete_flows_older_than(boundary)
        if deleted:
            _M_DEL_ROWS.labels(reason="ttl").inc(deleted)
        return deleted

    def delete_flows_older_than(self, boundary: int) -> int:
        """timeInserted < boundary, applied to flows and every view
        (monitor main.go:284-293 deletes from table + MVs)."""
        deleted = self.flows.delete_older_than(boundary)
        for view in self.views.values():
            view.delete_older_than(boundary)
        return deleted

    def monitor(self, capacity_bytes: int, **kw) -> RetentionMonitor:
        return RetentionMonitor(self, capacity_bytes, **kw)

    # -- persistence -------------------------------------------------------

    def save(self, path: str, tables: Optional[Sequence[str]] = None,
             compress: bool = True) -> Optional[int]:
        """Persist tables to one .npz (columns + dictionary tables),
        stamped with the current schema version (store/migration.py).

        `tables` restricts the snapshot (e.g. result tables only for a
        job's write-back); `compress=False` trades disk for CPU —
        right for short-lived job snapshots, wrong for durable
        checkpoints. The write is ATOMIC (temp file + rename) and
        keeps the previous snapshot as `<path>.prev`: a crash mid-save
        never tears an existing snapshot, and a later-corrupted
        primary still has a verified fallback.

        With a WAL attached, a FULL snapshot quiesces appends while it
        stamps the log position and scans the tables (so the stamp is
        exact), and returns that stamp — the caller passes it to
        `wal_gc()` once the snapshot is known durable. Partial
        (tables=...) snapshots stamp nothing: they are not recovery
        points."""
        wal = self._wal
        if wal is not None and tables is None:
            with wal.quiesce():
                stamp = wal.last_lsn
                payload = self._snapshot_payload(tables)
        else:
            stamp = None
            payload = self._snapshot_payload(tables)
        write_snapshot(path, payload, compress=compress,
                       wal_lsns=[stamp] if stamp is not None else None)
        return stamp

    def _snapshot_payload(self, tables: Optional[Sequence[str]] = None
                          ) -> Dict[str, np.ndarray]:
        payload: Dict[str, np.ndarray] = {}
        for table in (self.flows, *self.result_tables.values()):
            if tables is not None and table.name not in tables:
                continue
            data = table.scan()
            for col in table.schema:
                payload[f"{table.name}/{col.name}"] = data[col.name]
            for name, d in table.dicts.items():
                payload[f"{table.name}/__dict__/{name}"] = np.asarray(
                    d._strings, dtype=object)
        return payload

    @classmethod
    def load(cls, path: str,
             ttl_seconds: Optional[int] = None,
             build_views: bool = True) -> "FlowDatabase":
        """Load a persisted database, migrating older schema versions
        up to current first (the reference's schema-management init
        container runs before the server the same way).

        build_views=False skips materialized-view fan-out — for callers
        that immediately re-insert the rows elsewhere (sharded load)
        and would otherwise pay the O(rows) view build twice."""
        from .migration import migrate
        db = cls(ttl_seconds=None)
        payload = read_snapshot(path)
        if WAL_LSNS_KEY in payload:
            db._snapshot_lsns = [
                int(v) for v in np.asarray(payload[WAL_LSNS_KEY])]
        migrate(payload)
        for table in (db.flows, *db.result_tables.values()):
            cols: Dict[str, np.ndarray] = {}
            for name, d in table.dicts.items():
                key = f"{table.name}/__dict__/{name}"
                if key in payload:
                    for s in payload[key]:
                        d.encode_one(str(s))
            for col in table.schema:
                key = f"{table.name}/{col.name}"
                if key in payload:
                    cols[col.name] = payload[key]
            if cols and len(next(iter(cols.values()))):
                batch = ColumnarBatch(
                    {c.name: cols.get(c.name, np.zeros(
                        len(next(iter(cols.values()))), c.host_dtype))
                     for c in table.schema}, table.dicts)
                if table is db.flows and build_views:
                    db.insert_flows(batch)
                else:
                    table.insert(batch)
        db.ttl_seconds = ttl_seconds
        return db
