"""Sharded flow database — the Distributed-table tier.

Re-provides the reference's ClickHouse scale-out topology
(build/charts/theia/provisioning/datasources/create_table.sh:387-403:
`Distributed('{cluster}', default, <table>_local, rand())` over
`shards` from values.yaml:121-126): every logical table is backed by N
independent shards; inserts are routed row-wise by a uniform random
assignment (the `rand()` sharding key), reads fan out to every shard
and merge. Materialized views aggregate per shard on the insert path —
exactly like ClickHouse, where the MV populates <view>_local on the
shard the row landed on — and the distributed view read re-collapses
identical group keys across shards at query time.

Multicluster works the same way it does in the reference
(test/e2e_mc/multicluster_test.go:37-80): flow sources in different
clusters stamp their own `clusterUUID`, all rows land in one logical
store, and every consumer filters or groups by that column.

Each shard owns its dictionaries (shards are independent processes in a
real deployment); cross-shard merges re-encode through
ColumnarBatch.concat's dictionary reconciliation.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..schema import ColumnarBatch
from ..utils.logging import get_logger
from ..utils.pool import get_pool
from .flow_store import FlowDatabase, RetentionMonitor, write_snapshot
from .views import MATERIALIZED_VIEWS, group_sum, materialize_view_batch
from ..analysis.lockdep import named_lock

_logger = get_logger("sharded")


def _shard_pool() -> concurrent.futures.ThreadPoolExecutor:
    """Shared pool for parallel per-shard inserts (the native MV
    group-sum releases the GIL, so shards genuinely overlap on
    multi-core hosts)."""
    return get_pool("shard-insert", min(8, os.cpu_count() or 1))


class DistributedTable:
    """Read/write facade over one table across all shards."""

    def __init__(self, name: str, tables: Sequence, rng) -> None:
        self.name = name
        self.tables = list(tables)
        self._rng = rng
        self._lock = named_lock("store.sharded")

    @property
    def schema(self):
        return self.tables[0].schema

    def __len__(self) -> int:
        return sum(len(t) for t in self.tables)

    @property
    def nbytes(self) -> int:
        return sum(t.nbytes for t in self.tables)

    @property
    def generation(self) -> int:
        """Sum of shard mutation counters (monotonic: shard counters
        only grow)."""
        return sum(t.generation for t in self.tables)

    @property
    def rows_inserted_total(self) -> int:
        return sum(t.rows_inserted_total for t in self.tables)

    @property
    def bytes_inserted_total(self) -> int:
        return sum(t.bytes_inserted_total for t in self.tables)

    def _assign(self, n: int) -> np.ndarray:
        with self._lock:   # rand() routing; rng isn't thread-safe
            return self._rng.integers(0, len(self.tables), size=n)

    def insert(self, batch: ColumnarBatch) -> int:
        if len(batch) == 0:
            return 0
        assign = self._assign(len(batch))
        for i, table in enumerate(self.tables):
            part = batch.filter(assign == i)
            if len(part):
                table.insert(part)
        return len(batch)

    def insert_rows(self, rows) -> int:
        if not rows:
            return 0
        assign = self._assign(len(rows))
        for i, table in enumerate(self.tables):
            table.insert_rows([r for r, a in zip(rows, assign)
                               if a == i])
        return len(rows)

    def scan(self) -> ColumnarBatch:
        parts = [t.scan() for t in self.tables]
        return ColumnarBatch.concat(parts)

    def select(self, *a, **kw) -> ColumnarBatch:
        return ColumnarBatch.concat(
            [t.select(*a, **kw) for t in self.tables])

    def delete_where(self, mask: np.ndarray) -> int:
        """Delete by a mask over the scan() row order (shard order).

        Holds every shard's lock for the whole operation (in shard
        order, so no lock-order inversion) — lengths cannot shift
        between the split and the apply, preserving the single-node
        all-or-nothing contract against concurrent inserts."""
        with contextlib.ExitStack() as stack:
            for t in self.tables:
                stack.enter_context(t._lock)
            lengths = [t._row_count_locked() for t in self.tables]
            if len(mask) != sum(lengths):
                raise ValueError(
                    f"mask length {len(mask)} != table length "
                    f"{sum(lengths)}")
            deleted, off = 0, 0
            for t, n in zip(self.tables, lengths):
                part = mask[off:off + n]
                off += n
                deleted += t._delete_where_locked(part)
            return deleted

    def delete_ids(self, ids, column: str = "id",
                   invert: bool = False) -> int:
        return sum(t.delete_ids(ids, column=column, invert=invert)
                   for t in self.tables)

    def delete_older_than(self, boundary: int,
                          column: str = "timeInserted") -> int:
        return sum(t.delete_older_than(boundary, column)
                   for t in self.tables)

    def min_value(self, column: str = "timeInserted") -> Optional[int]:
        mins = [m for m in (t.min_value(column) for t in self.tables)
                if m is not None]
        return min(mins) if mins else None

    def retention_boundary(self, delete_n: int) -> Optional[int]:
        """Cluster-wide boundary from every shard's part/batch
        metadata (the reference monitor runs its boundary query over
        the Distributed table the same way)."""
        from .flow_store import boundary_from_meta
        metas = []
        for t in self.tables:
            rm = getattr(t, "_retention_meta", None)
            if not callable(rm):
                return None
            metas.extend(rm())
        return boundary_from_meta(metas, delete_n)

    def truncate(self) -> None:
        for t in self.tables:
            t.truncate()


class DistributedView:
    """Merged read view over one materialized view across shards."""

    def __init__(self, name: str, views: Sequence) -> None:
        self.name = name
        self.views = list(views)
        self.spec = views[0].spec

    def __len__(self) -> int:
        return len(self.scan())

    def scan(self) -> ColumnarBatch:
        """Concat shard views, then collapse identical group keys (the
        SummingMergeTree merge across shards happens at read time for
        Distributed views)."""
        merged = ColumnarBatch.concat([v.scan() for v in self.views])
        if len(merged) == 0:
            return merged
        keys = np.stack([np.asarray(merged[c], np.int64)
                         for c in self.spec.key_columns], axis=1)
        values = np.stack([np.asarray(merged[c], np.int64)
                           for c in self.spec.sum_columns], axis=1)
        gk, gv = group_sum(keys, values)
        return materialize_view_batch(self.spec, gk, gv, merged.dicts)

    def delete_older_than(self, boundary: int) -> int:
        return sum(v.delete_older_than(boundary) for v in self.views)

    def truncate(self) -> None:
        for v in self.views:
            v.truncate()


class ShardedFlowDatabase:
    """N-shard logical database with the FlowDatabase consumer surface.

    Analytics jobs, the manager, dashboards, and stats all run
    unmodified against this class — the same way the reference's
    consumers query the Distributed tables and never the `_local` ones.
    """

    def __init__(self, n_shards: int = 2,
                 ttl_seconds: Optional[int] = None,
                 seed: int = 0,
                 engine: Optional[str] = None,
                 parts_dir: Optional[str] = None,
                 parts_config: Optional[Dict[str, object]] = None
                 ) -> None:
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        if parts_dir is None:
            # resolve the env HERE so every shard gets its own
            # subdirectory — per-shard resolution would make all
            # shards share one part directory (and one GC)
            parts_dir = os.environ.get("THEIA_STORE_COLD_DIR") or None
        self.shards: List[FlowDatabase] = [
            FlowDatabase(
                ttl_seconds=ttl_seconds, engine=engine,
                parts_dir=(os.path.join(parts_dir, f"shard-{i:03d}")
                           if parts_dir else ""),
                parts_config=parts_config)
            for i in range(n_shards)]
        # One Generator per table: each DistributedTable serializes its
        # own rand() stream under its own lock; sharing one Generator
        # across tables would race (Generators are not thread-safe).
        from .flow_store import RESULT_TABLE_SCHEMAS
        result_names = [name for name, _ in RESULT_TABLE_SCHEMAS]
        seqs = np.random.SeedSequence(seed).spawn(1 + len(result_names))
        self.ttl_seconds = ttl_seconds
        self.flows = DistributedTable(
            "flows", [s.flows for s in self.shards],
            np.random.default_rng(seqs[0]))
        self.result_tables: Dict[str, DistributedTable] = {
            name: DistributedTable(
                name, [s.result_tables[name] for s in self.shards],
                np.random.default_rng(seqs[1 + i]))
            for i, name in enumerate(result_names)}
        self.tadetector = self.result_tables["tadetector"]
        self.recommendations = self.result_tables["recommendations"]
        self.dropdetection = self.result_tables["dropdetection"]
        self.flowpatterns = self.result_tables["flowpatterns"]
        self.spatialnoise = self.result_tables["spatialnoise"]
        self.views: Dict[str, DistributedView] = {
            name: DistributedView(name,
                                  [s.views[name] for s in self.shards])
            for name in MATERIALIZED_VIEWS}
        #: per-shard WAL stamps from the loaded snapshot (see
        #: FlowDatabase._snapshot_lsns)
        self._snapshot_lsns: List[int] = []
        #: dedup tags adopted from foreign-topology WALs (per-shard
        #: tags live in the shards; recovered_acks() merges both)
        self._recovered_acks: List[tuple] = []

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    @property
    def rows_inserted_total(self) -> int:
        """Cumulative flow rows inserted across every shard (monotone;
        the cluster-wide insert-rate substrate)."""
        return self.flows.rows_inserted_total

    @property
    def bytes_inserted_total(self) -> int:
        return self.flows.bytes_inserted_total

    # -- ingest ----------------------------------------------------------

    def insert_flows(self, batch: ColumnarBatch,
                     now: Optional[int] = None,
                     dedup: Optional[tuple] = None,
                     wire: Optional[memoryview] = None) -> int:
        """Route rows to shards (rand()); each shard maintains its own
        views/TTL on its slice, like a ClickHouse shard does. A
        `dedup` tag rides into every shard's WAL record (each slice
        journals under the same (stream, seq), so recovery re-sums
        the full batch's ack). A whole-batch `wire` section is
        accepted but NOT forwarded: slices journal independently per
        shard, so each shard re-encodes its own rows (the verbatim
        fast path is the unsharded engine's)."""
        if len(batch) == 0:
            return 0
        assign = self.flows._assign(len(batch))
        parts = [(shard, batch.filter(assign == i))
                 for i, shard in enumerate(self.shards)]
        parts = [(s, p) for s, p in parts if len(p)]
        # Shards are fully independent stores (own locks, own views,
        # own dictionaries) — insert them concurrently when cores
        # exist; a ClickHouse Distributed insert fans out to shard
        # replicas in parallel the same way.
        if len(parts) > 1 and (os.cpu_count() or 1) > 2:
            return sum(_shard_pool().map(
                lambda sp: sp[0].insert_flows(sp[1], now=now,
                                              dedup=dedup), parts))
        return sum(s.insert_flows(p, now=now, dedup=dedup)
                   for s, p in parts)

    def insert_flow_rows(self, rows, now: Optional[int] = None) -> int:
        from ..schema import FLOW_SCHEMA
        if not rows:
            return 0
        return self.insert_flows(
            ColumnarBatch.from_rows(rows, FLOW_SCHEMA), now=now)

    # -- write-ahead log --------------------------------------------------

    def attach_wal(self, wal_dir: str, sync: Optional[str] = None,
                   segment_bytes: Optional[int] = None
                   ) -> Dict[str, object]:
        """One WAL per shard under `<wal_dir>/shard-NNN`, recovered in
        PARALLEL (shards are fully independent stores, so their
        replays never interact — determinism is per-shard log order).
        Stray logs from a different shard count (topology change
        across restarts) are adopted through the logical insert path
        so acknowledged rows are never orphaned."""
        stamps = self._snapshot_lsns
        dirs = [os.path.join(wal_dir, f"shard-{i:03d}")
                for i in range(self.n_shards)]

        def _attach(i: int) -> Dict[str, object]:
            return self.shards[i]._attach_wal_at(
                dirs[i], stamps[i] if i < len(stamps) else 0,
                sync, segment_bytes)

        if self.n_shards > 1 and (os.cpu_count() or 1) > 1:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(8, self.n_shards),
                    thread_name_prefix="theia-wal-replay") as pool:
                per_shard = list(pool.map(_attach,
                                          range(self.n_shards)))
        else:
            per_shard = [_attach(i) for i in range(self.n_shards)]
        from .wal import adopt_foreign_wal_dirs
        adopted = adopt_foreign_wal_dirs(self, wal_dir, dirs, stamps)
        stats: Dict[str, object] = {
            "recoveredRows": sum(int(s["recoveredRows"])
                                 for s in per_shard),
            "recoveredRecords": sum(int(s["recoveredRecords"])
                                    for s in per_shard),
            "droppedRecords": sum(int(s["droppedRecords"])
                                  for s in per_shard),
            "droppedBytes": sum(int(s["droppedBytes"])
                                for s in per_shard),
            "tornTail": any(s["tornTail"] for s in per_shard),
            "gapped": any(s["gapped"] for s in per_shard),
            "lastLsn": [int(s["lastLsn"]) for s in per_shard],
            "perShard": per_shard,
        }
        if adopted:
            stats["adoptedRows"] = adopted
        return stats

    @contextlib.contextmanager
    def wal_suspended(self):
        with contextlib.ExitStack() as stack:
            for s in self.shards:
                stack.enter_context(s.wal_suspended())
            yield

    def wal_stats(self) -> Optional[Dict[str, object]]:
        per = [s.wal_stats() for s in self.shards]
        if not any(per):
            return None
        live = [p for p in per if p]
        return {
            "shards": len(per),
            "segments": sum(p["segments"] for p in live),
            "bytes": sum(p["bytes"] for p in live),
            "lagRecords": sum(p["lagRecords"] for p in live),
            "lagBytes": sum(p["lagBytes"] for p in live),
            "lastLsn": [p["lastLsn"] if p else None for p in per],
            "syncedLsn": [p["syncedLsn"] if p else None for p in per],
            "policy": live[0]["policy"],
        }

    def wal_lag(self) -> int:
        """Unsynced-record lag summed over shards (the admission
        plane's cheap per-request pressure signal)."""
        return sum(s.wal_lag() for s in self.shards)

    def note_recovered_ack(self, stream: str, seq: int, rows: int,
                           total: Optional[int] = None) -> None:
        self._recovered_acks.append((stream, int(seq), int(rows),
                                     total))

    def recovered_acks(self) -> List[tuple]:
        """Dedup tags recovered across every shard's WAL replay. A
        batch split N ways journals its (stream, seq, logical total)
        in N shard logs, each with its slice's row count — the merge
        re-sums the slices into one logical ack; a sum short of the
        total means some slice was not durable at the crash."""
        merged: Dict[tuple, List] = {}
        for s in self.shards:
            for stream, seq, rows, total in s.recovered_acks():
                ent = merged.setdefault((stream, seq), [0, None])
                ent[0] += rows
                if total is not None:
                    ent[1] = max(ent[1] or 0, total)
        out = [(k[0], k[1], v[0], v[1]) for k, v in merged.items()]
        out.extend(self._recovered_acks)
        return out

    def wal_position(self) -> Optional[List[int]]:
        pos = [s.wal_position() for s in self.shards]
        if all(p is None for p in pos):
            return None
        return [0 if p is None else p for p in pos]

    def wal_reposition(self, position) -> None:
        if position is None:
            return
        if not isinstance(position, (list, tuple)):
            position = [position] * self.n_shards
        for s, p in zip(self.shards, position):
            s.wal_reposition(p)

    def wal_sync(self) -> None:
        for s in self.shards:
            s.wal_sync()

    def wal_gc(self, stamp) -> int:
        if stamp is None:
            return 0
        if not isinstance(stamp, (list, tuple)):
            stamp = [stamp] * self.n_shards
        return sum(s.wal_gc(p) for s, p in zip(self.shards, stamp))

    def close_wal(self) -> None:
        for s in self.shards:
            s.close_wal()

    # -- retention --------------------------------------------------------

    def evict_ttl(self, now: int) -> int:
        return sum(s.evict_ttl(now) for s in self.shards)

    def delete_flows_older_than(self, boundary: int) -> int:
        return sum(s.delete_flows_older_than(boundary)
                   for s in self.shards)

    def monitor(self, capacity_bytes: int, **kw) -> RetentionMonitor:
        # RetentionMonitor only touches .flows.{nbytes,scan} and
        # .delete_flows_older_than — all provided here, so monitoring a
        # sharded database trims every shard at one global boundary
        # (the reference monitor runs the boundary query cluster-wide).
        return RetentionMonitor(self, capacity_bytes, **kw)

    def demote_cold(self, target_bytes: int) -> int:
        """Tiered retention across shards: each shard demotes toward
        an equal split of the resident-byte target."""
        per = max(0, int(target_bytes) // self.n_shards)
        return sum(s.demote_cold(per) for s in self.shards)

    def maintenance_tick(self) -> int:
        return sum(s.maintenance_tick() for s in self.shards)

    def store_stats(self) -> Dict[str, object]:
        """Aggregated engine/tier summary across shards."""
        per = [s.store_stats() for s in self.shards]
        doc: Dict[str, object] = {
            "engine": per[0]["engine"],
            "shards": len(per),
            "flowRows": sum(int(p["flowRows"]) for p in per),
            "flowBytes": sum(int(p["flowBytes"]) for p in per),
        }
        if any("parts" in p for p in per):
            keys = ("count", "hot", "cold", "hotBytes", "coldBytes",
                    "rows", "memtableRows", "memtableBytes", "sealed",
                    "merges", "demoted")
            agg = {k: sum(int(p["parts"][k]) for p in per
                          if "parts" in p) for k in keys}
            doc["parts"] = agg
        return doc

    # -- persistence ------------------------------------------------------

    def save(self, path: str, tables=None, compress: bool = True
             ) -> Optional[List[int]]:
        """Persist the *logical* contents as one single-node snapshot
        (FlowDatabase format); loading re-shards. Mirrors backing up a
        cluster through the Distributed table.

        With WALs attached, a full snapshot quiesces EVERY shard's log
        while it stamps the per-shard LSN vector and scans, so each
        stamp exactly partitions that shard's records into in-snapshot
        vs to-replay; returns the vector for wal_gc()."""
        wals = [s._wal for s in self.shards]
        stamps: Optional[List[int]] = None
        with contextlib.ExitStack() as stack:
            if tables is None and any(w is not None for w in wals):
                for w in wals:
                    if w is not None:
                        stack.enter_context(w.quiesce())
                stamps = [0 if w is None else w.last_lsn
                          for w in wals]
            datas = {"flows": self.flows.scan()}
            for name, src in self.result_tables.items():
                datas[name] = src.scan()
        # merge + serialize OUTSIDE the quiesce window — only the
        # scans need the consistent point. The merged carrier is
        # explicitly FLAT: a parts-engine carrier would write
        # transient part files beside the live shards' for no benefit
        # (the sharded snapshot is a wholesale logical backup).
        merged = FlowDatabase(engine="flat")
        if len(datas["flows"]):
            merged.flows.insert(datas["flows"])
        for name in self.result_tables:
            if len(datas[name]):
                merged.result_tables[name].insert(datas[name])
        write_snapshot(path, merged._snapshot_payload(tables),
                       compress=compress, wal_lsns=stamps)
        return stamps

    @classmethod
    def load(cls, path: str, n_shards: int = 2,
             ttl_seconds: Optional[int] = None,
             seed: int = 0,
             engine: Optional[str] = None,
             parts_dir: Optional[str] = None,
             parts_config: Optional[Dict[str, object]] = None
             ) -> "ShardedFlowDatabase":
        # The temp carrier is flat: a parts-engine carrier would seal
        # transient part files it immediately discards (a parts-aware
        # snapshot still loads — the cross-engine donor path decodes
        # it).
        single = FlowDatabase.load(path, build_views=False,
                                   engine="flat")
        # Defer TTL until every row is back in, exactly like
        # FlowDatabase.load (flow_store.py) — otherwise the re-insert
        # itself evicts persisted rows, at a routing-dependent boundary
        # per shard.
        db = cls(n_shards=n_shards, ttl_seconds=None, seed=seed,
                 engine=engine, parts_dir=parts_dir,
                 parts_config=parts_config)
        db._snapshot_lsns = list(single._snapshot_lsns)
        flows = single.flows.scan()
        if len(flows):
            db.insert_flows(flows)
        for name, src in single.result_tables.items():
            data = src.scan()
            if len(data):
                db.result_tables[name].insert(data)
        db.ttl_seconds = ttl_seconds
        for shard in db.shards:
            shard.ttl_seconds = ttl_seconds
        return db
