"""Incremental durability: periodic atomic snapshots of the store.

Plays the durability role ClickHouse replication plays in the
reference (Replicated*MergeTree + ZooKeeper, Helm
build/charts/theia/values.yaml:121-183): without it, the store's
contents exist only in memory and a crash loses everything since
startup. A Checkpointer thread snapshots the database to the
persistence path every `interval` seconds — atomically (write to a
temp file in the same directory, then os.replace), so a crash at ANY
moment leaves either the previous or the new complete snapshot, never
a torn file. Loss after kill -9 is bounded by the checkpoint interval.

The snapshot runs OFF the insert path: `FlowDatabase.save` scans each
table under its own lock briefly (zero-copy concat of the append log),
so ingest keeps flowing while the checkpoint compresses and writes.
A cheap fingerprint (row counts + byte sizes) skips writes when
nothing changed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple

from ..utils import get_logger
from ..utils.faults import fire as _fire_fault

logger = get_logger("checkpoint")


class Checkpointer:
    """Background periodic snapshot writer for a FlowDatabase (or
    ShardedFlowDatabase — both expose save()).

    `assume_current=True` seeds the change detector with the
    database's current state — pass it when the database was just
    loaded from `path`, so an idle restart doesn't rewrite a
    multi-GB identical snapshot on the first tick."""

    def __init__(self, db, path: str, interval: float = 60.0,
                 compress: bool = True,
                 assume_current: bool = False) -> None:
        self.db = db
        self.path = path
        self.interval = interval
        self.compress = compress
        self.checkpoints_written = 0
        self.last_checkpoint_time: float = 0.0
        self.last_error: Optional[str] = None
        self._last_fingerprint: Optional[Tuple] = (
            self._fingerprint() if assume_current else None)
        #: WAL stamp of the PREVIOUS successful snapshot — GC lags one
        #: checkpoint so the `.prev` fallback snapshot always still
        #: has the log records above ITS stamp (collecting up to the
        #: current stamp would orphan .prev the moment the primary
        #: corrupts)
        self._gc_stamp = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._gc_stale_tmp()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-checkpointer")
        self._thread.start()

    def _gc_stale_tmp(self) -> None:
        """Remove orphaned atomic-write temp files beside the snapshot
        (a kill -9 mid-write leaves a near-snapshot-size .tmp-*; a
        crash-looping manager would otherwise leak one per cycle until
        the volume fills). Age-gated so a concurrent writer's live
        temp file is never collected, and scoped to SNAPSHOT temps
        (.tmp-*.npz) only: THEIA_WAL_DIR may share this directory, and
        the WAL's own files must never be collected by the snapshot
        janitor."""
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        now = time.time()
        try:
            names = os.listdir(d)
        except OSError:
            return
        for name in names:
            if not (name.startswith(".tmp-") and name.endswith(".npz")):
                continue
            p = os.path.join(d, name)
            try:
                if now - os.path.getmtime(p) > 60:
                    os.unlink(p)
                    logger.info("removed stale snapshot temp %s", p)
            except OSError:
                pass

    def stop(self) -> bool:
        """Returns False if the checkpoint thread failed to stop (a
        wedged write) — the caller's final save could then race a
        late os.replace; both writes are atomic, so the file is never
        torn, but the caller should log the condition."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=30)
            if self._thread.is_alive():
                logger.error("checkpoint thread did not stop in 30s")
                return False
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.checkpoint()
            except Exception as e:   # keep ticking after a bad write
                self.last_error = f"{type(e).__name__}: {e}"
                logger.error("checkpoint failed: %s", self.last_error)

    # -- one checkpoint ---------------------------------------------------

    def _fingerprint(self) -> Tuple:
        """Change detector: per-table monotonic mutation counters
        (Table.generation counts inserts AND deletes, so same-size
        churn — TTL evicts N while ingest adds N — still registers;
        row counts alone would not). Built from the result-table
        REGISTRY, not a hardcoded table list: a result table added to
        the store is covered automatically, so a completed job's rows
        can never be invisible to the change detector (and silently
        lost to a crash)."""
        return (self.db.flows.generation,
                *(self.db.result_tables[name].generation
                  for name in sorted(self.db.result_tables)))

    def checkpoint(self) -> bool:
        """Write one snapshot (FlowDatabase.save is itself atomic:
        temp file + rename); returns False when skipped (unchanged
        since the last write). A successful stamped snapshot then
        garbage-collects WAL segments wholly below the PREVIOUS
        snapshot's stamp — covered by two generations, so recovery
        keeps working from `<path>.prev` if the primary is later
        found corrupt — bounding disk use to ~two checkpoint
        intervals of log."""
        fp = self._fingerprint()
        if fp == self._last_fingerprint:
            return False
        _fire_fault("checkpoint.save", path=self.path)
        stamp = self.db.save(self.path, compress=self.compress)
        self._last_fingerprint = fp
        self.checkpoints_written += 1
        self.last_checkpoint_time = time.time()
        gc = getattr(self.db, "wal_gc", None)
        if self._gc_stamp is not None and callable(gc):
            try:
                gc(self._gc_stamp)
            except Exception as e:   # GC failure must not fail the tick
                logger.error("WAL gc after checkpoint failed: %s", e)
        self._gc_stamp = stamp
        logger.v(1).info("checkpoint %d written to %s",
                         self.checkpoints_written, self.path)
        return True
