"""Replicated flow database — the high-availability tier.

Re-provides the role of the reference's Replicated*MergeTree +
ZooKeeper topology (build/charts/theia/values.yaml:121-183: `replicas`
per shard, ZooKeeper coordinating replica queues): R live copies of
the logical store, writes fanned to every live replica, reads served
from the lowest-index live one, immediate failover when a replica is
marked down, and catch-up-by-copy when one comes back (the in-memory
analogue of a ClickHouse replica replaying its queue from a peer).

Composition order matters: replication wraps the WHOLE logical store
(optionally a ShardedFlowDatabase), so `--shards N --replicas R` is N
shards × R replicas — the same grid the reference's operator CRD
renders.

Consumer surface: identical to FlowDatabase. Read paths delegate to
the active replica via __getattr__; write paths (insert, TTL,
retention, result-table mutation) are explicit fan-out overrides.
Result tables are wrapped so analytics jobs and the controller's GC
mutate every live replica; their deletes are value-based
(Table.delete_ids), because replicas route rows to different physical
orders and a positional mask would corrupt them.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .flow_store import FlowDatabase

#: result-table write/read methods the replica proxy forwards
_TABLE_WRITES = ("insert", "insert_rows", "delete_ids",
                 "delete_older_than", "truncate")


class AllReplicasDownError(Exception):
    """Every replica is marked down — no copy can serve."""


def _suspend_ttl(replica):
    """Disable TTL on a replica (and its shards, if sharded) for a
    bulk re-insert; returns the saved value for _restore_ttl."""
    saved = replica.ttl_seconds
    replica.ttl_seconds = None
    for shard in getattr(replica, "shards", ()):
        shard.ttl_seconds = None
    return saved


def _restore_ttl(replica, saved) -> None:
    replica.ttl_seconds = saved
    for shard in getattr(replica, "shards", ()):
        shard.ttl_seconds = saved


class _ReplicatedTable:
    """One result table across replicas: reads from the active copy,
    writes to every live copy."""

    def __init__(self, db: "ReplicatedFlowDatabase", name: str) -> None:
        self._db = db
        self._table_name = name

    def _active(self):
        return self._db.active.result_tables[self._table_name]

    # -- reads ------------------------------------------------------------

    @property
    def name(self):
        return self._table_name

    @property
    def schema(self):
        return self._active().schema

    @property
    def dicts(self):
        return self._active().dicts

    @property
    def nbytes(self):
        return self._active().nbytes

    @property
    def generation(self):
        return self._active().generation

    def __len__(self):
        return len(self._active())

    def scan(self):
        return self._active().scan()

    def select(self, *a, **kw):
        return self._active().select(*a, **kw)

    def min_value(self, *a, **kw):
        return self._active().min_value(*a, **kw)

    # -- writes (fan-out) --------------------------------------------------

    def delete_where(self, mask):
        raise NotImplementedError(
            "positional delete_where is unsafe across replicas (each "
            "copy holds the same logical rows in a different physical "
            "order); use the value-based delete_ids")

    def __getattr__(self, name):
        if name in _TABLE_WRITES:
            def fan(*a, **kw):
                out = 0
                with self._db._write_lock:
                    for r in self._db.live():
                        out = getattr(
                            r.result_tables[self._table_name],
                            name)(*a, **kw)
                return out
            return fan
        return getattr(self._active(), name)


class ReplicatedFlowDatabase:
    """R live copies of the logical store behind one FlowDatabase
    surface."""

    def __init__(self, replicas: int = 2,
                 factory: Optional[Callable[[], object]] = None,
                 ttl_seconds: Optional[int] = None) -> None:
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        make = factory or (
            lambda: FlowDatabase(ttl_seconds=ttl_seconds))
        self.replicas: List = [make() for _ in range(replicas)]
        self._down: set = set()
        self._lock = threading.Lock()
        # Serializes fan-out writes against each other (deterministic
        # per-replica apply order) and — critically — against resync:
        # without it a write landing between the resync copy and the
        # up-mark would be missing from the recovered replica forever.
        self._write_lock = threading.Lock()
        self.result_tables: Dict[str, _ReplicatedTable] = {
            name: _ReplicatedTable(self, name)
            for name in self.replicas[0].result_tables}
        for name, proxy in self.result_tables.items():
            setattr(self, name, proxy)

    # -- replica membership ------------------------------------------------

    def live(self) -> List:
        with self._lock:
            down = set(self._down)
        out = [r for i, r in enumerate(self.replicas) if i not in down]
        if not out:
            raise AllReplicasDownError(
                f"all {len(self.replicas)} replicas are down")
        return out

    @property
    def active(self):
        """Lowest-index live replica — the read servant."""
        return self.live()[0]

    def set_replica_down(self, index: int) -> None:
        with self._lock:
            self._down.add(index)

    def set_replica_up(self, index: int, resync: bool = True) -> None:
        """Bring a replica back; by default it catches up by copying
        the active peer's state wholesale (the replica-queue replay
        analogue — correct, if not incremental, at in-memory scale).
        Holds the write lock across copy + up-mark, so no write can
        slip between them and be lost on the recovered replica."""
        with self._write_lock:
            if resync:
                peer = self.active
                if self.replicas[index] is not peer:
                    self._resync(self.replicas[index], peer)
            with self._lock:
                self._down.discard(index)

    @staticmethod
    def _resync(stale, peer) -> None:
        stale.flows.truncate()
        for view in stale.views.values():
            view.truncate()
        flows = peer.flows.scan()
        if len(flows):
            stale.insert_flows(flows)
        for name, table in stale.result_tables.items():
            table.truncate()
            data = peer.result_tables[name].scan()
            if len(data):
                table.insert(data)

    # -- writes (fan-out) --------------------------------------------------

    def insert_flows(self, batch, now=None) -> int:
        n = 0
        with self._write_lock:
            for r in self.live():
                n = r.insert_flows(batch, now=now)
        return n

    def insert_flow_rows(self, rows, now=None) -> int:
        n = 0
        with self._write_lock:
            for r in self.live():
                n = r.insert_flow_rows(rows, now=now)
        return n

    def evict_ttl(self, now: int) -> int:
        out = 0
        with self._write_lock:
            for r in self.live():
                out = r.evict_ttl(now)
        return out

    def delete_flows_older_than(self, boundary: int) -> int:
        out = 0
        with self._write_lock:
            for r in self.live():
                out = r.delete_flows_older_than(boundary)
        return out

    # -- reads / passthrough ----------------------------------------------

    def monitor(self, capacity_bytes: int, **kw):
        from .flow_store import RetentionMonitor
        return RetentionMonitor(self, capacity_bytes, **kw)

    def __getattr__(self, name):
        # flows / views / ttl_seconds / save / shards / ... — served by
        # the active replica. (Direct writes through these bypass
        # replication; the manager's write paths all go through the
        # overrides above.)
        return getattr(self.active, name)

    @classmethod
    def load(cls, path: str, replicas: int = 2,
             ttl_seconds: Optional[int] = None,
             **kw) -> "ReplicatedFlowDatabase":
        """Load a snapshot into every replica (they start identical,
        like freshly synced ClickHouse replicas). TTL is deferred
        until every row is back in — the re-insert must not evict
        persisted rows at an arbitrary boundary (same discipline as
        FlowDatabase.load / ShardedFlowDatabase.load)."""
        db = cls(replicas=replicas, ttl_seconds=ttl_seconds, **kw)
        saved_ttls = [_suspend_ttl(r) for r in db.replicas]
        single = FlowDatabase.load(path, build_views=False)
        flows = single.flows.scan()
        if len(flows):
            db.insert_flows(flows)
        for name, table in single.result_tables.items():
            data = table.scan()
            if len(data):
                db.result_tables[name].insert(data)
        for r, ttl in zip(db.replicas, saved_ttls):
            _restore_ttl(r, ttl)
        return db
