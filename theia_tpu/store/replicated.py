"""Replicated flow database — the high-availability tier.

Re-provides the role of the reference's Replicated*MergeTree +
ZooKeeper topology (build/charts/theia/values.yaml:121-183: `replicas`
per shard, ZooKeeper coordinating replica queues): R live copies of
the logical store, writes fanned to every live replica, reads served
from the lowest-index live one, immediate failover when a replica is
marked down, and catch-up-by-copy when one comes back (the in-memory
analogue of a ClickHouse replica replaying its queue from a peer).

Composition order matters: replication wraps the WHOLE logical store
(optionally a ShardedFlowDatabase), so `--shards N --replicas R` is N
shards × R replicas — the same grid the reference's operator CRD
renders.

Consumer surface: identical to FlowDatabase. Read paths delegate to
the active replica via __getattr__; write paths (insert, TTL,
retention, result-table mutation) are explicit fan-out overrides.
Result tables are wrapped so analytics jobs and the controller's GC
mutate every live replica; their deletes are value-based
(Table.delete_ids), because replicas route rows to different physical
orders and a positional mask would corrupt them.

Failure domains: a replica that raises during a fan-out write is
auto-QUARANTINED (marked down with the failure recorded) while the
write succeeds on the survivors — the divergence window is closed the
moment it opens, instead of replicas silently drifting apart. A write
that fails on EVERY live replica quarantines nobody and re-raises the
first error: uniform failure means the request was bad (no replica
took it, so no divergence), and a ValueError must keep reaching the
client as a 400, not a replica incident. ReplicaRepairLoop resyncs
and re-admits quarantined replicas in the background (capped
exponential backoff per replica); replicas downed MANUALLY via
set_replica_down are operator intent and are never re-admitted by it.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..utils import get_logger
from ..utils.backoff import capped_backoff
from ..utils.faults import fire as _fire_fault
from .flow_store import FlowDatabase
from ..analysis.lockdep import named_lock

logger = get_logger("replicated")

_M_REPL_WRITE = _metrics.histogram(
    "theia_replica_write_seconds",
    "Per-replica fan-out write latency", labelnames=("replica",))
_M_REPL_QUAR = _metrics.counter(
    "theia_replica_quarantines_total",
    "Replicas auto-quarantined after a failed fan-out write the "
    "survivors took")
_M_REPL_REPAIR = _metrics.counter(
    "theia_replica_repairs_total",
    "Repair-loop resync attempts on quarantined replicas, by outcome",
    labelnames=("result",))

#: result-table write/read methods the replica proxy forwards
_TABLE_WRITES = ("insert", "insert_rows", "delete_ids",
                 "delete_older_than", "truncate")


class AllReplicasDownError(Exception):
    """Every replica is marked down — no copy can serve."""


def _suspend_ttl(replica):
    """Disable TTL on a replica (and its shards, if sharded) for a
    bulk re-insert; returns the saved value for _restore_ttl."""
    saved = replica.ttl_seconds
    replica.ttl_seconds = None
    for shard in getattr(replica, "shards", ()):
        shard.ttl_seconds = None
    return saved


def _restore_ttl(replica, saved) -> None:
    replica.ttl_seconds = saved
    for shard in getattr(replica, "shards", ()):
        shard.ttl_seconds = saved


class _ReplicatedTable:
    """One result table across replicas: reads from the active copy,
    writes to every live copy."""

    def __init__(self, db: "ReplicatedFlowDatabase", name: str) -> None:
        self._db = db
        self._table_name = name

    def _active(self):
        return self._db.active.result_tables[self._table_name]

    # -- reads ------------------------------------------------------------

    @property
    def name(self):
        return self._table_name

    @property
    def schema(self):
        return self._active().schema

    @property
    def dicts(self):
        return self._active().dicts

    @property
    def nbytes(self):
        return self._active().nbytes

    @property
    def generation(self):
        return self._active().generation

    def __len__(self):
        return len(self._active())

    def scan(self):
        return self._active().scan()

    def select(self, *a, **kw):
        return self._active().select(*a, **kw)

    def min_value(self, *a, **kw):
        return self._active().min_value(*a, **kw)

    # -- writes (fan-out) --------------------------------------------------

    def delete_where(self, mask):
        raise NotImplementedError(
            "positional delete_where is unsafe across replicas (each "
            "copy holds the same logical rows in a different physical "
            "order); use the value-based delete_ids")

    def __getattr__(self, name):
        if name in _TABLE_WRITES:
            def fan(*a, **kw):
                return self._db._fanout(
                    lambda r: getattr(
                        r.result_tables[self._table_name],
                        name)(*a, **kw),
                    f"{self._table_name}.{name}")
            return fan
        return getattr(self._active(), name)


class ReplicatedFlowDatabase:
    """R live copies of the logical store behind one FlowDatabase
    surface."""

    def __init__(self, replicas: int = 2,
                 factory: Optional[Callable[[], object]] = None,
                 ttl_seconds: Optional[int] = None) -> None:
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        if factory is None:
            # default factory resolves THEIA_STORE_COLD_DIR ONCE and
            # gives every replica its own subdirectory — per-replica
            # env resolution would share one part directory, and the
            # active replica's save-time GC would delete its peers'
            # cold-tier files
            base = os.environ.get("THEIA_STORE_COLD_DIR") or None
            counter = itertools.count()

            def factory():
                i = next(counter)
                return FlowDatabase(
                    ttl_seconds=ttl_seconds,
                    parts_dir=(os.path.join(base, f"replica-{i:03d}")
                               if base else ""))
        make = factory
        self.replicas: List = [make() for _ in range(replicas)]
        self._down: set = set()
        #: auto-quarantined replica index → {reason, since,
        #: failedWrites}; a subset of _down. Manual set_replica_down
        #: marks never appear here, so the repair loop leaves them be.
        self._quarantined: Dict[int, Dict[str, object]] = {}
        self._lock = named_lock("store.replicated")
        # Serializes fan-out writes against each other (deterministic
        # per-replica apply order) and — critically — against resync:
        # without it a write landing between the resync copy and the
        # up-mark would be missing from the recovered replica forever.
        self._write_lock = named_lock("store.replicated_write")
        self.result_tables: Dict[str, _ReplicatedTable] = {
            name: _ReplicatedTable(self, name)
            for name in self.replicas[0].result_tables}
        for name, proxy in self.result_tables.items():
            setattr(self, name, proxy)
        # LOGICAL cumulative insert totals, counted once per fan-out
        # write (not per replica). The per-replica Table counters are
        # physical and jump on resync (truncate + full re-insert), so
        # proxying them through `active` would spike the insert-rate
        # stats on every failover; these stay monotone instead.
        self._rows_inserted_total = 0
        self._bytes_inserted_total = 0
        #: dedup tags adopted from stray WALs (each replica's own
        #: recovered tags live in the replica; recovered_acks() merges)
        self._recovered_acks: List[tuple] = []

    # -- replica membership ------------------------------------------------

    def _live_indexed(self) -> List[Tuple[int, object]]:
        with self._lock:
            down = set(self._down)
        out = [(i, r) for i, r in enumerate(self.replicas)
               if i not in down]
        if not out:
            raise AllReplicasDownError(
                f"all {len(self.replicas)} replicas are down")
        return out

    def live(self) -> List:
        return [r for _, r in self._live_indexed()]

    @property
    def active(self):
        """Lowest-index live replica — the read servant."""
        return self.live()[0]

    def set_replica_down(self, index: int) -> None:
        """Manual down-mark (operator intent): excluded from writes and
        reads, but NOT auto-re-admitted by the repair loop — even if
        the replica was auto-quarantined first, the manual mark
        supersedes it (the quarantine record is dropped so repair
        leaves the replica alone)."""
        with self._lock:
            self._down.add(index)
            self._quarantined.pop(index, None)

    def set_replica_up(self, index: int, resync: bool = True) -> None:
        """Bring a replica back; by default it catches up by copying
        the active peer's state wholesale (the replica-queue replay
        analogue — correct, if not incremental, at in-memory scale).
        Holds the write lock across copy + up-mark, so no write can
        slip between them and be lost on the recovered replica."""
        with self._write_lock:
            if resync:
                peer = self.active
                if self.replicas[index] is not peer:
                    self._resync(self.replicas[index], peer)
            with self._lock:
                self._down.discard(index)
                self._quarantined.pop(index, None)

    def repair_replica(self, index: int) -> bool:
        """The repair loop's re-admit entry: set_replica_up(resync=True)
        gated — under the write lock — on the quarantine record still
        existing. Returns False without touching the replica when it
        was manually downed (or healed) after the caller sampled
        quarantined_indices(); a bare set_replica_up here would revert
        an operator's set_replica_down issued in that window."""
        with self._write_lock:
            with self._lock:
                if index not in self._quarantined:
                    return False
            peer = self.active
            if self.replicas[index] is not peer:
                self._resync(self.replicas[index], peer)
            with self._lock:
                self._down.discard(index)
                self._quarantined.pop(index, None)
        return True

    def _quarantine(self, index: int, exc: BaseException) -> None:
        """Auto-mark a replica down after it failed a fan-out write
        the survivors took (the divergence trigger). Caller holds
        _write_lock; _lock nests inside it everywhere."""
        with self._lock:
            self._down.add(index)
            info = self._quarantined.setdefault(
                index, {"since": time.time(), "failedWrites": 0})
            info["failedWrites"] = int(info["failedWrites"]) + 1
            info["reason"] = f"{type(exc).__name__}: {exc}"
        _M_REPL_QUAR.inc()
        logger.error("replica %d quarantined after failed fan-out "
                     "write: %s", index, exc)

    def quarantined_indices(self) -> List[int]:
        with self._lock:
            return sorted(self._quarantined)

    def membership(self) -> Dict[str, object]:
        """Operator view of the replica set (served by /healthz)."""
        with self._lock:
            down = sorted(self._down)
            quarantined = {str(i): dict(v) for i, v
                           in sorted(self._quarantined.items())}
        return {
            "replicas": len(self.replicas),
            "live": [i for i in range(len(self.replicas))
                     if i not in down],
            "down": down,
            "quarantined": quarantined,
        }

    @staticmethod
    def _resync(stale, peer) -> None:
        # Journaling is suspended for the wholesale copy: every row
        # re-inserted here is already durable in the PEER's log, and
        # re-logging it would corrupt the stale replica's LSN
        # sequence. Afterwards the stale replica's WAL jumps to the
        # peer's position ("replays its peers' WAL position"): its
        # memory now reflects everything up to that LSN, so appends
        # continue above it — the gap this leaves is why recovery
        # prefers an ungapped replica until the next checkpoint GCs
        # the stale segments.
        with contextlib.ExitStack() as stack:
            if hasattr(stale, "wal_suspended"):
                stack.enter_context(stale.wal_suspended())
            stale.flows.truncate()
            for view in stale.views.values():
                view.truncate()
            from ..query.rollup import truncate_rollups
            truncate_rollups(stale)   # re-derived by insert_flows
            flows = peer.flows.scan()
            if len(flows):
                stale.insert_flows(flows)
            for name, table in stale.result_tables.items():
                table.truncate()
                data = peer.result_tables[name].scan()
                if len(data):
                    table.insert(data)
        pos = peer.wal_position() if hasattr(peer, "wal_position") \
            else None
        if pos is not None:
            stale.wal_reposition(pos)

    # -- writes (fan-out) --------------------------------------------------

    def _fanout(self, apply: Callable, what: str):
        """Apply one write to every live replica under the write lock.
        A replica that raises while its peers succeed is quarantined
        (partial failure = real divergence); the write succeeds — the
        last successful replica's result is returned — as long as ≥1
        replica took it. Uniform failure (every live replica raised)
        quarantines nobody and re-raises the first error: in the
        overwhelmingly common case (validation rejects the batch)
        nothing was applied anywhere, and a ValueError must keep
        reaching the client as a 400, not a replica incident. Residual
        risk, accepted: a replica that mutates partially and THEN
        raises, while its peers raise too, diverges without being
        quarantined — closing that needs per-write versioning, not a
        failure-count heuristic."""
        with self._write_lock:
            indexed = self._live_indexed()
            out = None
            ok = False
            failures: List[Tuple[int, BaseException]] = []
            for i, r in indexed:
                t0 = time.perf_counter()
                try:
                    _fire_fault("replica.write", replica=i, op=what)
                    out = apply(r)
                    ok = True
                except Exception as e:
                    failures.append((i, e))
                finally:
                    _M_REPL_WRITE.labels(replica=str(i)).observe(
                        time.perf_counter() - t0)
            if not ok:
                raise failures[0][1]
            for i, e in failures:
                self._quarantine(i, e)
            return out

    def insert_flows(self, batch, now=None, dedup=None,
                     wire=None) -> int:
        # `wire` rides through to every replica: each journals the
        # same received bytes verbatim (replicas are whole copies,
        # unlike shard slices)
        n = self._fanout(
            lambda r: r.insert_flows(batch, now=now, dedup=dedup,
                                     wire=wire),
            "insert_flows")
        nbytes = sum(np.asarray(a).nbytes
                     for a in batch.columns.values())
        with self._lock:
            self._rows_inserted_total += n
            self._bytes_inserted_total += nbytes
        return n

    def insert_flow_rows(self, rows, now=None) -> int:
        n = self._fanout(
            lambda r: r.insert_flow_rows(rows, now=now),
            "insert_flow_rows")
        with self._lock:
            # row-shaped inserts carry no columnar byte size here; the
            # rows counter still moves (bytes stay a lower bound)
            self._rows_inserted_total += n
        return n

    @property
    def rows_inserted_total(self) -> int:
        """Cumulative LOGICAL flow rows written through the fan-out
        (monotone across failover and resync, unlike the per-replica
        physical counters)."""
        with self._lock:
            return self._rows_inserted_total

    @property
    def bytes_inserted_total(self) -> int:
        with self._lock:
            return self._bytes_inserted_total

    def evict_ttl(self, now: int) -> int:
        return self._fanout(lambda r: r.evict_ttl(now), "evict_ttl")

    def delete_flows_older_than(self, boundary: int) -> int:
        return self._fanout(
            lambda r: r.delete_flows_older_than(boundary),
            "delete_flows_older_than")

    # -- write-ahead log ---------------------------------------------------

    def attach_wal(self, wal_dir: str, sync=None,
                   segment_bytes=None) -> Dict[str, object]:
        """One WAL per replica under `<wal_dir>/replica-NNN`. Each
        replica first recovers from its own log; then every replica is
        resynced from the BEST-recovered one — most rows behind a
        contiguous (ungapped) log — because a replica that was
        quarantined before the crash carries a gap where the fan-out
        wrote around it, and recovering from a gapped log would
        silently resurrect a stale copy. The survivors' resync also
        jumps their logs to the best replica's position (the runtime
        repair path's discipline, applied at startup)."""
        per: List[Dict[str, object]] = []
        for i, r in enumerate(self.replicas):
            per.append(r.attach_wal(
                os.path.join(wal_dir, f"replica-{i:03d}"),
                sync=sync, segment_bytes=segment_bytes))

        def _pos(s) -> int:
            last = s["lastLsn"]
            return (sum(last) if isinstance(last, (list, tuple))
                    else int(last))

        best = max(range(len(per)), key=lambda i: (
            not per[i]["gapped"], _pos(per[i]),
            int(per[i]["recoveredRows"])))
        peer = self.replicas[best]
        for i, r in enumerate(self.replicas):
            if i == best:
                continue
            # the common clean restart: every replica recovered the
            # same ungapped log to the same position — already
            # identical, a wholesale copy would be pure waste
            if not per[i]["gapped"] \
                    and _pos(per[i]) == _pos(per[best]) \
                    and per[i]["recoveredRows"] == \
                    per[best]["recoveredRows"]:
                continue
            self._resync(r, peer)
        stats = dict(per[best])
        stats["replica"] = best
        stats["perReplica"] = per
        if any(i != best and _pos(per[i]) != _pos(per[best])
               for i in range(len(per))):
            logger.warning(
                "replica WALs recovered to different positions; all "
                "replicas resynced from replica %d (%d rows)",
                best, int(per[best]["recoveredRows"]))
        # Foreign topology content (a previous plain/sharded run's
        # logs in the same --wal-dir, or replica dirs beyond our
        # count) — partitions replay through the fan-out insert so
        # every replica journals them; stray replica COPIES are
        # redundant with what our own replicas just recovered and are
        # only removed (or kept, loudly, if somehow ahead).
        from .wal import adopt_foreign_wal_dirs
        own = [os.path.join(wal_dir, f"replica-{i:03d}")
               for i in range(len(self.replicas))]
        stamps = getattr(self.replicas[0], "_snapshot_lsns", [])
        adopted = adopt_foreign_wal_dirs(
            self, wal_dir, own, list(stamps),
            replica_copies=False, own_position=_pos(per[best]))
        if adopted:
            stats["adoptedRows"] = adopted
        return stats

    @contextlib.contextmanager
    def wal_suspended(self):
        """Suspend journaling on EVERY replica (the __getattr__ proxy
        would reach only the active one; a fan-out write during the
        suspension must not be journaled by the others either)."""
        with contextlib.ExitStack() as stack:
            for r in self.replicas:
                if hasattr(r, "wal_suspended"):
                    stack.enter_context(r.wal_suspended())
            yield

    def wal_stats(self) -> Optional[Dict[str, object]]:
        return self.active.wal_stats()

    def wal_lag(self) -> int:
        """Worst unsynced-record lag across live replicas (the
        admission plane's pressure signal: the slowest copy sets the
        real durability exposure)."""
        lags = [r.wal_lag() for r in self.live()
                if hasattr(r, "wal_lag")]
        return max(lags) if lags else 0

    def note_recovered_ack(self, stream: str, seq: int, rows: int,
                           total: Optional[int] = None) -> None:
        self._recovered_acks.append((stream, int(seq), int(rows),
                                     total))

    def recovered_acks(self) -> List[tuple]:
        """Dedup tags recovered at attach_wal. Replica logs are COPIES
        of the same logical stream, so the merge dedupes by
        (stream, seq) (taking the max recovered count) instead of
        summing — summing would multiply every ack by the replica
        count."""
        merged: Dict[tuple, List] = {}
        for r in self.replicas:
            ra = getattr(r, "recovered_acks", None)
            if not callable(ra):
                continue
            for stream, seq, rows, total in ra():
                ent = merged.setdefault((stream, seq), [0, None])
                ent[0] = max(ent[0], rows)
                if total is not None:
                    ent[1] = max(ent[1] or 0, total)
        out = [(k[0], k[1], v[0], v[1]) for k, v in merged.items()]
        out.extend(self._recovered_acks)
        return out

    def wal_sync(self) -> None:
        for r in self.live():
            r.wal_sync()

    def wal_gc(self, stamp) -> int:
        # live replicas advance in LSN lockstep (same fan-out
        # sequence; resync repositions), so the active's snapshot
        # stamp covers every live log
        return sum(r.wal_gc(stamp) for r in self.live())

    def close_wal(self) -> None:
        for r in self.replicas:
            r.close_wal()

    # -- reads / passthrough ----------------------------------------------

    def monitor(self, capacity_bytes: int, **kw):
        from .flow_store import RetentionMonitor
        return RetentionMonitor(self, capacity_bytes, **kw)

    def demote_cold(self, target_bytes: int) -> int:
        """Tiered retention must reach EVERY live replica (each holds
        a full copy; __getattr__ would demote only the active one).
        Returns the max freed — replicas are copies, so summing would
        double-count the logical bytes."""
        return max((r.demote_cold(target_bytes)
                    for r in self.live()), default=0)

    def maintenance_tick(self) -> int:
        return sum(r.maintenance_tick() for r in self.live())

    def __getattr__(self, name):
        # flows / views / ttl_seconds / save / shards / ... — served by
        # the active replica. (Direct writes through these bypass
        # replication; the manager's write paths all go through the
        # overrides above.)
        return getattr(self.active, name)

    @classmethod
    def load(cls, path: str, replicas: int = 2,
             ttl_seconds: Optional[int] = None,
             **kw) -> "ReplicatedFlowDatabase":
        """Load a snapshot into every replica (they start identical,
        like freshly synced ClickHouse replicas). TTL is deferred
        until every row is back in — the re-insert must not evict
        persisted rows at an arbitrary boundary (same discipline as
        FlowDatabase.load / ShardedFlowDatabase.load)."""
        db = cls(replicas=replicas, ttl_seconds=ttl_seconds, **kw)
        saved_ttls = [_suspend_ttl(r) for r in db.replicas]
        # flat temp carrier (parts-aware snapshots decode through the
        # cross-engine donor path; a parts carrier would seal
        # transient files beside the replicas')
        single = FlowDatabase.load(path, build_views=False,
                                   engine="flat")
        for r in db.replicas:
            # every replica starts at the snapshot's WAL stamp, so a
            # later attach_wal replays only records above it
            r._snapshot_lsns = list(single._snapshot_lsns)
        flows = single.flows.scan()
        if len(flows):
            db.insert_flows(flows)
        for name, table in single.result_tables.items():
            data = table.scan()
            if len(data):
                db.result_tables[name].insert(data)
        for r, ttl in zip(db.replicas, saved_ttls):
            _restore_ttl(r, ttl)
        return db


class ReplicaRepairLoop:
    """Background self-healing for auto-quarantined replicas: resync
    from the active peer and re-admit via db.repair_replica (the
    set_replica_up(resync=True) path, gated on the quarantine record
    still existing so a concurrent manual down-mark wins) — the
    in-memory analogue of a ClickHouse replica replaying its
    ZooKeeper queue after an outage. Failed repair attempts back off
    exponentially per replica (capped), so a persistently broken copy
    is probed, not hammered. Replicas downed manually stay down (they
    carry no quarantine record).

    The clock is injectable (`time_fn`) and repair_once() is public,
    so tests drive the schedule without sleeping."""

    def __init__(self, db: ReplicatedFlowDatabase,
                 interval: float = 2.0, base_backoff: float = 1.0,
                 max_backoff: float = 60.0,
                 time_fn: Callable[[], float] = time.monotonic) -> None:
        self.db = db
        self.interval = interval
        self.base_backoff = base_backoff
        self.max_backoff = max_backoff
        self.repairs = 0
        self.failed_attempts = 0
        self._time = time_fn
        self._fails: Dict[int, int] = {}
        self._next_attempt: Dict[int, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-replica-repair")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.repair_once()
            except Exception as e:   # keep repairing after a bad pass
                logger.error("replica repair pass failed: %s", e)

    def repair_once(self) -> List[int]:
        """One repair pass; returns the re-admitted replica indices."""
        now = self._time()
        quarantined = self.db.quarantined_indices()
        # a replica healed elsewhere (manual set_replica_up) sheds its
        # backoff state
        for i in list(self._fails):
            if i not in quarantined:
                self._fails.pop(i, None)
                self._next_attempt.pop(i, None)
        healed: List[int] = []
        for i in quarantined:
            if self._next_attempt.get(i, 0.0) > now:
                continue
            try:
                if not self.db.repair_replica(i):
                    # manually downed (or healed elsewhere) since we
                    # sampled the quarantine list — not ours to touch
                    continue
            except Exception as e:
                self.failed_attempts += 1
                _M_REPL_REPAIR.labels(result="failed").inc()
                fails = self._fails.get(i, 0) + 1
                self._fails[i] = fails
                delay = capped_backoff(self.base_backoff,
                                       self.max_backoff, fails)
                self._next_attempt[i] = now + delay
                logger.error("replica %d repair attempt %d failed "
                             "(%s); next attempt in %.1fs",
                             i, fails, e, delay)
            else:
                self.repairs += 1
                _M_REPL_REPAIR.labels(result="repaired").inc()
                self._fails.pop(i, None)
                self._next_attempt.pop(i, None)
                healed.append(i)
                logger.info("replica %d resynced and re-admitted "
                            "after quarantine", i)
        return healed
