"""Versioned schema migration for persisted FlowDatabase files.

Re-provides the reference's schema-management init container
(plugins/clickhouse-schema-management/main.go:62-117): a framework
version maps to a schema version (VERSION_MAP), stored data is migrated
up or down through ordered migrators to the target, and the resulting
version is stamped so future loads know where they stand. The reference
keeps five SQL migrators
(build/charts/theia/provisioning/datasources/migrators/0000{1..5}_*.sql);
here migrators are column transforms over the persisted .npz payload.

Schema history (mirrors the reference's column evolution):
  v1 — flows without `trusted`           (pre policy-feedback)
  v2 — + `trusted` UInt8                 (subsequent-NPR support)
  v3 — + `egressName`, `egressIP`        (egress observability)
  v4 — + `dropdetection` result table    (traffic-drop detection)
  v5 — + `tadetector.refitEvery`         (ARIMA refit-cadence audit)
  v6 — + `flowpatterns`, `spatialnoise`  (pattern mining + spatial
        DBSCAN result tables)
  v7 — + `__metrics__` result table      (self-scraped metrics
        history)
  v8 — + `__rollup__/<view>/*` payloads  (streaming rollup-view
        aggregate state stamped with its view definition; current)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

import numpy as np

CURRENT_SCHEMA_VERSION = 8
VERSION_KEY = "__schema_version__"

# framework version → schema version (reference VERSION_MAP,
# clickhouse-schema-management/main.go)
VERSION_MAP = {
    "0.1.0": 1,
    "0.1.1": 2,
    "0.2.0": 3,
    "0.3.0": 4,
    "0.4.0": 5,
    "0.5.0": 6,
    "0.6.0": 7,
    "0.7.0": 8,
}

Payload = Dict[str, np.ndarray]


def _n_rows(payload: Payload, table: str = "flows") -> int:
    for key, arr in payload.items():
        if key.startswith(f"{table}/") and "__dict__" not in key:
            return len(arr)
    return 0


def _add_numeric(payload: Payload, name: str, dtype) -> None:
    payload[f"flows/{name}"] = np.zeros(_n_rows(payload), dtype)


def _add_string(payload: Payload, name: str) -> None:
    # code 0 == '' for every row; dictionary starts with just ''
    payload[f"flows/{name}"] = np.zeros(_n_rows(payload), np.int32)
    payload[f"flows/__dict__/{name}"] = np.asarray([""], dtype=object)


def _drop(payload: Payload, name: str) -> None:
    payload.pop(f"flows/{name}", None)
    payload.pop(f"flows/__dict__/{name}", None)


@dataclasses.dataclass(frozen=True)
class Migration:
    version: int            # version this migration upgrades TO
    name: str
    up: Callable[[Payload], None]
    down: Callable[[Payload], None]   # reverts to version-1


MIGRATIONS: List[Migration] = [
    Migration(
        version=2, name="add_trusted",
        up=lambda p: _add_numeric(p, "trusted", np.int32),
        down=lambda p: _drop(p, "trusted")),
    Migration(
        version=3, name="add_egress_name_ip",
        up=lambda p: (_add_string(p, "egressName"),
                      _add_string(p, "egressIP")) and None,
        down=lambda p: (_drop(p, "egressName"),
                        _drop(p, "egressIP")) and None),
    Migration(
        version=4, name="add_dropdetection_table",
        up=lambda p: _add_dropdetection(p),
        down=lambda p: _drop_table(p, "dropdetection")),
    Migration(
        version=5, name="add_tadetector_refit_every",
        # Pre-v5 rows predate the grouped-refit knob: every ARIMA job
        # ran the then-hardwired auto cadence. The zero-fill means "no
        # cadence recorded" (rows with algoType=ARIMA and refitEvery=0
        # are legacy approximate results, not exact ones).
        up=lambda p: _add_table_schema_column(p, "tadetector",
                                              "refitEvery"),
        down=lambda p: _drop_key(p, "tadetector/refitEvery")),
    Migration(
        version=6, name="add_flowpatterns_spatialnoise_tables",
        up=lambda p: (_add_empty_table(p, "flowpatterns"),
                      _add_empty_table(p, "spatialnoise")) and None,
        down=lambda p: (_drop_table(p, "flowpatterns"),
                        _drop_table(p, "spatialnoise")) and None),
    Migration(
        version=7, name="add_metrics_history_table",
        up=lambda p: _add_empty_table(p, "__metrics__"),
        down=lambda p: _drop_table(p, "__metrics__")),
    Migration(
        version=8, name="add_rollup_view_payloads",
        # Rollup aggregate state is OPTIONAL in a snapshot: a v8 load
        # with no `__rollup__/...` keys simply rebuilds the declared
        # views from the flows rows (query/rollup.py
        # restore_or_rebuild), so upgrading is a no-op. Downgrading
        # drops the payloads a pre-v8 reader would not understand.
        up=lambda p: None,
        down=lambda p: _drop_prefix(p, "__rollup__/")),
]


def _drop_prefix(payload: Payload, prefix: str) -> None:
    for key in [k for k in payload if k.startswith(prefix)]:
        payload.pop(key)


def _drop_key(payload: Payload, key: str) -> None:
    payload.pop(key, None)


def _add_table_schema_column(payload: Payload, table: str,
                             name: str) -> None:
    """Zero-fill a new numeric column with the LIVE schema's host dtype
    so migrated payloads match freshly-saved ones (adopt-time casting in
    flow_store would paper over a mismatch, but the on-disk format
    shouldn't diverge)."""
    from ..schema import TADETECTOR_SCHEMA
    schema = {"tadetector": TADETECTOR_SCHEMA}[table]
    col = next(c for c in schema if c.name == name)
    payload[f"{table}/{name}"] = np.zeros(_n_rows(payload, table),
                                          col.host_dtype)


def _add_dropdetection(payload: Payload) -> None:
    _add_empty_table(payload, "dropdetection")


def _add_empty_table(payload: Payload, table: str) -> None:
    """Empty result table (columns straight from the live schema so
    the migrator can't drift from it; string columns get an ''-seeded
    dict, the same empty-table layout FlowDatabase.save emits)."""
    from .flow_store import RESULT_TABLE_SCHEMAS
    schema = dict(RESULT_TABLE_SCHEMAS)[table]
    for col in schema:
        if col.is_string:
            payload[f"{table}/{col.name}"] = np.zeros(0, np.int32)
            payload[f"{table}/__dict__/{col.name}"] = np.asarray(
                [""], dtype=object)
        else:
            payload[f"{table}/{col.name}"] = np.zeros(0, col.host_dtype)


def _drop_table(payload: Payload, table: str) -> None:
    for key in [k for k in payload if k.startswith(f"{table}/")]:
        payload.pop(key)


def payload_version(payload: Payload) -> int:
    if VERSION_KEY in payload:
        return int(np.asarray(payload[VERSION_KEY]).item())
    # Unstamped files predate the migrator; infer from columns.
    if "flows/egressName" in payload:
        return 3
    if "flows/trusted" in payload:
        return 2
    return 1


def migrate(payload: Payload,
            target: int = CURRENT_SCHEMA_VERSION) -> Payload:
    """Migrate a persisted payload to `target`, stamping the result.
    Runs up- or down-migrators in order (main.go startMigration)."""
    if not 1 <= target <= CURRENT_SCHEMA_VERSION:
        raise ValueError(f"unknown schema version {target}")
    # Migration mutates the payload, so any integrity stamp written by
    # flow_store.write_snapshot no longer matches; drop it rather than
    # let a re-saved migrated payload fail verification. (Verification
    # runs BEFORE migration on load, so nothing is lost here.)
    from .flow_store import INTEGRITY_KEY
    payload.pop(INTEGRITY_KEY, None)
    version = payload_version(payload)
    if version > CURRENT_SCHEMA_VERSION:
        raise ValueError(
            f"data written by a newer schema (v{version}); refusing")
    while version < target:
        step = next(m for m in MIGRATIONS if m.version == version + 1)
        step.up(payload)
        version += 1
    while version > target:
        step = next(m for m in MIGRATIONS if m.version == version)
        step.down(payload)
        version -= 1
    force(payload, version)
    return payload


def force(payload: Payload, version: int) -> None:
    """Stamp a version without running migrators (main.go Force())."""
    payload[VERSION_KEY] = np.asarray(version, np.int64)


def schema_version_for(framework_version: str) -> int:
    """Map a framework version to its schema version; unknown versions
    get the current schema (forward-compatible default)."""
    return VERSION_MAP.get(framework_version, CURRENT_SCHEMA_VERSION)
