"""Streaming materialized views over the flows table.

Re-provides the reference's three SummingMergeTree materialized views
(build/charts/theia/provisioning/datasources/create_table.sh:92-351):

  * flows_pod_view    — per-pod aggregation       (create_table.sh:92-175)
  * flows_node_view   — per-node aggregation      (create_table.sh:178-241)
  * flows_policy_view — per-NetworkPolicy totals  (create_table.sh:244-351)

Semantics match ClickHouse: each *insert block* is grouped by the view's
key columns with the metric columns summed (the MV GROUP BY runs per
block); further collapsing of identical keys across blocks happens at
"merge" time — here `compact()`, called automatically on read. All group
keys are integers (dictionary codes for strings), so the per-block group-by
is one lexsort + reduceat over fixed-width arrays — no Python-object work
on the ingest path.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Tuple

import numpy as np

from ..schema import ColumnarBatch, StringDictionary
from ..analysis.lockdep import named_lock


def group_reduce(keys: np.ndarray, values: np.ndarray, op: str = "sum"
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized GROUP BY: `keys` [n,k] int64, `values` [n,m].

    `op` is "sum" or "max". Returns (unique_keys [g,k], reduced [g,m])
    with groups in lexicographic order. This is the host-side analogue of
    the on-device segment reductions the analytics jobs use; lexsort +
    reduceat keeps it allocation-lean.
    """
    n = keys.shape[0]
    if n == 0:
        return keys, values
    order = np.lexsort(keys.T[::-1])
    sk = keys[order]
    sv = values[order]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    boundary[1:] = np.any(sk[1:] != sk[:-1], axis=1)
    starts = np.flatnonzero(boundary)
    ufunc = np.add if op == "sum" else np.maximum
    reduced = ufunc.reduceat(sv, starts, axis=0)
    return sk[starts], reduced


def group_sum(keys: np.ndarray, values: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    return group_reduce(keys, values, "sum")


def group_sum_fast(keys: np.ndarray, values: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-insert-block GROUP BY for the MV hot path: sort by a single
    64-bit row hash instead of lexsorting 15-20 key columns (~20x less
    sort work). Output group ORDER is arbitrary, and a hash collision
    between distinct keys may split a group into two rows — both are
    fine for a SummingMergeTree part: `compact()`/`_merged` re-groups
    exactly (lexsort) at read time, which is also where ClickHouse
    collapses part rows. Do NOT use where callers rely on lexicographic
    group order (use group_reduce)."""
    n = keys.shape[0]
    if n == 0:
        return keys, values
    h = np.full(n, 0xcbf29ce484222325, np.uint64)
    for i in range(keys.shape[1]):
        x = keys[:, i].astype(np.uint64)
        x *= np.uint64(0xff51afd7ed558ccd)
        x ^= x >> np.uint64(33)
        h ^= x
        h *= np.uint64(0x100000001b3)
    order = np.argsort(h, kind="stable")
    sk = keys[order]
    sv = values[order]
    boundary = np.empty(n, dtype=bool)
    boundary[0] = True
    # Full-row compare: equal keys are adjacent (equal hash); colliding
    # distinct keys interleaved in a run just produce extra boundaries.
    boundary[1:] = np.any(sk[1:] != sk[:-1], axis=1)
    starts = np.flatnonzero(boundary)
    return sk[starts], np.add.reduceat(sv, starts, axis=0)


def materialize_view_batch(spec: "ViewSpec", keys: np.ndarray,
                           values: np.ndarray,
                           dicts: Dict[str, StringDictionary]
                           ) -> ColumnarBatch:
    """(keys [g,k], values [g,m]) → a ColumnarBatch in the view's row
    shape. The single materialization point for view reads — ViewTable
    (single node) and DistributedView (sharded) both go through it, so
    the two read paths cannot drift."""
    cols: Dict[str, np.ndarray] = {}
    for i, name in enumerate(spec.key_columns):
        cols[name] = keys[:, i].astype(
            np.int32 if name in dicts else np.int64)
    for i, name in enumerate(spec.sum_columns):
        cols[name] = values[:, i]
    return ColumnarBatch(
        cols, {n: dicts[n] for n in spec.key_columns if n in dicts})


@dataclasses.dataclass(frozen=True)
class ViewSpec:
    key_columns: Tuple[str, ...]
    sum_columns: Tuple[str, ...]


# Column lists transcribed from the reference MV definitions (see module
# docstring for the create_table.sh line ranges).
MATERIALIZED_VIEWS: Dict[str, ViewSpec] = {
    "flows_pod_view": ViewSpec(
        key_columns=(
            "timeInserted", "flowEndSeconds", "flowEndSecondsFromSourceNode",
            "flowEndSecondsFromDestinationNode", "sourcePodName",
            "destinationPodName", "destinationIP", "destinationServicePort",
            "destinationServicePortName", "flowType", "sourcePodNamespace",
            "destinationPodNamespace", "sourceTransportPort",
            "destinationTransportPort", "clusterUUID"),
        sum_columns=(
            "octetDeltaCount", "reverseOctetDeltaCount", "throughput",
            "reverseThroughput", "throughputFromSourceNode",
            "throughputFromDestinationNode")),
    "flows_node_view": ViewSpec(
        key_columns=(
            "timeInserted", "flowEndSeconds", "flowEndSecondsFromSourceNode",
            "flowEndSecondsFromDestinationNode", "sourceNodeName",
            "destinationNodeName", "sourcePodNamespace",
            "destinationPodNamespace", "clusterUUID"),
        sum_columns=(
            "octetDeltaCount", "reverseOctetDeltaCount", "throughput",
            "reverseThroughput", "throughputFromSourceNode",
            "reverseThroughputFromSourceNode",
            "throughputFromDestinationNode",
            "reverseThroughputFromDestinationNode")),
    "flows_policy_view": ViewSpec(
        key_columns=(
            "timeInserted", "flowEndSeconds", "flowEndSecondsFromSourceNode",
            "flowEndSecondsFromDestinationNode", "egressNetworkPolicyName",
            "egressNetworkPolicyNamespace", "egressNetworkPolicyRuleAction",
            "ingressNetworkPolicyName", "ingressNetworkPolicyNamespace",
            "ingressNetworkPolicyRuleAction", "sourcePodName",
            "sourceTransportPort", "sourcePodNamespace",
            "destinationPodName", "destinationTransportPort",
            "destinationPodNamespace", "destinationServicePort",
            "destinationServicePortName", "destinationIP", "clusterUUID"),
        sum_columns=(
            "octetDeltaCount", "reverseOctetDeltaCount", "throughput",
            "reverseThroughput", "throughputFromSourceNode",
            "reverseThroughputFromSourceNode",
            "throughputFromDestinationNode",
            "reverseThroughputFromDestinationNode")),
}


class ViewTable:
    """One materialized view: accumulated (keys, sums) parts + compaction."""

    def __init__(self, name: str, spec: ViewSpec,
                 dicts: Dict[str, StringDictionary]) -> None:
        self.name = name
        self.spec = spec
        # Shared with the flows table, so view key codes decode with the
        # same dictionaries.
        self.dicts = dicts
        # Parts are (keys, values, exact). `exact` records whether the
        # part is known collision-free (native memcmp grouping, or a
        # read-time lexsort compaction); group_sum_fast parts are not —
        # a 64-bit row-hash collision can split one key across rows.
        self._parts: List[Tuple[np.ndarray, np.ndarray, bool]] = []
        self._lock = named_lock("store.view")

    def __len__(self) -> int:
        keys, _ = self._merged()
        return keys.shape[0]

    def apply_insert_block(self, block: ColumnarBatch) -> None:
        """Aggregate one flows insert block into this view (the MV SELECT
        ... GROUP BY per inserted block). Native single-pass hash
        grouping when available (native/groupsum.cc); numpy hash-sort
        otherwise — both emit unordered SummingMergeTree parts that
        compact() re-groups exactly at read time."""
        from ..ingest.native import native_group_sum
        out = native_group_sum(
            [block[c] for c in self.spec.key_columns],
            [block[c] for c in self.spec.sum_columns])
        exact = out is not None  # native grouping memcmps full keys
        if out is None:
            keys = np.stack([np.asarray(block[c], np.int64)
                             for c in self.spec.key_columns], axis=1)
            values = np.stack([np.asarray(block[c], np.int64)
                               for c in self.spec.sum_columns], axis=1)
            out = group_sum_fast(keys, values)
        with self._lock:
            self._parts.append((out[0], out[1], exact))

    def _merged(self) -> Tuple[np.ndarray, np.ndarray]:
        with self._lock:
            parts = list(self._parts)
        if not parts:
            k = np.zeros((0, len(self.spec.key_columns)), np.int64)
            v = np.zeros((0, len(self.spec.sum_columns)), np.int64)
            return k, v
        if len(parts) == 1 and parts[0][2]:
            return parts[0][0], parts[0][1]
        # Re-group even a lone inexact part: group_sum_fast may have
        # split a hash-colliding key into two rows, and scan() promises
        # exact re-grouping at read time.
        keys = np.concatenate([p[0] for p in parts], axis=0)
        values = np.concatenate([p[1] for p in parts], axis=0)
        gk, gv = group_sum(keys, values)
        with self._lock:
            # Swap in the compacted part only if no insert raced us.
            if len(self._parts) == len(parts) and \
                    self._parts[-1] is parts[-1]:
                self._parts = [(gk, gv, True)]
        return gk, gv

    def compact(self) -> None:
        self._merged()

    def scan(self) -> ColumnarBatch:
        """The view as a ColumnarBatch (keys + summed metrics)."""
        keys, values = self._merged()
        return materialize_view_batch(self.spec, keys, values,
                                      self.dicts)

    def restore(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Install persisted (keys, values) aggregates wholesale — the
        parts-aware snapshot saves views instead of rebuilding them
        from rows at load (the flat-load discipline would force every
        lazy part to decode). The arrays come from a `_merged()`
        capture, so the single part is exact."""
        with self._lock:
            self._parts = [(np.asarray(keys, np.int64).reshape(
                                -1, len(self.spec.key_columns)),
                            np.asarray(values, np.int64).reshape(
                                -1, len(self.spec.sum_columns)),
                            True)]

    def delete_older_than(self, boundary: int) -> int:
        """Drop view rows with timeInserted < boundary (retention trim
        deletes from MVs too, clickhouse-monitor/main.go:284-293).
        Filters part-by-part under the lock — no insert can be lost."""
        ti = self.spec.key_columns.index("timeInserted")
        with self._lock:
            dropped = 0
            new_parts = []
            for keys, values, exact in self._parts:
                keep = keys[:, ti] >= boundary
                dropped += int((~keep).sum())
                if keep.all():
                    new_parts.append((keys, values, exact))
                elif keep.any():
                    new_parts.append((keys[keep], values[keep], exact))
            self._parts = new_parts
        return dropped

    def truncate(self) -> None:
        with self._lock:
            self._parts = []
