"""Columnar flow store: tables, materialized views, TTL, retention."""

from .checkpoint import Checkpointer
from .flow_store import (FlowDatabase, RetentionLoop, RetentionMonitor,
                         Table)
from .replicated import (AllReplicasDownError, ReplicaRepairLoop,
                         ReplicatedFlowDatabase)
from .sharded import (DistributedTable, DistributedView,
                      ShardedFlowDatabase)
from .views import (MATERIALIZED_VIEWS, ViewSpec, ViewTable, group_reduce,
                    group_sum)

__all__ = [
    "AllReplicasDownError", "Checkpointer", "FlowDatabase",
    "ReplicaRepairLoop", "ReplicatedFlowDatabase",
    "RetentionLoop", "RetentionMonitor", "Table",
    "DistributedTable", "DistributedView", "ShardedFlowDatabase",
    "MATERIALIZED_VIEWS", "ViewSpec", "ViewTable", "group_reduce", "group_sum",
]
