"""Columnar flow store: tables, materialized views, TTL, retention."""

from .flow_store import FlowDatabase, RetentionMonitor, Table
from .views import (MATERIALIZED_VIEWS, ViewSpec, ViewTable, group_reduce,
                    group_sum)

__all__ = [
    "FlowDatabase", "RetentionMonitor", "Table",
    "MATERIALIZED_VIEWS", "ViewSpec", "ViewTable", "group_reduce", "group_sum",
]
