"""Columnar flow store: tables, materialized views, TTL, retention."""

from .checkpoint import Checkpointer
from .flow_store import (FlowDatabase, RetentionLoop, RetentionMonitor,
                         SnapshotCorruption, Table, boundary_from_meta,
                         read_snapshot, write_snapshot)
from .parts import (PartMaintenanceLoop, PartsError,
                    PartsManifestError, PartTable,
                    default_store_engine)
from .replicated import (AllReplicasDownError, ReplicaRepairLoop,
                         ReplicatedFlowDatabase)
from .sharded import (DistributedTable, DistributedView,
                      ShardedFlowDatabase)
from .views import (MATERIALIZED_VIEWS, ViewSpec, ViewTable, group_reduce,
                    group_sum)
from .wal import (SyncPolicy, WalCorruption, WalError, WriteAheadLog,
                  default_sync_policy)

__all__ = [
    "AllReplicasDownError", "Checkpointer", "FlowDatabase",
    "PartMaintenanceLoop", "PartsError", "PartsManifestError",
    "PartTable", "ReplicaRepairLoop", "ReplicatedFlowDatabase",
    "RetentionLoop", "RetentionMonitor", "SnapshotCorruption", "Table",
    "boundary_from_meta", "default_store_engine",
    "DistributedTable", "DistributedView", "ShardedFlowDatabase",
    "MATERIALIZED_VIEWS", "ViewSpec", "ViewTable", "group_reduce", "group_sum",
    "SyncPolicy", "WalCorruption", "WalError", "WriteAheadLog",
    "default_sync_policy", "read_snapshot", "write_snapshot",
]
