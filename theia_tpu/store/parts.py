"""Part-based columnar storage engine — sealed compressed parts,
pruned scans, background compaction, tiered retention.

The flat `Table` (flow_store.py) keeps every resident row at raw coded
width (~284 B/row for the 52-column flow schema) and `scan()`/`select()`
touch all of it. This module re-provides the table surface MergeTree-
style (the reference's ClickHouse storage layer): ingest appends to a
small mutable MEMTABLE that seals into immutable, time-partitioned
column PARTS using the WAL record encoding promoted to a storage
format — dictionary strings + width-reduced/delta ints, ~88 B/row vs
284 raw (store/wal.py measured it first) — so month-scale retention
fits bounded RAM.

Engine selection: `THEIA_STORE_ENGINE=parts|flat` (default `flat`,
same parity-gate-before-flip playbook as PR 6's
THEIA_DETECTOR_ENGINE). The parts engine is surface-identical to the
flat table: `scan()`/`select()` return byte-identical results
(tests/test_parts.py gates it under randomized inserts + deletes +
TTL + merges + recovery).

Layout:

  * In memory, a sealed part holds one chunk per column in TABLE-
    GLOBAL code space: numeric columns width-reduced against a
    per-part base (wal.width_reduce), string columns as the part's
    unique global dictionary codes + narrow local indices. Decoding a
    hot part back to a ColumnarBatch is pure integer work — no string
    re-encoding — so codes are byte-identical to the flat engine's.
  * On disk (when a part directory is configured), each part is one
    SELF-CONTAINED file: a checksummed header + the exact WAL record
    body (wal.encode_record_parts — unique strings shipped, so the
    file replays into any dictionary state, like a WAL record does).
  * Each part carries min/max metadata for the pruning columns
    (`timeInserted`, `flowStartSeconds`, `flowEndSeconds`), so
    `select(start_time, end_time)` decodes only overlapping parts —
    the MergeTree primary-index skip — and retention boundary
    selection is O(parts), not O(n log n).
  * A background merge loop (PartMaintenanceLoop, supervised with the
    shared capped_backoff schedule) compacts adjacent small parts of
    the same time partition into larger ones.
  * Retention DEMOTES cold parts to the disk tier (resident chunks
    freed; the self-contained file is decoded on demand) before any
    row is deleted — the in-DRAM active-flows working-set split
    (arXiv:1902.04143): hot set resident, long tail spilled.
  * Recovery = load the part MANIFEST (atomic, generational,
    `.prev` fallback like the snapshot) + the memtable rows from the
    npz snapshot + replay the short WAL tail above the snapshot
    stamp. Parts subsume the bulk of the snapshot, load lazily, and
    are the part-shipping foundation for replication (ROADMAP item 1).

Sort order + indexes (PR 12, the rest of the MergeTree read design):

  * Parts seal and merge SORTED by a configurable primary key
    (`THEIA_STORE_SORT_KEY`, default timeInserted,destinationIP,
    sourceIP — the reference's ClickHouse ORDER BY; string columns
    cluster by dictionary code, which groups identical values exactly
    even though the order is code-allocation order, not lexicographic).
  * Every sorted part carries an explicit ROW-ID column — the sort
    permutation (`sorted_row[i]` was insertion row `rowid[i]`) — so
    the insertion-order contract SURVIVES sorting: `scan()`/`select()`
    un-permute on decode (byte-identical flat parity holds unchanged)
    and positional delete masks resolve through the row-id.
  * Each sorted part keeps a SPARSE PRIMARY INDEX + per-granule SKIP
    INDEXES (`THEIA_STORE_GRANULE_ROWS`, default 8192): min/max zone
    maps on every column (the sort-key prefix's zone map IS the
    binary-searchable sparse index, since the column is sorted) and
    bounded set indexes of distinct dictionary codes on string
    columns. The query engine prunes at granule granularity INSIDE
    parts — predicates decide granules from resident metadata before
    any row is gathered (query/engine.py).
  * Runs of sorted parts merge with a K-WAY STREAMING merge (already-
    ordered runs concatenate; overlapping runs pay one stable key
    sort over the sort-key columns only) instead of concat+re-encode,
    and background maintenance UPGRADES pre-PR-12 unsorted parts
    (format v1) to sorted+indexed v2 in place.
  * The part format version is stamped per part in the manifest:
    v1 parts adopt lazily (scanned, never granule-pruned) so old
    stores load unchanged and converge via merges/upgrades.

Env knobs (all also constructor-injectable for tests):

    THEIA_STORE_ENGINE             parts|flat (default flat)
    THEIA_STORE_MEMTABLE_ROWS      memtable rows before a seal (65536)
    THEIA_STORE_PART_ROWS          merge target part size (262144)
    THEIA_STORE_PARTITION_SECONDS  time partition width (3600)
    THEIA_STORE_SORT_KEY           part primary key, comma-separated
                                   columns (default timeInserted,
                                   destinationIP,sourceIP; empty
                                   disables sorting → v1 parts)
    THEIA_STORE_GRANULE_ROWS       rows per index granule (8192)
    THEIA_STORE_COLD_DIR           part/manifest directory (manager
                                   default: <db path>.parts)
    THEIA_STORE_MERGE_INTERVAL     background merge cadence (5s;
                                   <=0 disables the loop)
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import struct
import threading
import uuid
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import metrics as _metrics
from ..schema import ColumnarBatch
from ..utils.backoff import capped_backoff
from ..utils.env import env_float, env_int
from ..utils.logging import get_logger
from . import wal as _wal
from .flow_store import Table
from ..analysis.lockdep import named_lock

logger = get_logger("parts")

#: columns carrying per-part min/max pruning metadata (intersected
#: with the table schema; `timeInserted` drives retention/TTL,
#: flowStart/flowEnd drive the jobs' `select(start, end)` windows)
PRUNE_COLUMNS = ("timeInserted", "flowStartSeconds", "flowEndSeconds")

DEFAULT_MEMTABLE_ROWS = 65536
DEFAULT_PART_ROWS = 262144
DEFAULT_PARTITION_SECONDS = 3600
#: degenerate-interleaving guard: a seal never cuts more than this
#: many partition runs (heavily out-of-order data seals as one part;
#: min/max pruning stays correct, just less selective)
MAX_PARTS_PER_SEAL = 32

#: the ClickHouse-ORDER-BY equivalent: parts sort by these columns
#: (string columns by dictionary code — identical values still
#: cluster exactly)
DEFAULT_SORT_KEY = "timeInserted,destinationIP,sourceIP"
DEFAULT_GRANULE_ROWS = 8192
#: a granule's string set index is dropped (None = "no proof") once
#: its distinct-code count exceeds this — the ClickHouse set(N) cap
SET_INDEX_MAX = 128
#: v1 parts rewritten sorted+indexed per maintenance pass (bounds the
#: one-time upgrade cost of a large pre-PR-12 store per pass)
UPGRADES_PER_PASS = 4

#: part format versions (stamped per part in the manifest AND in the
#: part-file header): v1 = insertion order, no row-id, no indexes
#: (pre-PR-12); v2 = sorted by the part's sort key, carries the
#: __rowid__ permutation column, granule-indexed
PART_FORMAT_UNSORTED = 1
PART_FORMAT_SORTED = 2

MANIFEST_NAME = "manifest.json"

_PART_MAGIC = b"TPRT"
_PART_VERSION = PART_FORMAT_UNSORTED
_PART_VERSIONS = (PART_FORMAT_UNSORTED, PART_FORMAT_SORTED)
#: magic, version, crc algo, reserved, body crc, body length
_PART_HEADER = struct.Struct("<4sBBHIQ")

_M_SEALED = _metrics.counter(
    "theia_store_parts_sealed_total",
    "Memtable seals into immutable column parts")
_M_MERGES = _metrics.counter(
    "theia_store_merges_total",
    "Background compactions of adjacent small parts into larger ones")
_M_PRUNED = _metrics.counter(
    "theia_store_parts_pruned_total",
    "Parts skipped by select() min/max pruning (read with "
    "theia_store_parts_scanned_total for the prune ratio)")
_M_SCANNED = _metrics.counter(
    "theia_store_parts_scanned_total",
    "Parts decoded by scan()/select() after pruning")
_M_DEMOTED = _metrics.counter(
    "theia_store_parts_demoted_total",
    "Hot parts demoted to the cold (disk) tier by retention")
_M_UPGRADED = _metrics.counter(
    "theia_store_parts_upgraded_total",
    "Pre-PR-12 unsorted (format v1) parts rewritten sorted+indexed "
    "(format v2) by background maintenance")


class PartsError(Exception):
    """A part file or manifest failed structural/integrity checks."""


class PartsManifestError(PartsError):
    """The manifest generation paired with a snapshot is unloadable —
    the caller falls back to the previous snapshot generation."""


STORE_ENGINES = ("flat", "parts")


def default_store_engine() -> str:
    """THEIA_STORE_ENGINE, validated; `flat` until the parity gate
    flips the default (the THEIA_DETECTOR_ENGINE playbook)."""
    name = os.environ.get("THEIA_STORE_ENGINE", "").strip().lower()
    if not name:
        return "flat"
    if name not in STORE_ENGINES:
        raise ValueError(
            f"unknown store engine {name!r} (THEIA_STORE_ENGINE): "
            f"expected one of {STORE_ENGINES}")
    return name


def default_sort_key() -> Tuple[str, ...]:
    """THEIA_STORE_SORT_KEY parsed to a column tuple. An EMPTY value
    disables sorting entirely (parts seal in insertion order, format
    v1 — the pre-PR-12 behavior, kept reachable for cross-version
    tests and as the escape hatch)."""
    raw = os.environ.get("THEIA_STORE_SORT_KEY")
    if raw is None:
        raw = DEFAULT_SORT_KEY
    return tuple(c.strip() for c in raw.split(",") if c.strip())


# -- sparse primary index + per-granule skip indexes -----------------------

def _inverse_permutation(rowid: np.ndarray) -> np.ndarray:
    """inv with inv[rowid[i]] = i: `sorted.take(inv)` restores
    insertion order — the decode side of the row-id contract."""
    rid = np.asarray(rowid, np.int64)
    inv = np.empty(len(rid), np.int64)
    inv[rid] = np.arange(len(rid), dtype=np.int64)
    return inv


class PartIndexes:
    """Resident index metadata for one SORTED part (~0.2 B/row):

    * `starts` — row offset of each granule (every Nth row); with the
      sort order, the sort-key prefix's zone map is the MergeTree
      sparse primary index (granule g's key range is exactly
      [zone min, zone max], binary-searchable because ascending).
    * `zones` — per-granule (mins, maxs) for EVERY column: numeric
      columns over values, string columns over dictionary codes (only
      meaningful for pruning on the sort-key prefix, where codes are
      clustered; harmless elsewhere).
    * `sets` — per-granule sorted distinct dictionary codes for string
      columns, or None once a granule exceeds SET_INDEX_MAX distinct
      values (no proof → scanned).

    Survives demotion (indexes stay resident when chunks spill) but
    not recovery: a manifest-adopted part starts with indexes=None —
    scanned, not granule-pruned — and rebuilds them on hot promotion
    or upgrade, the same laziness as the chunks themselves."""

    __slots__ = ("granule", "rows", "starts", "zones", "sets")

    def __init__(self, granule: int, rows: int, starts: np.ndarray,
                 zones: Dict[str, Tuple[np.ndarray, np.ndarray]],
                 sets: Dict[str, List[Optional[np.ndarray]]]) -> None:
        self.granule = granule
        self.rows = rows
        self.starts = starts
        self.zones = zones
        self.sets = sets

    @property
    def n_granules(self) -> int:
        return len(self.starts)

    def granule_ends(self) -> np.ndarray:
        return np.append(self.starts[1:], self.rows)

    @property
    def nbytes(self) -> int:
        n = self.starts.nbytes
        for mins, maxs in self.zones.values():
            n += mins.nbytes + maxs.nbytes
        for per in self.sets.values():
            n += sum(s.nbytes for s in per if s is not None)
        return n


def build_part_indexes(schema, batch: ColumnarBatch, granule: int,
                       sort_key: Sequence[str]) -> PartIndexes:
    """Index one SORTED batch: one reduceat pass per column for the
    zone maps, one bounded np.unique per (granule, string column) for
    the set indexes."""
    n = len(batch)
    granule = max(1, int(granule))
    starts = np.arange(0, n, granule, dtype=np.int64)
    ends = np.minimum(starts + granule, n)
    zones: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    sets: Dict[str, List[Optional[np.ndarray]]] = {}
    for col in schema:
        arr = np.ascontiguousarray(batch[col.name])
        zones[col.name] = (np.minimum.reduceat(arr, starts),
                           np.maximum.reduceat(arr, starts))
        if col.is_string:
            per: List[Optional[np.ndarray]] = []
            for s, e in zip(starts, ends):
                u = np.unique(arr[s:e])
                per.append(u.astype(np.int32)
                           if len(u) <= SET_INDEX_MAX else None)
            sets[col.name] = per
    return PartIndexes(granule, n, starts, zones, sets)


def kway_merge_order(runs: Sequence[Sequence[np.ndarray]]
                     ) -> Optional[np.ndarray]:
    """Merge order for K individually-sorted runs of multi-column keys
    (each run a list of per-column arrays, primary column first) over
    their CONCATENATION. Returns None when the runs are already
    globally ordered end-to-end (the common case for time-ordered
    ingest: adjacent parts hold disjoint key ranges — the merge is a
    concat); otherwise one stable lexsort over the key columns only.
    Stability makes the result identical to sorting the insertion-
    order concatenation: within a run equal keys are already in
    insertion order, and runs concatenate in insertion order."""
    runs = [r for r in runs if len(r) and len(r[0])]
    if len(runs) <= 1:
        return None
    ordered = True
    for a, b in zip(runs, runs[1:]):
        last = tuple(c[-1] for c in a)
        first = tuple(c[0] for c in b)
        if last > first:
            ordered = False
            break
    if ordered:
        return None
    cols = [np.concatenate([r[j] for r in runs])
            for j in range(len(runs[0]))]
    return np.lexsort(tuple(reversed(cols)))


# -- column chunks (in-RAM encoded representation) -------------------------

class _NumChunk:
    """Width-reduced numeric column: stored (narrow) + base offset."""

    __slots__ = ("stored", "base", "dtype")

    def __init__(self, stored: np.ndarray, base: int, dtype) -> None:
        self.stored = stored
        self.base = base
        self.dtype = np.dtype(dtype)

    @property
    def nbytes(self) -> int:
        return self.stored.nbytes

    def decode(self) -> np.ndarray:
        if self.stored.dtype == self.dtype and not self.base:
            return self.stored
        arr = self.stored.astype(self.dtype)
        if self.base:
            arr += self.dtype.type(self.base)
        return arr


class _StrChunk:
    """Dictionary column in table-global code space: the part's unique
    global codes + narrow local indices. Decoding is one gather — no
    string work, so codes match the flat engine byte for byte."""

    __slots__ = ("uniq", "local")

    def __init__(self, uniq: np.ndarray, local: np.ndarray) -> None:
        self.uniq = uniq      # int32 global codes, ascending
        self.local = local    # u1/u2/int32 indices into uniq

    @property
    def nbytes(self) -> int:
        return self.uniq.nbytes + self.local.nbytes

    def decode(self) -> np.ndarray:
        if not len(self.uniq):
            return np.zeros(len(self.local), np.int32)
        return self.uniq[self.local.astype(np.int64)]


def _encode_chunks(schema, dicts, batch: ColumnarBatch
                   ) -> Dict[str, object]:
    """Seal one adopted (table-coded) batch into per-column chunks."""
    chunks: Dict[str, object] = {}
    for col in schema:
        arr = np.ascontiguousarray(batch[col.name])
        if col.is_string:
            codes = np.asarray(arr, np.int32)
            d = dicts[col.name]
            # O(n + dict) unique via occupancy mask (codes are dense
            # dictionary indices) — the WAL encoder's trick
            mask = np.zeros(len(d), bool)
            mask[codes] = True
            uniq = np.flatnonzero(mask).astype(np.int32)
            remap = np.cumsum(mask, dtype=np.int32) - 1
            local = remap[codes]
            if len(uniq) <= 0xFF:
                local = local.astype("<u1")
            elif len(uniq) <= 0xFFFF:
                local = local.astype("<u2")
            chunks[col.name] = _StrChunk(uniq, local)
        else:
            stored, base = _wal.width_reduce(arr)
            chunks[col.name] = _NumChunk(stored, base, col.host_dtype)
    return chunks


# -- part files (self-contained on-disk representation) --------------------

def write_part_file(path: str, table: str, batch: ColumnarBatch,
                    version: int = _PART_VERSION) -> int:
    """Write one part as a checksummed, SELF-CONTAINED file: header +
    the exact WAL record body (unique strings shipped), so the file
    decodes into any dictionary state — the property that makes parts
    shippable to replicas and reloadable across restarts. `version`
    stamps the part format (v2 = sorted rows + the __rowid__
    permutation column riding the record encoding as an ordinary
    numeric column — the body codec is unchanged). Buffered write;
    durability is the caller's (fsync at manifest publish — until
    then the WAL covers the rows). Returns bytes written."""
    parts = _wal.encode_record_parts(table, batch)
    body_len = sum(len(p) for p in parts)
    crc = 0
    for p in parts:
        crc = _wal._write_crc(p, crc)
    crc &= 0xFFFFFFFF
    with open(path, "wb") as f:
        f.write(_PART_HEADER.pack(_PART_MAGIC, version,
                                  _wal._WRITE_ALGO, 0, crc, body_len))
        for p in parts:
            f.write(p)
    return _PART_HEADER.size + body_len


def read_part_body(path: str) -> bytes:
    """The verified raw record BODY of a part file — already the exact
    self-contained WAL record encoding (write_part_file's contract), so
    cluster resync ships sealed cold parts without decoding a row."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise PartsError(f"part {path} unreadable: {e}")
    if len(data) < _PART_HEADER.size:
        raise PartsError(f"part {path}: short header")
    magic, ver, algo, _, crc, body_len = _PART_HEADER.unpack_from(
        data, 0)
    if magic != _PART_MAGIC or ver not in _PART_VERSIONS:
        raise PartsError(f"part {path}: bad magic/version")
    body = data[_PART_HEADER.size:]
    if len(body) != body_len:
        raise PartsError(
            f"part {path}: body is {len(body)} bytes, header says "
            f"{body_len}")
    crc_fn = _wal._checksum_fn(algo)
    if crc_fn is not None and (crc_fn(body, 0) & 0xFFFFFFFF) != crc:
        raise PartsError(f"part {path}: checksum mismatch")
    return body


def read_part_file(path: str,
                   columns: Optional[Sequence[str]] = None
                   ) -> ColumnarBatch:
    """Decode one part file (verifying the checksum) into a batch with
    fresh per-file dictionaries — the caller adopts it into table code
    space. Raises PartsError on any structural damage.

    `columns` restricts the decode to that subset: the other columns'
    byte ranges are skipped on disk (wal.decode_record_body) — the
    cold-tier read path for queries that touch a handful of the 52
    columns."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise PartsError(f"part {path} unreadable: {e}")
    if len(data) < _PART_HEADER.size:
        raise PartsError(f"part {path}: short header")
    magic, ver, algo, _, crc, body_len = _PART_HEADER.unpack_from(
        data, 0)
    if magic != _PART_MAGIC or ver not in _PART_VERSIONS:
        raise PartsError(f"part {path}: bad magic/version")
    body = data[_PART_HEADER.size:]
    if len(body) != body_len:
        raise PartsError(
            f"part {path}: body is {len(body)} bytes, header says "
            f"{body_len}")
    crc_fn = _wal._checksum_fn(algo)
    if crc_fn is not None and (crc_fn(body, 0) & 0xFFFFFFFF) != crc:
        raise PartsError(f"part {path}: checksum mismatch")
    try:
        _, batch = _wal.decode_record_body(
            body, None if columns is None else frozenset(columns))
    except _wal.WalCorruption as e:
        raise PartsError(f"part {path}: {e}")
    return batch


# -- parts ----------------------------------------------------------------

#: process-unique Part identities — the query-result cache fingerprints
#: the part SET with these (seal/merge/delete mint new Part objects,
#: demote flips the tier; either moves the fingerprint)
_part_uid = itertools.count(1)


class Part:
    """One immutable sealed part: row count + min/max pruning metadata
    always resident; column chunks resident on the hot tier, decoded
    on demand from the self-contained file on the cold tier.

    Format v2 parts additionally carry (hot tier) the `rowid` sort
    permutation and the granule `indexes`; rowid spills with the
    chunks on demotion (the file holds it), indexes stay resident —
    they are the pruning substrate and cost ~0.2 B/row."""

    __slots__ = ("rows", "minmax", "chunks", "path", "tier",
                 "file_bytes", "raw_bytes", "uid",
                 "fmt", "sort_key", "rowid", "indexes")

    def __init__(self, rows: int, minmax: Dict[str, Tuple[int, int]],
                 chunks: Optional[Dict[str, object]],
                 path: Optional[str] = None, tier: str = "hot",
                 file_bytes: int = 0, raw_bytes: int = 0,
                 fmt: int = PART_FORMAT_UNSORTED,
                 sort_key: Tuple[str, ...] = (),
                 rowid: Optional[np.ndarray] = None,
                 indexes: Optional[PartIndexes] = None) -> None:
        self.uid = next(_part_uid)
        self.rows = rows
        self.minmax = minmax
        self.chunks = chunks
        self.path = path
        self.tier = tier
        self.file_bytes = file_bytes
        self.raw_bytes = raw_bytes
        self.fmt = fmt
        self.sort_key = tuple(sort_key)
        self.rowid = rowid
        self.indexes = indexes

    @property
    def nbytes(self) -> int:
        """Resident (hot-tier) encoded bytes (chunks + the rowid
        permutation); a demoted part costs 0 — its tiny indexes are
        metadata, like minmax, and deliberately not charged."""
        if self.chunks is None:
            return 0
        n = sum(c.nbytes for c in self.chunks.values())
        if self.rowid is not None:
            n += self.rowid.nbytes
        return n

    def overlaps(self, start: Optional[int], end: Optional[int],
                 time_column: str, end_column: str) -> bool:
        """May this part hold rows with `time_column >= start AND
        end_column < end`? Missing metadata means 'maybe' (decode)."""
        if start is not None:
            mm = self.minmax.get(time_column)
            if mm is not None and mm[1] < start:
                return False
        if end is not None:
            mm = self.minmax.get(end_column)
            if mm is not None and mm[0] >= end:
                return False
        return True

    def manifest_entry(self) -> Dict[str, object]:
        entry: Dict[str, object] = {
            "file": os.path.basename(self.path) if self.path else None,
            "rows": self.rows,
            "tier": self.tier,
            "bytes": self.file_bytes,
            "rawBytes": self.raw_bytes,
            "minmax": {k: [int(v[0]), int(v[1])]
                       for k, v in self.minmax.items()},
        }
        if self.fmt != PART_FORMAT_UNSORTED:
            # fmt is OMITTED for v1 entries, so pre-PR-12 manifests
            # (which never carried the key) and v1 entries read the
            # same way: absent → unsorted
            entry["fmt"] = int(self.fmt)
            entry["sortKey"] = list(self.sort_key)
            if self.indexes is not None:
                entry["granule"] = int(self.indexes.granule)
        return entry


def _minmax_of(batch: ColumnarBatch,
               columns: Sequence[str]) -> Dict[str, Tuple[int, int]]:
    out: Dict[str, Tuple[int, int]] = {}
    for name in columns:
        if name in batch and len(batch):
            a = batch[name]
            out[name] = (int(a.min()), int(a.max()))
    return out


class PartTable(Table):
    """Part-backed drop-in for `Table`: same dictionaries, same insert
    path (WAL hook included), byte-identical scan/select results —
    rows live in sealed compressed parts + a small mutable memtable,
    in strict insertion order (so positional delete masks and
    flat-engine parity hold exactly)."""

    def __init__(self, name: str, schema,
                 directory: Optional[str] = None,
                 memtable_rows: Optional[int] = None,
                 part_rows: Optional[int] = None,
                 partition_seconds: Optional[int] = None,
                 time_column: str = "timeInserted",
                 sort_key: Optional[object] = None,
                 granule_rows: Optional[int] = None,
                 prune_columns: Optional[Sequence[str]] = None) -> None:
        super().__init__(name, schema)
        # part primary key: None → env default; "" / () disables
        # sorting (format v1, the pre-PR-12 layout). Columns the
        # schema lacks are dropped silently so one env value serves
        # every table shape.
        if sort_key is None:
            key = default_sort_key()
        elif isinstance(sort_key, str):
            key = tuple(c.strip() for c in sort_key.split(",")
                        if c.strip())
        else:
            key = tuple(sort_key)
        self.sort_key: Tuple[str, ...] = tuple(
            c for c in key if any(col.name == c for col in schema))
        self.granule_rows = max(1, (
            env_int("THEIA_STORE_GRANULE_ROWS", DEFAULT_GRANULE_ROWS)
            if granule_rows is None else int(granule_rows)))
        self.parts_upgraded = 0
        # Directory is EXPLICIT-ONLY at this level: the topology
        # wrappers (FlowDatabase / Sharded / Replicated) resolve
        # THEIA_STORE_COLD_DIR and suffix shard-NNN / replica-NNN —
        # two tables resolving the env var themselves would share one
        # directory, and the first save's GC would delete the other's
        # files.
        self.directory = directory or None
        self.memtable_rows = (
            env_int("THEIA_STORE_MEMTABLE_ROWS", DEFAULT_MEMTABLE_ROWS)
            if memtable_rows is None else int(memtable_rows))
        self.part_rows = (
            env_int("THEIA_STORE_PART_ROWS", DEFAULT_PART_ROWS)
            if part_rows is None else int(part_rows))
        self.partition_seconds = max(1, (
            env_int("THEIA_STORE_PARTITION_SECONDS",
                    DEFAULT_PARTITION_SECONDS)
            if partition_seconds is None else int(partition_seconds)))
        self.part_time_column = (time_column if any(
            c.name == time_column for c in schema) else None)
        # per-part min/max metadata columns: the flow defaults, or a
        # caller-supplied set (the `__metrics__` table tracks
        # `resolution` so queries prune rollup tiers and EXPLAIN can
        # name them); always intersected with the schema
        self._prune_columns = tuple(
            c for c in (PRUNE_COLUMNS if prune_columns is None
                        else tuple(prune_columns))
            if any(col.name == c for col in schema))
        #: sealed parts, strict insertion order; the memtable
        #: (self._batches, inherited) holds the unsealed tail
        self._parts: List[Part] = []
        self._memtable_len = 0
        self.parts_sealed = 0
        self.parts_merged = 0
        self.parts_merged_cold = 0
        self.parts_demoted = 0
        self.manifest_generation = 0
        #: part files written since the last manifest publish (fsynced
        #: there; until then the WAL carries the rows). Guarded by
        #: _fsync_lock: writers append from under the table lock (seal)
        #: AND outside it (merge, materialize), and the publish swap
        #: must not orphan a concurrent append — an entry lost here is
        #: a manifest referencing a never-fsynced file.
        self._pending_fsync: List[str] = []
        self._fsync_lock = named_lock("parts.fsync")
        #: basenames of files created but possibly not yet reachable
        #: through _parts (a merge building its replacement part) —
        #: the GC keep-set includes them so a concurrent save cannot
        #: collect a file mid-creation
        self._gc_guard: set = set()
        #: two-phase GC for never-published tables: files found
        #: unreferenced by one maintenance pass are only unlinked by
        #: the NEXT pass, so a reader that snapshotted parts just
        #: before a cold merge retired them keeps at least one full
        #: maintenance interval to finish streaming their files
        self._gc_candidates: set = set()
        #: basenames captured by an in-flight snapshot's manifest
        #: entries (set at capture, rolled into _manifest_files at
        #: publish) — the maintenance GC must not collect a file the
        #: about-to-publish generation references
        self._capture_keep: set = set()
        #: basenames referenced by the current + previous on-disk
        #: manifest generations — the file-GC keep set (lag-one, so
        #: the `.prev` snapshot's manifest stays loadable)
        self._manifest_files: List[set] = [set(), set()]
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            # protect files referenced by manifests a previous run
            # left here (we may be starting fresh beside them)
            for suffix, slot in ((".prev", 0), ("", 1)):
                files = self._read_manifest_files(
                    os.path.join(self.directory,
                                 MANIFEST_NAME + suffix))
                self._manifest_files[slot] |= files

    # -- bookkeeping -------------------------------------------------------

    @staticmethod
    def _read_manifest_files(path: str) -> set:
        try:
            with open(path) as f:
                doc = json.load(f)
            return {e["file"] for e in doc.get("parts", [])
                    if e.get("file")}
        except Exception:
            return set()

    def __len__(self) -> int:
        with self._lock:
            return (sum(p.rows for p in self._parts)
                    + self._memtable_len)

    @property
    def nbytes(self) -> int:
        """RESIDENT bytes: hot-part chunks + raw memtable. Cold parts
        cost disk, not RAM — retention's capacity denominator."""
        with self._lock:
            parts = list(self._parts)
            mem = list(self._batches)
        return (sum(p.nbytes for p in parts)
                + sum(v.nbytes for b in mem
                      for v in b.columns.values()))

    def _row_count_locked(self) -> int:
        return sum(p.rows for p in self._parts) + self._memtable_len

    # -- ingest ------------------------------------------------------------

    def _append_adopted(self, adopted: ColumnarBatch,
                        seal: bool = True) -> None:
        """Memtable append. The batch's column arrays are adopted BY
        REFERENCE — no copy between decode and memtable, which is the
        last leg of the TBLK zero-copy ingest path (the decoded block's
        arrays land here as-is; sealing re-encodes only when a part is
        cut). `seal=False` is the snapshot-restore path: recovery must
        not write fresh part files for rows the npz already holds — the
        next live insert seals normally."""
        nbytes = sum(a.nbytes for a in adopted.columns.values())
        with self._lock:
            self._batches.append(adopted)
            self._memtable_len += len(adopted)
            self.generation += 1
            self.rows_inserted_total += len(adopted)
            self.bytes_inserted_total += nbytes
            if seal and self._memtable_len >= self.memtable_rows:
                self._seal_locked()

    def _seal_locked(self) -> None:
        """Seal the memtable into one or more parts, cut at time-
        partition changes between CONSECUTIVE rows — insertion order
        is preserved exactly (the parity + positional-mask contract);
        out-of-order arrivals just produce more parts with overlapping
        ranges, which pruning handles via min/max."""
        if not self._batches:
            return
        batch = (self._batches[0] if len(self._batches) == 1
                 else ColumnarBatch.concat(self._batches))
        self._batches = []
        self._memtable_len = 0
        if not len(batch):
            return
        segments: List[ColumnarBatch] = [batch]
        if self.part_time_column is not None:
            pkey = (np.asarray(batch[self.part_time_column], np.int64)
                    // self.partition_seconds)
            cuts = np.flatnonzero(pkey[1:] != pkey[:-1]) + 1
            if 0 < len(cuts) < MAX_PARTS_PER_SEAL:
                bounds = [0, *cuts.tolist(), len(batch)]
                segments = [
                    batch.take(np.arange(bounds[i], bounds[i + 1]))
                    for i in range(len(bounds) - 1)]
        for seg in segments:
            self._parts.append(self._build_part(seg))
            self.parts_sealed += 1
            _M_SEALED.inc()

    def _build_part(self, batch: ColumnarBatch,
                    write_file: bool = True,
                    resident: bool = True,
                    presorted_rowid: Optional[np.ndarray] = None
                    ) -> Part:
        """Seal one adopted batch into a Part — sorted by the table's
        sort key (format v2, with the rowid permutation + granule
        indexes) unless sorting is disabled. `batch` is in INSERTION
        order, except when `presorted_rowid` is given: the k-way merge
        path hands an already-sorted batch plus its permutation, and
        the stable re-sort is skipped. `write_file=False` skips the
        on-disk copy — the delete paths rewrite parts while HOLDING
        the table lock, and disk I/O there would stall the ingest hot
        path; the next snapshot materializes missing files outside
        the lock (snapshot_parts_state). `resident=False` skips the
        in-RAM chunk encode — the cold-merge path, whose product goes
        straight to disk (indexes are built either way: they are the
        cold tier's pruning substrate)."""
        n = len(batch)
        fmt = PART_FORMAT_UNSORTED
        rowid: Optional[np.ndarray] = None
        indexes: Optional[PartIndexes] = None
        sbatch = batch
        if self.sort_key and n:
            fmt = PART_FORMAT_SORTED
            if presorted_rowid is not None:
                rowid = np.asarray(presorted_rowid, np.uint32)
            else:
                order = np.lexsort(tuple(
                    np.asarray(batch[c])
                    for c in reversed(self.sort_key)))
                rowid = order.astype(np.uint32)
                if not np.array_equal(order,
                                      np.arange(n, dtype=order.dtype)):
                    sbatch = batch.take(order)
            indexes = build_part_indexes(self.schema, sbatch,
                                         self.granule_rows,
                                         self.sort_key)
        chunks = (_encode_chunks(self.schema, self.dicts, sbatch)
                  if resident else None)
        minmax = _minmax_of(sbatch, self._prune_columns)
        raw = sum(a.nbytes for a in batch.columns.values())
        path = None
        file_bytes = 0
        if self.directory and write_file:
            path, file_bytes = self._write_file(sbatch, rowid)
        return Part(n, minmax, chunks, path=path,
                    file_bytes=file_bytes, raw_bytes=raw,
                    fmt=fmt,
                    sort_key=self.sort_key if fmt >= 2 else (),
                    rowid=rowid if (resident and fmt >= 2) else None,
                    indexes=indexes)

    def _write_file(self, batch: ColumnarBatch,
                    rowid: Optional[np.ndarray] = None
                    ) -> Tuple[str, int]:
        """`batch` is in FILE order; a non-None `rowid` appends the
        permutation column and stamps format v2."""
        path = os.path.join(
            self.directory, f"part-{uuid.uuid4().hex[:16]}.tprt")
        # guard BEFORE the write: a save's GC running mid-creation
        # must keep the half-written file
        self._gc_guard.add(os.path.basename(path))
        version = _PART_VERSION
        if rowid is not None:
            cols = dict(batch.columns)
            cols[_wal.ROWID_COLUMN] = np.asarray(rowid, np.int64)
            batch = ColumnarBatch(cols, batch.dicts)
            version = PART_FORMAT_SORTED
        file_bytes = write_part_file(path, self.name, batch,
                                     version=version)
        with self._fsync_lock:
            self._pending_fsync.append(path)
        return path, file_bytes

    def _materialize_part(self, part: Part) -> None:
        """Write the file for a fileless (delete-rewritten) part, in
        its native format (v2 parts write sorted rows + rowid from
        the resident chunks). Runs outside the table lock; the
        guarded swap tolerates a concurrent materializer or a racing
        delete — the losing file just becomes an unreferenced orphan
        the GC collects."""
        batch, rowid = self._decode_part_sorted(part, with_rowid=True)
        path, nbytes = self._write_file(batch, rowid)
        with self._lock:
            if part.path is None:
                part.path, part.file_bytes = path, nbytes
            else:
                self._gc_guard.discard(os.path.basename(path))

    def seal(self) -> None:
        """Force-seal the memtable (tests, bench)."""
        with self._lock:
            self._seal_locked()

    # -- external part surgery ---------------------------------------------

    def sealed_parts(self) -> List[Part]:
        """Point-in-time snapshot of the sealed-part list (the parts
        themselves are immutable). The public face for out-of-package
        maintenance (the metrics-history downsampler, obs/history.py)
        — part internals may move; this list and `replace_parts` are
        the contract."""
        with self._lock:
            return list(self._parts)

    def replace_parts(self, old: Sequence[Part],
                      rows: Sequence[Dict[str, object]]) -> bool:
        """Atomically swap the `old` sealed parts for ONE new part
        built from `rows` (row dicts in natural value space; empty →
        the old parts are simply dropped). This keeps the
        part-mutation invariants — build outside the lock, swap +
        generation bump under it, abort when a concurrent
        merge/demote already replaced any of `old` — IN this class,
        next to the merge/upgrade paths that share them. Readers are
        never caught between states: they see the old parts or the
        new one, never neither. Returns False on the concurrent-
        mutation abort (the caller retries against fresh state)."""
        new_part = None
        if rows:
            adopted = ColumnarBatch.from_rows(list(rows), self.schema,
                                              self.dicts)
            # fileless: an aborted swap must not leave an orphaned,
            # permanently-guarded part file behind — the published
            # part's file is materialized by snapshot/maintenance
            # outside the lock, like every hot rewrite product
            new_part = self._build_part(adopted, write_file=False)
        drop = set(map(id, old))
        with self._lock:
            present = {id(p) for p in self._parts}
            if not drop <= present:
                return False
            self._parts = [p for p in self._parts
                           if id(p) not in drop]
            if new_part is not None:
                self._parts.append(new_part)
            self.generation += 1
            for p in old:
                self._retire_file(p)
        return True

    # -- decode ------------------------------------------------------------

    def _decode_part(self, part: Part,
                     columns: Optional[Sequence[str]] = None
                     ) -> ColumnarBatch:
        """Part → ColumnarBatch in table code space, in INSERTION
        order (sorted v2 parts un-permute through their rowid — the
        contract every parity surface and positional delete mask
        stands on). Hot parts gather from resident chunks; tier-'hot'
        parts without chunks (lazy manifest recovery) decode their
        file once and promote; cold parts decode on demand and stay
        cold.

        `columns` restricts the decode to that subset: resident
        chunks gather only those columns, and a FILE decode skips the
        other columns' bytes on disk (plus the rowid column for a v2
        part — the un-permute needs it). A subset decode NEVER
        promotes (promotion needs every column) — a lazy hot part
        stays lazy, a cold part stays cold, which is exactly what a
        query that touches 4 of 52 columns wants."""
        chunks, rowid = self._resident_pair(part)
        if chunks is not None:
            names = list(columns) if columns is not None else \
                list(chunks)
            cols = {n: chunks[n].decode() for n in names}
            if rowid is not None:
                inv = _inverse_permutation(rowid)
                cols = {n: a[inv] for n, a in cols.items()}
            return ColumnarBatch(cols, self.dicts)
        adopted, rowid_arr = self._file_batch(part, columns)
        if part.tier == "hot" and columns is None:
            # promote in FILE (sorted) order; rowid + indexes first so
            # a racing insertion-order reader that sees the chunks
            # also sees the permutation (_resident_pair re-reads)
            if rowid_arr is not None:
                part.rowid = rowid_arr
                part.indexes = build_part_indexes(
                    self.schema, adopted, self.granule_rows,
                    part.sort_key or self.sort_key)
            part.chunks = _encode_chunks(self.schema, self.dicts,
                                         adopted)
        if rowid_arr is not None:
            adopted = adopted.take(_inverse_permutation(rowid_arr))
        return adopted

    def _decode_part_sorted(self, part: Part,
                            columns: Optional[Sequence[str]] = None,
                            with_rowid: bool = False):
        """Part → batch in FILE/chunk order (the part's SORT order for
        v2) — the query engine's granule-sliced view and the k-way
        merge's input. Never promotes, never un-permutes. Returns the
        batch, or (batch, rowid-or-None) when `with_rowid` (rowid is
        None for v1 parts)."""
        chunks, rowid = self._resident_pair(part)
        if chunks is not None:
            names = list(columns) if columns is not None else \
                list(chunks)
            batch = ColumnarBatch(
                {n: chunks[n].decode() for n in names}, self.dicts)
            return (batch, rowid) if with_rowid else batch
        want_rowid = with_rowid and part.fmt >= PART_FORMAT_SORTED
        batch, rowid_arr = self._file_batch(
            part, columns, want_rowid=want_rowid)
        return (batch, rowid_arr) if with_rowid else batch

    def _resident_pair(self, part: Part):
        """Race-consistent (chunks, rowid) snapshot of a part's
        resident state, taken lock-free against BOTH in-place
        transitions: DEMOTION clears chunks first then rowid, so
        reading rowid before chunks can't see chunks with the
        permutation already gone; lazy PROMOTION sets rowid before
        chunks, so observing fresh chunks with a stale rowid=None is
        repaired by one re-read. If a demotion races the re-read too,
        the file path (always present across either transition) is
        the safe answer — chunks reports None."""
        rowid = part.rowid
        chunks = part.chunks
        if chunks is not None and rowid is None and \
                part.fmt >= PART_FORMAT_SORTED:
            rowid = part.rowid
            if rowid is None:
                chunks = None
        return chunks, rowid

    def _file_batch(self, part: Part,
                    columns: Optional[Sequence[str]] = None,
                    want_rowid: bool = True
                    ) -> Tuple[ColumnarBatch, Optional[np.ndarray]]:
        """Decode a part's FILE into table code space, in FILE (sort)
        order: (adopted batch, rowid permutation or None for v1).
        `want_rowid=False` skips reading the rowid column's bytes on
        a subset decode that doesn't need the permutation."""
        if part.path is None:
            raise PartsError(
                f"part of {self.name} has neither resident chunks nor "
                f"a file (corrupted state)")
        read_cols = columns
        if columns is not None and want_rowid and \
                part.fmt >= PART_FORMAT_SORTED:
            read_cols = list(columns)
            if _wal.ROWID_COLUMN not in read_cols:
                read_cols.append(_wal.ROWID_COLUMN)
        raw = read_part_file(part.path, columns=read_cols)
        rowid_arr = raw.columns.pop(_wal.ROWID_COLUMN, None)
        batch = self._adopt(raw, columns=columns)
        return batch, (None if rowid_arr is None
                       else np.asarray(rowid_arr, np.uint32))

    def _snapshot_refs(self) -> Tuple[List[Part], List[ColumnarBatch]]:
        with self._lock:
            return list(self._parts), list(self._batches)

    def export_encoded_records(self, parts: Optional[List[Part]] = None,
                               mem: Optional[List[ColumnarBatch]] = None,
                               chunk_rows: int = 65536):
        """Yield self-contained WAL-record BODIES covering every row of
        this table — the cluster resync shipping format ("ship sealed
        parts, then the WAL tail"). COLD/lazy parts ship their file
        body verbatim (it IS the exact record body — zero decode),
        which for a sorted v2 part means the rows arrive in the part's
        SORT order (receivers drop the __rowid__ column at adoption);
        hot parts and the memtable encode their batches in insertion
        order. Cross-node row parity is therefore ORDER-INSENSITIVE
        by contract (the PR-12 oracle floor) — each node's own
        insertion order stays self-consistent, which is all the
        positional-delete machinery needs, but a resynced follower's
        row order may legitimately differ from its leader's. Pass
        refs captured under the caller's consistency
        latch; parts are immutable, so the refs stay valid after the
        latch releases (a raced maintenance GC unlinking a retired
        file falls back to the in-RAM decode path)."""
        if parts is None or mem is None:
            parts, mem = self._snapshot_refs()
        for p in parts:
            if p.chunks is None and p.path is not None:
                try:
                    yield read_part_body(p.path)
                    continue
                except PartsError:
                    pass   # fall through: _decode_part re-raises if
                           # the file is truly gone AND chunks is None
            yield _wal.encode_record_body(self.name,
                                          self._decode_part(p))
        for b in mem:
            for i in range(0, len(b), chunk_rows):
                idx = np.arange(i, min(i + chunk_rows, len(b)))
                yield _wal.encode_record_body(self.name, b.take(idx))

    def scan(self) -> ColumnarBatch:
        """Whole-table view, insertion order. Unlike the flat engine
        there is deliberately NO compaction side effect: the encoded
        parts ARE the resident representation."""
        parts, mem = self._snapshot_refs()
        if not parts and not mem:
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype)
                 for c in self.schema}, self.dicts)
        if parts:
            _M_SCANNED.inc(len(parts))
        batches = [self._decode_part(p) for p in parts] + mem
        if len(batches) == 1:
            return batches[0]
        return ColumnarBatch.concat(batches)

    def select(self, start_time: Optional[int] = None,
               end_time: Optional[int] = None,
               time_column: str = "flowStartSeconds",
               end_column: str = "flowEndSeconds",
               columns: Optional[Sequence[str]] = None
               ) -> ColumnarBatch:
        """Time-window select decoding ONLY parts whose min/max range
        overlaps the window — the pruned read path that makes keeping
        analytics in the store affordable. `columns` projects the
        result to that subset AND pushes the projection into the part
        decode: a pruned select over cold parts reads only those
        columns' bytes from disk (the window columns ride along for
        the mask, then drop out of the result)."""
        if start_time is None and end_time is None and columns is None:
            return self.scan()
        decode_cols = None
        if columns is not None:
            decode_cols = list(columns)
            for c in ((time_column,) if start_time is not None else ()
                      ) + ((end_column,) if end_time is not None
                           else ()):
                if c not in decode_cols:
                    decode_cols.append(c)
        parts, mem = self._snapshot_refs()
        live = [p for p in parts
                if p.overlaps(start_time, end_time, time_column,
                              end_column)]
        _M_PRUNED.inc(len(parts) - len(live))
        if live:
            _M_SCANNED.inc(len(live))
        out: List[ColumnarBatch] = []
        decoded = [self._decode_part(p, columns=decode_cols)
                   for p in live]
        if columns is not None:
            mem = [b.select(decode_cols) for b in mem]
        for batch in (decoded + mem):
            if not len(batch):
                continue
            mask = np.ones(len(batch), dtype=bool)
            if start_time is not None:
                mask &= batch[time_column] >= start_time
            if end_time is not None:
                mask &= batch[end_column] < end_time
            if columns is not None:
                batch = batch.select(columns)
            out.append(batch if mask.all() else batch.filter(mask))
        if not out:
            schema = (self.schema if columns is None else
                      [c for c in self.schema if c.name in columns])
            return ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype)
                 for c in schema}, self.dicts)
        return out[0] if len(out) == 1 else ColumnarBatch.concat(out)

    # -- deletes -----------------------------------------------------------

    def _retire_file(self, part: Part) -> None:
        """A dropped/rewritten part leaves its file ON DISK for the
        publish-time GC: an in-flight snapshot may have captured
        manifest entries referencing it moments ago, and the lag-one
        manifest pair may still need it — gc_part_files' keep-set is
        the single place that can decide removal safely. Here we only
        release the creation guard."""
        if part.path is not None:
            self._gc_guard.discard(os.path.basename(part.path))

    def _replacement_part(self, old: Part,
                          keep: ColumnarBatch) -> Part:
        """Survivor part for a boundary-straddling rewrite, SAME TIER
        as the original: a cold part's survivors go straight back to
        the cold tier (file written now — the decode already paid the
        disk read, and re-promoting retention's own rewrites would
        migrate the cold tier back into RAM); hot survivors stay
        resident and fileless until maintenance/snapshot materializes
        them outside the lock."""
        if old.tier == "cold" and self.directory:
            part = self._build_part(keep, write_file=True,
                                    resident=False)
            part.tier = "cold"
            return part
        return self._build_part(keep, write_file=False)

    def _rewrite_part_locked(self, idx: int,
                             keep: ColumnarBatch) -> None:
        """Replace part `idx` in place with the filtered survivor
        rows (or drop it when none survive)."""
        old = self._parts[idx]
        if len(keep):
            self._parts[idx] = self._replacement_part(old, keep)
        else:
            del self._parts[idx]
        self._retire_file(old)

    def _filter_memtable_locked(self, mask_of) -> int:
        """Filter every memtable batch by `mask_of(batch)` (a delete
        mask, or None/all-False to keep the batch untouched); rebuilds
        the memtable bookkeeping and returns rows deleted. The single
        memtable walk every delete path shares."""
        deleted = 0
        new_mem: List[ColumnarBatch] = []
        for b in self._batches:
            m = mask_of(b)
            if m is None or not m.any():
                new_mem.append(b)
                continue
            deleted += int(m.sum())
            kept = b.filter(~m)
            if len(kept):
                new_mem.append(kept)
        self._batches = new_mem
        self._memtable_len = sum(len(b) for b in new_mem)
        return deleted

    def _delete_where_locked(self, mask: np.ndarray) -> int:
        total = self._row_count_locked()
        if len(mask) != total:
            raise ValueError(
                f"mask length {len(mask)} != table length {total}")
        if total == 0 or not mask.any():
            return 0
        deleted = 0
        off = 0
        # forward walk with explicit offsets; collect rewrites first
        # so indices stay stable, then apply back-to-front
        rewrites: List[Tuple[int, Optional[ColumnarBatch]]] = []
        for i, part in enumerate(self._parts):
            sl = mask[off:off + part.rows]
            off += part.rows
            if not sl.any():
                continue
            deleted += int(sl.sum())
            if sl.all():
                rewrites.append((i, None))
            else:
                data = self._decode_part(part)
                rewrites.append((i, data.filter(~sl)))
        for i, keep in reversed(rewrites):
            if keep is None:
                old = self._parts.pop(i)
                self._retire_file(old)
            else:
                self._rewrite_part_locked(i, keep)

        def mem_mask(b):
            nonlocal off
            sl = mask[off:off + len(b)]
            off += len(b)
            return sl

        deleted += self._filter_memtable_locked(mem_mask)
        if deleted:
            self.generation += 1
        return deleted

    def delete_older_than(self, boundary: int,
                          column: str = "timeInserted") -> int:
        """`column < boundary` delete: whole parts wholly below the
        boundary DROP without decoding (the common retention case);
        only boundary-straddling parts pay a decode + rewrite."""
        deleted = 0
        with self._lock:
            kept_parts: List[Part] = []
            for part in self._parts:
                mm = part.minmax.get(column)
                if mm is not None and mm[0] >= boundary:
                    kept_parts.append(part)
                    continue
                if mm is not None and mm[1] < boundary:
                    deleted += part.rows
                    self._retire_file(part)
                    continue
                data = self._decode_part(part)
                mask = np.asarray(data[column]) < boundary
                n = int(mask.sum())
                if n == 0:
                    kept_parts.append(part)
                    continue
                deleted += n
                keep = data.filter(~mask)
                self._retire_file(part)
                if len(keep):
                    kept_parts.append(
                        self._replacement_part(part, keep))
            self._parts = kept_parts
            deleted += self._filter_memtable_locked(
                lambda b: np.asarray(b[column]) < boundary)
            if deleted:
                self.generation += 1
        return deleted

    def delete_ids(self, ids, column: str = "id",
                   invert: bool = False) -> int:
        """Value-based delete resolved through DICTIONARY CODES (no
        string materialization); parts whose unique-code set misses
        every target skip their decode entirely. Codes resolve under
        the table lock — see Table.delete_ids for the invert=True
        race this closes."""
        d = self.dicts[column]
        deleted = 0
        with self._lock:
            # unique, not just sorted: the per-part unique-code
            # intersection below passes assume_unique=True, and the
            # caller's id list may repeat
            codes = np.unique(np.asarray(
                [c for c in (d.lookup(str(s)) for s in ids)
                 if c is not None], np.int32))
            if not len(codes) and not invert:
                return 0
            rewrites: List[Tuple[int, Optional[ColumnarBatch]]] = []
            for i, part in enumerate(self._parts):
                chunk = part.chunks.get(column) \
                    if part.chunks is not None else None
                if (not invert and isinstance(chunk, _StrChunk)
                        and not np.isin(chunk.uniq, codes,
                                        assume_unique=True).any()):
                    continue   # provably no row matches — skip decode
                data = self._decode_part(part)
                mask = np.isin(np.asarray(data[column], np.int32),
                               codes)
                if invert:
                    mask = ~mask
                if not mask.any():
                    continue
                deleted += int(mask.sum())
                rewrites.append(
                    (i, None if mask.all() else data.filter(~mask)))
            for i, keep in reversed(rewrites):
                if keep is None:
                    old = self._parts.pop(i)
                    self._retire_file(old)
                else:
                    self._rewrite_part_locked(i, keep)

            def mem_mask(b):
                m = np.isin(np.asarray(b[column], np.int32), codes)
                return ~m if invert else m

            deleted += self._filter_memtable_locked(mem_mask)
            if deleted:
                self.generation += 1
        return deleted

    def time_bounds(self, columns=Table.TIME_BOUND_COLUMNS):
        """{column: (min, max)} from resident part metadata plus the
        (small) memtable — O(parts) per call, the cluster-heartbeat
        piggyback. A part missing metadata for a column makes that
        column unknown (omitted): peer pruning must never act on a
        bound that does not cover every row."""
        with self._lock:
            parts = list(self._parts)
            mem = list(self._batches)
        out = {}
        for col in columns:
            lo: Optional[int] = None
            hi: Optional[int] = None
            known = True
            for p in parts:
                mm = p.minmax.get(col)
                if mm is None:
                    known = False
                    break
                lo = mm[0] if lo is None else min(lo, mm[0])
                hi = mm[1] if hi is None else max(hi, mm[1])
            if not known:
                continue
            for b in mem:
                if col in b and len(b):
                    a = b[col]
                    lo = (int(a.min()) if lo is None
                          else min(lo, int(a.min())))
                    hi = (int(a.max()) if hi is None
                          else max(hi, int(a.max())))
            if lo is not None:
                out[col] = (int(lo), int(hi))
        return out

    def min_value(self, column: str = "timeInserted") -> Optional[int]:
        """O(parts) from metadata for pruning columns; decode fallback
        otherwise."""
        with self._lock:
            parts = list(self._parts)
            mem = list(self._batches)
        mins: List[int] = []
        decode: List[Part] = []
        for p in parts:
            mm = p.minmax.get(column)
            if mm is not None:
                mins.append(mm[0])
            else:
                decode.append(p)
        for p in decode:
            data = self._decode_part(p)
            if len(data):
                mins.append(int(data[column].min()))
        mins.extend(int(b[column].min()) for b in mem if len(b))
        return min(mins) if mins else None

    def truncate(self) -> None:
        with self._lock:
            for part in self._parts:
                self._retire_file(part)
            self._parts = []
            self._batches = []
            self._memtable_len = 0
            self.generation += 1

    # -- retention: O(parts) boundary + tiering ----------------------------

    def _retention_meta(self) -> List[Tuple[int, int, int, Callable]]:
        """(min, max, rows, fetch_time_column) per part/memtable batch
        — the O(parts) substrate for retention boundary selection
        (flow_store.boundary_from_meta)."""
        col = self.part_time_column or "timeInserted"
        with self._lock:
            parts = list(self._parts)
            mem = list(self._batches)
        out: List[Tuple[int, int, int, Callable]] = []
        for p in parts:
            mm = p.minmax.get(col)
            if mm is None:
                data = self._decode_part(p)
                if not len(data):
                    continue
                a = np.asarray(data[col])
                mm = (int(a.min()), int(a.max()))
            out.append((mm[0], mm[1], p.rows,
                        lambda p=p: np.asarray(
                            self._decode_part(p)[col])))
        for b in mem:
            if len(b):
                a = np.asarray(b[col])
                out.append((int(a.min()), int(a.max()), len(b),
                            lambda a=a: a))
        return out

    def retention_boundary(self, delete_n: int) -> Optional[int]:
        from .flow_store import boundary_from_meta
        return boundary_from_meta(self._retention_meta(), delete_n)

    def demote_oldest(self, target_bytes: int) -> int:
        """Demote hot parts — oldest first by min time — to the cold
        tier until resident bytes fall to `target_bytes`. A part
        without a file (no directory configured) cannot be demoted.
        Returns resident bytes freed."""
        freed = 0
        col = self.part_time_column or "timeInserted"
        with self._lock:
            resident = (sum(p.nbytes for p in self._parts)
                        + sum(v.nbytes for b in self._batches
                              for v in b.columns.values()))
            candidates = sorted(
                (p for p in self._parts
                 if p.tier == "hot" and p.chunks is not None
                 and p.path is not None),
                key=lambda p: p.minmax.get(col, (0, 0))[0])
            for part in candidates:
                if resident - freed <= target_bytes:
                    break
                freed += part.nbytes
                # tier BEFORE chunks: a lock-free reader (the query
                # engine) that observes chunks=None must also observe
                # tier=cold, or it would take the lazy-hot decode
                # path and promote the part we just demoted. chunks
                # BEFORE rowid: _decode_part reads rowid first, so it
                # can never see resident chunks whose permutation is
                # already gone. The granule indexes stay resident —
                # they are what lets cold queries keep pruning.
                part.tier = "cold"
                part.chunks = None
                part.rowid = None
                self.parts_demoted += 1
                _M_DEMOTED.inc()
        return freed

    # -- background compaction ---------------------------------------------

    def maintain(self) -> int:
        """One maintenance pass: merge runs of ADJACENT small parts in
        the same time partition (adjacency preserves global insertion
        order) — hot runs in RAM, cold runs on disk without
        re-promotion — upgrade a bounded number of pre-PR-12 v1
        parts to sorted+indexed v2 in place, materialize files for
        delete-rewritten parts, and — for tables that never publish
        a manifest (sharded/replicated shards, whose wholesale
        snapshots don't consult part files) — collect unreferenced
        files, which would otherwise accumulate forever since every
        delete defers its unlink to a publish-time GC that never runs
        there. Returns merges performed (upgrades count: a store with
        pending upgrades keeps its maintenance cadence busy)."""
        merges = self._merge_pass()
        if self.sort_key:
            merges += self._upgrade_pass()
        if self.directory:
            with self._lock:
                missing = [p for p in self._parts if p.path is None]
            for p in missing:
                self._materialize_part(p)
            if self.manifest_generation == 0 and \
                    not self._manifest_files[0] and \
                    not self._manifest_files[1]:
                self._gc_unpublished()
        return merges

    def _merge_pass(self) -> int:
        merges = 0
        for tier in ("hot", "cold"):
            if tier == "cold" and not self.directory:
                continue   # cold parts live in files — nothing to do
            while True:
                run = self._find_merge_run(tier)
                if run is None:
                    break
                if self._merge_run(run, tier):
                    merges += 1
                else:
                    break
        return merges

    def _kway_merged(self, refs: List[Part]
                     ) -> Tuple[ColumnarBatch, np.ndarray]:
        """K-way streaming merge of a run of SORTED parts: decode each
        part in its sort order (no un-permute, no re-sort), compute
        the merge order from the sort-key columns only (already-
        ordered runs concatenate for free — kway_merge_order), and
        carry the rowid permutations through with each part's rows
        offset by its predecessors' row counts, so the merged part's
        insertion order is exactly the concatenation of the sources'.
        Returns (merged sorted batch, merged rowid)."""
        batches: List[ColumnarBatch] = []
        rowids: List[np.ndarray] = []
        off = 0
        for p in refs:
            b, rid = self._decode_part_sorted(p, with_rowid=True)
            if rid is None:
                raise PartsError(
                    f"part of {self.name} claims format v2 but has "
                    f"no rowid permutation")
            batches.append(b)
            rowids.append(np.asarray(rid, np.int64) + off)
            off += p.rows
        order = kway_merge_order(
            [[np.asarray(b[c]) for c in self.sort_key]
             for b in batches])
        merged = ColumnarBatch.concat(batches)
        rowid = np.concatenate(rowids)
        if order is not None:
            merged = merged.take(order)
            rowid = rowid[order]
        return merged, rowid.astype(np.uint32)

    def _upgrade_pass(self) -> int:
        """Rewrite up to UPGRADES_PER_PASS format-v1 parts as sorted+
        indexed v2, tier preserved (a cold v1 part rewrites straight
        to disk, never promoting a byte). The path old stores take to
        granule pruning without an explicit migration step. Same
        guarded-swap discipline as _merge_run: the rebuild happens
        outside the lock, and a part a concurrent delete already
        replaced just leaves an orphan file for the GC."""
        with self._lock:
            candidates = [p for p in self._parts
                          if p.fmt < PART_FORMAT_SORTED and p.rows
                          and (p.tier == "hot" or self.directory)
                          ][:UPGRADES_PER_PASS]
        upgraded = 0
        for old in candidates:
            batch = self._decode_part(old)      # insertion order
            hot = old.tier == "hot"
            new_part = self._build_part(
                batch, write_file=not hot, resident=hot)
            new_part.tier = old.tier
            with self._lock:
                try:
                    i = self._parts.index(old)
                except ValueError:
                    i = -1
                if i >= 0:
                    self._parts[i] = new_part
            if i < 0:
                self._retire_file(new_part)
                continue
            self._retire_file(old)
            self.parts_upgraded += 1
            upgraded += 1
            _M_UPGRADED.inc()
        return upgraded

    def _merge_run(self, refs: List[Part], tier: str) -> bool:
        """Compact one run into a single part of the SAME tier. A cold
        run's replacement is written straight to disk and registered
        cold (chunks None) — a long-retention tier coalesces its tiny
        files WITHOUT re-promoting a byte into RAM; the source parts'
        transient decode is bounded by the run's row budget.

        A run of format-v2 parts sharing the table's sort key takes
        the K-WAY STREAMING path (_kway_merged); mixed or v1 runs
        fall back to concat+rebuild — which, with a sort key
        configured, produces a v2 part, i.e. merges UPGRADE old
        parts."""
        # decode + re-encode OUTSIDE the lock (parts are immutable);
        # swap in only if the run is still intact
        if self.sort_key and all(
                p.fmt >= PART_FORMAT_SORTED
                and p.sort_key == self.sort_key for p in refs):
            merged, rowid = self._kway_merged(refs)
            new_part = self._build_part(merged,
                                        resident=(tier == "hot"),
                                        presorted_rowid=rowid)
        else:
            merged = ColumnarBatch.concat(
                [self._decode_part(p) for p in refs])
            new_part = self._build_part(merged,
                                        resident=(tier == "hot"))
        if tier == "cold":
            new_part.tier = "cold"
        with self._lock:
            try:
                i = self._parts.index(refs[0])
            except ValueError:
                i = -1
            intact = (i >= 0 and
                      self._parts[i:i + len(refs)] == refs)
            if intact:
                self._parts[i:i + len(refs)] = [new_part]
        if not intact:
            # a concurrent delete rewrote the run — drop our merged
            # part; the next maintenance pass retries (bailing here
            # keeps a delete-heavy phase from pinning this pass in a
            # rebuild loop)
            self._retire_file(new_part)
            return False
        for p in refs:
            self._retire_file(p)
        self.parts_merged += 1
        if tier == "cold":
            self.parts_merged_cold += 1
        _M_MERGES.inc()
        return True

    def _find_merge_run(self, tier: str = "hot"
                        ) -> Optional[List[Part]]:
        """Leftmost run of >= 2 ADJACENT small same-partition parts of
        `tier` (adjacency preserves global insertion order). Hot runs
        compact resident chunks; cold runs compact the on-disk files a
        long-retention tier otherwise accumulates one tiny demotion at
        a time."""
        col = self.part_time_column
        with self._lock:
            small = self.part_rows // 2

            def pkey(p: Part) -> Optional[int]:
                if col is None:
                    return 0
                mm = p.minmax.get(col)
                return (None if mm is None
                        else mm[0] // self.partition_seconds)

            run: List[Part] = []
            total = 0
            for p in self._parts:
                mergeable = (p.tier == tier and p.rows < small
                             and pkey(p) is not None
                             and (tier == "hot"
                                  or p.path is not None))
                if (mergeable and run
                        and pkey(p) == pkey(run[0])
                        and total + p.rows <= self.part_rows):
                    run.append(p)
                    total += p.rows
                    continue
                if len(run) >= 2:
                    return list(run)
                run = [p] if mergeable else []
                total = p.rows if mergeable else 0
            return list(run) if len(run) >= 2 else None

    # -- manifest persistence ----------------------------------------------

    def snapshot_parts_state(self) -> Tuple[List[Dict[str, object]],
                                            Dict[str, np.ndarray]]:
        """Under the caller's quiesce window: (manifest entries for
        every sealed part, memtable columns payload). Requires a
        directory (every sealed part has a file)."""
        with self._lock:
            parts = list(self._parts)
            mem = list(self._batches)
        for p in parts:
            if p.path is None and self.directory:
                # delete-path rewrites skip the file write while they
                # hold the table lock; materialize here, outside it
                # (parts are immutable, so this needs no lock)
                self._materialize_part(p)
        entries = [p.manifest_entry() for p in parts]
        if any(e["file"] is None for e in entries):
            raise PartsError(
                f"table {self.name} has sealed parts without files — "
                f"manifest persistence needs a part directory")
        self._capture_keep = {e["file"] for e in entries if e["file"]}
        if mem:
            batch = mem[0] if len(mem) == 1 \
                else ColumnarBatch.concat(mem)
        else:
            batch = ColumnarBatch(
                {c.name: np.zeros(0, c.host_dtype)
                 for c in self.schema}, self.dicts)
        payload = {f"{self.name}/{c.name}": batch[c.name]
                   for c in self.schema}
        return entries, payload

    def publish_manifest(self, entries: List[Dict[str, object]],
                         stamp: Optional[int]) -> int:
        """Durably publish one manifest generation: fsync the part
        files it references, then atomically rotate
        manifest.json → manifest.json.prev and publish the new one
        (fsynced). Returns the generation id the paired snapshot must
        record."""
        if not self.directory:
            raise PartsError("publish_manifest needs a part directory")
        # locked swap: a concurrent merge appending a new file must
        # not land its entry on the orphaned list (a manifest could
        # then reference a never-fsynced file)
        with self._fsync_lock:
            pending, self._pending_fsync = self._pending_fsync, []
        try:
            for path in pending:
                fd = os.open(path, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
        except OSError as e:
            with self._fsync_lock:
                self._pending_fsync = pending + self._pending_fsync
            raise PartsError(f"part fsync failed: {e}")
        self.manifest_generation += 1
        gen = self.manifest_generation
        body = json.dumps({"parts": entries}, sort_keys=True)
        doc = {
            "table": self.name,
            "generation": gen,
            "stamp": int(stamp) if stamp is not None else None,
            "crc": zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF,
            "parts": entries,
        }
        path = os.path.join(self.directory, MANIFEST_NAME)
        tmp = path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        os.replace(tmp, path)
        self._manifest_files = [
            self._manifest_files[1],
            {e["file"] for e in entries if e["file"]},
        ]
        return gen

    def load_manifest(self, expected_gen: int) -> int:
        """Adopt the manifest generation paired with a loaded snapshot
        (manifest.json, else manifest.json.prev): register every part
        lazily (metadata resident, chunks decoded on first touch).
        Raises PartsManifestError when neither manifest matches or a
        referenced part file is missing/short — the caller falls back
        to the previous snapshot generation."""
        if not self.directory:
            raise PartsManifestError(
                "snapshot references a part manifest but no part "
                "directory is configured (THEIA_STORE_COLD_DIR)")
        primary = os.path.join(self.directory, MANIFEST_NAME)
        errors: List[str] = []
        for path in (primary, primary + ".prev"):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except FileNotFoundError:
                errors.append(f"{path}: missing")
                continue
            except Exception as e:
                errors.append(f"{path}: unreadable ({e})")
                continue
            if int(doc.get("generation", -1)) != int(expected_gen):
                errors.append(
                    f"{path}: generation {doc.get('generation')} != "
                    f"snapshot's {expected_gen}")
                continue
            body = json.dumps({"parts": doc.get("parts", [])},
                              sort_keys=True)
            if doc.get("crc") is not None and \
                    (zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF) \
                    != int(doc["crc"]):
                errors.append(f"{path}: parts-list checksum mismatch")
                continue
            try:
                parts = self._adopt_manifest_doc(doc)
            except PartsManifestError as e:
                errors.append(f"{path}: {e}")
                continue
            with self._lock:
                self._parts = parts
                self.manifest_generation = int(doc["generation"])
            if path != primary:
                logger.error(
                    "part manifest %s did not match snapshot "
                    "generation %d — recovered from the previous "
                    "manifest generation", primary, expected_gen)
                # Repair the slot state: park the orphan (newer or
                # corrupt) primary as *.orphaned and promote the
                # matched manifest back to the primary slot.
                # Otherwise the NEXT publish would rotate the orphan
                # into .prev, evicting this generation from both
                # slots while the paired snapshot still needs it —
                # one crash would silently void the .prev fallback.
                with contextlib.suppress(OSError):
                    os.replace(primary, primary + ".orphaned")
                with contextlib.suppress(OSError):
                    os.replace(path, primary)
                self._manifest_files = [
                    set(),
                    {e["file"] for e in doc.get("parts", [])
                     if e.get("file")},
                ]
            return sum(p.rows for p in parts)
        raise PartsManifestError(
            f"no loadable manifest for generation {expected_gen}: "
            + "; ".join(errors))

    def _adopt_manifest_doc(self, doc) -> List[Part]:
        parts: List[Part] = []
        for e in doc.get("parts", []):
            if not e.get("file"):
                raise PartsManifestError("manifest entry without file")
            path = os.path.join(self.directory, e["file"])
            try:
                size = os.path.getsize(path)
            except OSError:
                raise PartsManifestError(f"part file {path} missing")
            if size != int(e.get("bytes", size)):
                raise PartsManifestError(
                    f"part file {path} is {size} bytes, manifest "
                    f"says {e['bytes']} (torn write)")
            parts.append(Part(
                int(e["rows"]),
                {k: (int(v[0]), int(v[1]))
                 for k, v in (e.get("minmax") or {}).items()},
                None, path=path,
                tier=e.get("tier", "hot"),
                file_bytes=size,
                raw_bytes=int(e.get("rawBytes", 0)),
                # pre-PR-12 entries carry no fmt → v1: adopted
                # lazily, scanned, never granule-pruned, upgraded by
                # background merges. v2 entries decode through their
                # rowid; indexes rebuild on hot promotion.
                fmt=int(e.get("fmt", PART_FORMAT_UNSORTED)),
                sort_key=tuple(e.get("sortKey") or ())))
        with self._lock:
            self.rows_inserted_total += sum(p.rows for p in parts)
            self.bytes_inserted_total += sum(p.raw_bytes
                                             for p in parts)
        return parts

    def gc_part_files(self) -> int:
        """Remove part files referenced by NEITHER live parts nor the
        last two on-disk manifest generations (lag-one, mirroring the
        WAL segment GC: the `.prev` snapshot's manifest must stay
        loadable). Called after a successful manifest publish."""
        if not self.directory:
            return 0
        keep = self._gc_keep_set()
        keep |= self._manifest_files[0] | self._manifest_files[1]
        removed = self._unlink_except(keep)
        # the just-published generation covers the captured entries
        self._capture_keep = set()
        return removed

    def _gc_unpublished(self) -> int:
        """Maintenance GC for a table with NO manifest generations
        (part files are a cold-tier cache only, never a recovery
        source): retired files — including their never-to-be-drained
        pending-fsync entries — collect here, since the publish-time
        GC never runs. TWO-PHASE: a file is unlinked only once two
        consecutive passes found it unreferenced — a query that
        snapshotted the part list just before a cold merge retired a
        run must be able to finish streaming those files (readers are
        lock-free and hold no leases; one maintenance interval is the
        grace window)."""
        keep = self._gc_keep_set(include_pending=False)
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        unref = {n for n in names
                 if n.startswith("part-") and n.endswith(".tprt")
                 and n not in keep}
        doomed = unref & self._gc_candidates
        self._gc_candidates = unref - doomed
        removed = 0
        for name in doomed:
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(self.directory, name))
                removed += 1
        if removed:
            logger.v(1).info(
                "parts gc removed %d unreferenced part files under "
                "%s", removed, self.directory)
        with self._fsync_lock:
            self._pending_fsync = [
                p for p in self._pending_fsync
                if os.path.basename(p) in keep]
        return removed

    def _gc_keep_set(self, include_pending: bool = True) -> set:
        with self._lock:
            live = {os.path.basename(p.path) for p in self._parts
                    if p.path}
        # guard entries whose part reached _parts are covered by
        # `live` now; prune them so abandoned files don't linger
        self._gc_guard -= live
        keep = live | set(self._gc_guard) | set(self._capture_keep)
        if include_pending:
            with self._fsync_lock:
                keep |= {os.path.basename(p)
                         for p in self._pending_fsync}
        return keep

    def _unlink_except(self, keep: set) -> int:
        removed = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if not (name.startswith("part-")
                    and name.endswith(".tprt")):
                continue
            if name in keep:
                continue
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(self.directory, name))
                removed += 1
        if removed:
            logger.v(1).info("parts gc removed %d unreferenced part "
                             "files under %s", removed, self.directory)
        return removed

    # -- observability -----------------------------------------------------

    def parts_stats(self) -> Dict[str, object]:
        with self._lock:
            parts = list(self._parts)
            mem_rows = self._memtable_len
            mem_bytes = sum(v.nbytes for b in self._batches
                            for v in b.columns.values())
        hot = [p for p in parts if p.tier == "hot"]
        cold = [p for p in parts if p.tier != "hot"]
        indexed = [p for p in parts if p.indexes is not None]
        return {
            "count": len(parts),
            "hot": len(hot),
            "cold": len(cold),
            "hotBytes": sum(p.nbytes for p in hot),
            "coldBytes": sum(p.file_bytes for p in cold),
            "rows": sum(p.rows for p in parts),
            "memtableRows": mem_rows,
            "memtableBytes": mem_bytes,
            "sealed": self.parts_sealed,
            "merges": self.parts_merged,
            "coldMerges": self.parts_merged_cold,
            "demoted": self.parts_demoted,
            "sorted": sum(1 for p in parts
                          if p.fmt >= PART_FORMAT_SORTED),
            "upgraded": self.parts_upgraded,
            "sortKey": list(self.sort_key),
            "granuleRows": self.granule_rows,
            "indexedParts": len(indexed),
            "indexBytes": sum(p.indexes.nbytes for p in indexed),
            "granules": sum(p.indexes.n_granules for p in indexed),
            "generation": self.manifest_generation,
            "directory": self.directory,
        }

    def parts_debug_entries(self, limit: int = 256
                            ) -> List[Dict[str, object]]:
        """Per-part inspection rows for GET /debug/parts (bounded:
        a month-scale store can hold thousands of parts)."""
        col = self.part_time_column or "timeInserted"
        with self._lock:
            parts = list(self._parts)
        out: List[Dict[str, object]] = []
        for p in parts[:max(0, int(limit))]:
            idx = p.indexes
            entry: Dict[str, object] = {
                "uid": p.uid,
                "tier": p.tier,
                "fmt": p.fmt,
                "rows": p.rows,
                "residentBytes": p.nbytes,
                "fileBytes": p.file_bytes,
                "timeRange": list(p.minmax.get(col) or ()),
            }
            if idx is not None:
                entry["granules"] = idx.n_granules
                entry["granuleRows"] = idx.granule
                entry["indexBytes"] = idx.nbytes
            out.append(entry)
        return out


# -- supervised background compaction loop --------------------------------

class PartMaintenanceLoop:
    """Background driver for part compaction across a whole database
    (FlowDatabase / ShardedFlowDatabase / ReplicatedFlowDatabase — all
    expose `maintenance_tick()`), with the PR-2 supervision idioms: a
    failed pass backs off on the shared capped_backoff schedule
    instead of hammering a broken store; the first clean pass restores
    the cadence. Stats surface on /healthz under store.maintenance."""

    def __init__(self, db, interval: Optional[float] = None,
                 backoff_cap: float = 300.0) -> None:
        self.db = db
        self.interval = (
            env_float("THEIA_STORE_MERGE_INTERVAL", 5.0)
            if interval is None else float(interval))
        self.backoff_cap = backoff_cap
        self.rounds = 0
        self.merges = 0
        self.failures = 0
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-parts-merge")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        while not self._stop.wait(self.current_delay):
            self.run_once()

    def run_once(self) -> int:
        try:
            merged = int(self.db.maintenance_tick())
        except Exception as e:   # a bad pass must not kill the loop
            self.failures += 1
            self.consecutive_failures += 1
            self.current_delay = capped_backoff(
                max(self.interval, 0.001) * 2, self.backoff_cap,
                self.consecutive_failures)
            logger.error(
                "part maintenance pass failed (%d consecutive): %s; "
                "backing off %.1fs", self.consecutive_failures, e,
                self.current_delay)
            return 0
        if self.consecutive_failures:
            logger.info("part maintenance recovered after %d failed "
                        "passes", self.consecutive_failures)
        self.consecutive_failures = 0
        self.current_delay = self.interval
        self.rounds += 1
        self.merges += merged
        return merged

    def stats(self) -> Dict[str, object]:
        return {
            "rounds": self.rounds,
            "merges": self.merges,
            "failures": self.failures,
            "intervalSeconds": self.interval,
        }
