"""Concurrency correctness tooling.

Three cooperating passes keep the homegrown concurrent core honest the
way the reference's battle-tested engines (ClickHouse, Spark) are kept
honest by their own CI:

  * ``lockgraph`` — static AST lock-order analysis over the whole
    package: lock identification, held->acquired edge extraction (one
    level interprocedural), cycle + blocking-call-under-lock reports.
  * ``lockdep``  — the runtime witness: a named-lock factory adopted
    by every lock in the package which, under ``THEIA_LOCKDEP=1``,
    records per-thread held-sets and flags an order inversion the
    moment both orders have EVER been observed — no deadlock needed.
  * ``lint``     — the recurring review-hardening bug classes as
    mechanical checks (undeclared THEIA_* env reads, unregistered
    fault sites, bare/swallowed exceptions, raw clocks in
    injectable-clock modules).

Run the static passes with ``python -m theia_tpu.analysis``; tier-1
asserts a clean (zero unwaived findings) run via tests/test_analysis.py.

This ``__init__`` deliberately imports nothing: ``lockdep`` is imported
by every module in the package, so the package root must stay free of
heavyweight (or cyclic) imports.
"""
