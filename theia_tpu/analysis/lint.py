"""Repo lint — the recurring review-hardening bug classes, mechanized.

Ten PRs of review logs name the same four defect families over and
over; each is a pattern a machine can hold better than a reviewer:

  * ``undeclared-env``: a ``THEIA_*`` environment variable read in
    code (``os.environ.get/[]``, ``os.getenv``, ``env_int``,
    ``env_float``, local ``_env_int`` helpers) with no row in any
    docs/*.md knob table — an operator knob nobody can discover.
    This generalizes the PR-11 docdrift env gate (which covered four
    prefixes) to EVERY env access; tests/test_docdrift.py drives both
    directions from this pass's extraction.
  * ``unregistered-fault-site`` / ``stale-fault-site``: ``fire()``
    literals vs ``utils/faults.KNOWN_SITES``, both directions — a
    drill script must never arm a site that no longer fires.
  * ``bare-except`` / ``swallowed-except``: ``except:`` and broad
    ``except Exception: pass`` — the error-eating class every
    "review hardening" list has had an instance of.
  * ``raw-clock``: a direct ``time.time()``/``time.monotonic()`` call
    in a module that follows the injectable-clock convention (some
    function takes a ``clock`` parameter) — untestable time is how
    the PR-5 load-flake got in.

Run with the rest of the suite via ``python -m theia_tpu.analysis``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import Finding

#: docs knob-table rows: `| `THEIA_FOO` | default | meaning |`
_ENV_ROW = re.compile(r"^\|\s*`(THEIA_[A-Z0-9_]+)`", re.MULTILINE)

def _iter_py(package_dir: str, extra: Sequence[str] = ()
             ) -> List[Tuple[str, str]]:
    """(path, repo-relative) for every module in the package plus any
    ``extra`` files (bench.py reads knobs too)."""
    root = os.path.dirname(os.path.abspath(package_dir))
    out = []
    for dirpath, _d, filenames in sorted(os.walk(package_dir)):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                out.append((path, os.path.relpath(path, root)))
    for path in extra:
        if os.path.exists(path):
            out.append((path, os.path.relpath(
                path, root)))
    return out


# -- env knob extraction (shared with tests/test_docdrift.py) ------------

_ENV_NAME = re.compile(r"THEIA_[A-Z0-9_]+")


def _docstring_linenos(tree: ast.AST) -> Set[int]:
    """Line spans of module/class/function docstrings (mentioning a
    knob in prose is not a read)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef,
                             ast.FunctionDef, ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                d = body[0].value
                out.update(range(d.lineno,
                                 getattr(d, "end_lineno",
                                         d.lineno) + 1))
    return out


def extract_env_reads(package_dir: str, extra: Sequence[str] = ()
                      ) -> Dict[str, List[str]]:
    """Every ``THEIA_*`` name the code READS from the environment ->
    [file:line sites]. Two tiers, merged: direct reads (env access
    calls with a literal name) and indirect references (a THEIA_*
    name in any non-docstring string literal — knob names are also
    passed as DATA, e.g. ``sample_env="THEIA_TRACE_SAMPLE_INGEST"``
    or rollup tier tuples, and read through a variable later).
    Docstrings and comments never count."""
    reads: Dict[str, List[str]] = {}

    def note(name: str, rel: str, lineno: int) -> None:
        if name.startswith("THEIA_"):
            reads.setdefault(name, []).append(f"{rel}:{lineno}")

    for path, rel in _iter_py(package_dir, extra):
        with open(path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        doc_lines = _docstring_linenos(tree)
        for node in ast.walk(tree):
            # one tier suffices: the name literal inside ANY env
            # access call (`os.environ.get("X")`, `env_int("X", d)`,
            # `os.environ["X"]`) is itself an ast.Constant, so the
            # string sweep covers direct reads and names-as-data
            # identically
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.lineno not in doc_lines:
                for name in _ENV_NAME.findall(node.value):
                    note(name, rel, node.lineno)
    return reads


def documented_env_knobs(docs_dir: str) -> Dict[str, List[str]]:
    """THEIA_* names with a knob-table row in any docs/*.md ->
    [doc files]."""
    out: Dict[str, List[str]] = {}
    if not os.path.isdir(docs_dir):
        return out
    for fn in sorted(os.listdir(docs_dir)):
        if not fn.endswith(".md"):
            continue
        text = open(os.path.join(docs_dir, fn),
                    encoding="utf-8").read()
        for name in _ENV_ROW.findall(text):
            out.setdefault(name, []).append(fn)
    return out


# -- fault-site extraction -----------------------------------------------

def extract_fired_sites(package_dir: str
                        ) -> Dict[str, List[str]]:
    """Literal first args of ``fire(...)`` / ``_fire_fault(...)``
    calls -> sites. ``site#target`` entries normalize to the site."""
    fired: Dict[str, List[str]] = {}
    for path, rel in _iter_py(package_dir):
        with open(path, "r", encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        if rel.endswith("utils/faults.py"):
            continue                      # the registry itself
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            fname = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if fname not in ("fire", "_fire_fault"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value.partition("#")[0]
                fired.setdefault(site, []).append(
                    f"{rel}:{node.lineno}")
    return fired


# -- the pass ------------------------------------------------------------

class Lint:
    def __init__(self, package_dir: str, docs_dir: str,
                 extra: Sequence[str] = ()) -> None:
        self.package_dir = package_dir
        self.docs_dir = docs_dir
        self.extra = list(extra)

    def run(self) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_env())
        findings.extend(self._check_fault_sites())
        findings.extend(self._check_excepts_and_clocks())
        return findings

    def _check_env(self) -> List[Finding]:
        reads = extract_env_reads(self.package_dir, self.extra)
        documented = documented_env_knobs(self.docs_dir)
        findings = []
        for name in sorted(reads):
            if name not in documented:
                findings.append(Finding(
                    check="undeclared-env",
                    key=f"undeclared-env:{name}",
                    message=(f"{name} is read from the environment "
                             f"but has no knob-table row in any "
                             f"docs/*.md"),
                    site=reads[name][0],
                    detail=", ".join(reads[name][:5])))
        return findings

    def _check_fault_sites(self) -> List[Finding]:
        from ..utils.faults import KNOWN_SITES
        fired = extract_fired_sites(self.package_dir)
        findings = []
        for site in sorted(fired):
            if site not in KNOWN_SITES:
                findings.append(Finding(
                    check="unregistered-fault-site",
                    key=f"unregistered-fault-site:{site}",
                    message=(f"fault site {site!r} is fired but not "
                             f"registered in utils/faults.py "
                             f"KNOWN_SITES"),
                    site=fired[site][0]))
        for site in KNOWN_SITES:
            if site not in fired:
                findings.append(Finding(
                    check="stale-fault-site",
                    key=f"stale-fault-site:{site}",
                    message=(f"KNOWN_SITES entry {site!r} is never "
                             f"fired — a drill arming it would "
                             f"silently do nothing"),
                    site="theia_tpu/utils/faults.py"))
        return findings

    def _check_excepts_and_clocks(self) -> List[Finding]:
        findings = []
        for path, rel in _iter_py(self.package_dir):
            with open(path, "r", encoding="utf-8") as f:
                try:
                    tree = ast.parse(f.read(), filename=path)
                except SyntaxError:
                    continue
            has_clock_param = False
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    args = node.args
                    names = [a.arg for a in
                             args.posonlyargs + args.args
                             + args.kwonlyargs]
                    if "clock" in names:
                        has_clock_param = True
                        break
            func_of: Dict[int, str] = {}
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    for sub in ast.walk(node):
                        ln = getattr(sub, "lineno", None)
                        if ln is not None and ln not in func_of:
                            func_of[ln] = node.name

            def qual(node: ast.AST) -> str:
                return func_of.get(getattr(node, "lineno", 0),
                                   "<module>")

            for node in ast.walk(tree):
                if isinstance(node, ast.ExceptHandler):
                    if node.type is None:
                        findings.append(Finding(
                            check="bare-except",
                            key=f"bare-except:{rel}:{qual(node)}",
                            message=(f"bare `except:` in "
                                     f"{qual(node)} catches "
                                     f"KeyboardInterrupt/SystemExit "
                                     f"too"),
                            site=f"{rel}:{node.lineno}"))
                    elif _is_broad(node.type) and \
                            all(isinstance(s, (ast.Pass,
                                               ast.Continue))
                                for s in node.body):
                        findings.append(Finding(
                            check="swallowed-except",
                            key=(f"swallowed-except:{rel}:"
                                 f"{qual(node)}"),
                            message=(f"broad exception silently "
                                     f"swallowed in {qual(node)} — "
                                     f"a real bug here leaves no "
                                     f"trace"),
                            site=f"{rel}:{node.lineno}"))
                elif has_clock_param and isinstance(node, ast.Call):
                    fn = node.func
                    if isinstance(fn, ast.Attribute) and \
                            isinstance(fn.value, ast.Name) and \
                            fn.value.id == "time" and \
                            fn.attr in ("time", "monotonic"):
                        findings.append(Finding(
                            check="raw-clock",
                            key=(f"raw-clock:{rel}:{qual(node)}:"
                                 f"time.{fn.attr}"),
                            message=(
                                f"direct time.{fn.attr}() in "
                                f"{qual(node)} — this module "
                                f"follows the injectable-clock "
                                f"convention; wall-clock reads here "
                                f"are untestable"),
                            site=f"{rel}:{node.lineno}"))
        # dedup raw-clock repeats per (file, func, call)
        seen: Set[str] = set()
        uniq = []
        for f in findings:
            if f.key in seen:
                continue
            seen.add(f.key)
            uniq.append(f)
        return uniq


def _is_broad(type_node: Optional[ast.expr]) -> bool:
    names = []
    if isinstance(type_node, ast.Name):
        names = [type_node.id]
    elif isinstance(type_node, ast.Tuple):
        names = [e.id for e in type_node.elts
                 if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)
