"""Static lock-order analysis — the AST half of the lockdep story.

Walks every module in the package and builds the same acquisition-order
graph the runtime witness accumulates, but from SOURCE, so orderings
that no test exercises are still seen:

  1. **Lock identification.** Any attribute or module global assigned
     from ``named_lock/named_rlock/named_condition`` (the name literal
     is the lock class), a bare ``threading.Lock/RLock/Condition``
     call, or a ``_Latch(...)`` construction. Attribute locks are keyed
     per (class, attr); non-``self`` receivers resolve through an
     attr-name table with a receiver-name hint when two classes share
     the attr name.

  2. **Acquisition contexts.** ``with``-items (including conditional
     expressions and ``ExitStack.enter_context``), ``.acquire()``
     calls (non-blocking try-acquires are held but add NO order edge —
     a trylock cannot complete a deadlock cycle, and the ingest
     shards' opportunistic pattern would otherwise read as an
     inversion), latch ``.read()/.write()`` context calls, and
     context-returning methods (``quiesce``). One level
     interprocedural: a call made while holding L pulls the callee's
     own acquisitions in as L -> M edges (same-package resolution:
     ``self`` methods, module functions, package-unique method names).

  3. **Reports.** Cycles in the merged edge graph (the PR-14
     latch-inside-lock class), blocking calls under a lock (fsync,
     sleep, socket/HTTP, queue waits, future results, subprocess —
     the convoy makers), and torn multi-field transitions (the PR-12
     class: two attributes assigned in one locked block while another
     method of the class reads both without the lock).

Every report is checked against analysis/waivers.py; a waiver must
cite the invariant that makes the flagged code safe.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .base import Finding

#: constructors that make a lock; named_* carry the class name literal
_FACTORIES = ("named_lock", "named_rlock", "named_condition")
_BARE_LOCKS = ("Lock", "RLock", "Condition")

#: methods that RETURN a lock acquisition context (hand-curated repo
#: idioms; the latch read/write pair is handled structurally)
_CONTEXT_METHODS = {
    "quiesce": "wal.latch",          # WriteAheadLog.quiesce -> latch.write()
}

#: method names stdlib containers also carry: package-unique-name
#: call resolution must never claim these (a deque has .clear() too)
_STDLIB_METHODS = frozenset({
    "clear", "get", "put", "pop", "append", "appendleft", "extend",
    "update", "items", "keys", "values", "add", "remove", "discard",
    "close", "copy", "read", "write", "flush", "send", "recv",
    "sort", "join", "split", "strip", "index", "count", "insert",
    "reverse", "setdefault", "popitem", "encode", "decode", "format",
    "start", "stop", "run", "submit", "shutdown", "result", "done",
    "wait", "notify", "set", "reset", "acquire", "release", "open",
    "seek", "tell", "save", "load", "name", "match", "search",
})

#: blocking-call table: (dotted call, attr-call name, receiver hint)
#: — a curated list, not a taxonomy: these are the calls the repo's
#: review history caught sleeping/fsyncing/waiting under a lock
_BLOCKING_DOTTED = {
    "os.fsync", "os.fdatasync", "time.sleep",
    "urllib.request.urlopen", "socket.create_connection",
    "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "subprocess.call",
}
#: attr-call names blocking on ANY receiver
_BLOCKING_ATTRS = {"fsync", "fdatasync", "communicate", "getresponse",
                   "urlopen"}
#: attr-call names blocking only when the receiver source hints at the
#: right kind of object (queue waits, future results, thread joins)
_BLOCKING_HINTED = {
    "get": ("queue", "_q"),
    "put": ("queue", "_q"),
    "result": ("fut", "future"),
    "join": ("thread", "proc", "loop", "timer", "worker", "shipper"),
    "wait": ("event", "stop", "done", "ready"),
}


@dataclasses.dataclass
class _Acq:
    name: str            # lock class name
    site: str            # file:line
    blocking: bool       # False for try/timed acquires


@dataclasses.dataclass
class _FuncInfo:
    qual: str                                    # module:Class.func
    acquisitions: List[_Acq] = dataclasses.field(default_factory=list)
    #: (held lock, acquired lock, site) direct edges inside this func
    edges: List[Tuple[str, str, str]] = dataclasses.field(
        default_factory=list)
    #: (held lock, callee display, call key candidates, site)
    held_calls: List[Tuple[str, str, List[str], str]] = \
        dataclasses.field(default_factory=list)
    #: (held lock, blocking call name, site)
    blocking: List[Tuple[str, str, str]] = dataclasses.field(
        default_factory=list)


def _expr_src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return "?"


class _Module:
    def __init__(self, path: str, rel: str, modname: str) -> None:
        self.path = path
        self.rel = rel                   # repo-relative, for sites
        self.modname = modname           # theia_tpu.store.wal
        with open(path, "r", encoding="utf-8") as f:
            self.tree = ast.parse(f.read(), filename=path)
        self.imports: Dict[str, str] = {}    # alias -> module name
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom):
                # `from .x import y`, `from ..pkg import z as w`, and
                # the bare-relative form `from . import wal as _wal`
                # (module=None) — the package's most common aliasing
                if node.level:
                    parts = modname.split(".")
                    base = ".".join(
                        parts[:len(parts) - node.level]
                        + ([node.module] if node.module else []))
                elif node.module:
                    base = node.module
                else:
                    continue
                for a in node.names:
                    self.imports[a.asname or a.name] = \
                        f"{base}.{a.name}"


class LockGraph:
    """The whole-package analysis: construct with the package root,
    then ``run()`` for findings."""

    def __init__(self, package_dir: str,
                 modules: Optional[Sequence[_Module]] = None) -> None:
        self.package_dir = package_dir
        if modules is not None:
            self.modules = list(modules)
        else:
            self.modules = []
            root = os.path.dirname(os.path.abspath(package_dir))
            for dirpath, _dirnames, filenames in sorted(
                    os.walk(package_dir)):
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    rel = os.path.relpath(path, root)
                    mod = rel[:-3].replace(os.sep, ".")
                    if mod.endswith(".__init__"):
                        mod = mod[:-len(".__init__")]
                    self.modules.append(_Module(path, rel, mod))
        #: (module, class-or-None, attr) -> lock name
        self.locks: Dict[Tuple[str, Optional[str], str], str] = {}
        #: class name -> base class names (package classes only;
        #: single-level name resolution is enough for this codebase)
        self.bases: Dict[Tuple[str, str], List[str]] = {}
        #: attr -> {(class, lockname)} for non-self resolution
        self.attr_index: Dict[str, Set[Tuple[str, str]]] = {}
        #: function table + resolution indexes
        self.funcs: Dict[str, _FuncInfo] = {}
        self.module_funcs: Dict[Tuple[str, str], str] = {}
        self.class_methods: Dict[Tuple[str, str, str], str] = {}
        self.method_index: Dict[str, Set[str]] = {}
        #: merged order graph: (held, acquired) -> site
        self.graph: Dict[Tuple[str, str], str] = {}
        self.unresolved: List[str] = []

    # -- pass 1: lock identification ------------------------------------

    def _lock_name_from_call(self, call: ast.Call,
                             mod: _Module,
                             owner: Optional[str],
                             attr: str) -> Optional[str]:
        fn = call.func
        fname = None
        if isinstance(fn, ast.Name):
            fname = fn.id
        elif isinstance(fn, ast.Attribute):
            fname = fn.attr
        if fname in _FACTORIES:
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                return call.args[0].value
            return f"{mod.modname}.{owner or ''}.{attr}".replace(
                "..", ".")
        if fname in _BARE_LOCKS and isinstance(fn, ast.Attribute) \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "threading":
            return f"{mod.modname}.{owner or ''}.{attr}".replace(
                "..", ".")
        if fname == "_Latch":
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                return call.args[0].value
            return "wal.latch"
        return None

    def _collect_locks(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self.bases[(mod.modname, node.name)] = [
                        b.id if isinstance(b, ast.Name) else b.attr
                        for b in node.bases
                        if isinstance(b, (ast.Name, ast.Attribute))]
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Assign):
                            targets = sub.targets
                            value = sub.value
                        elif isinstance(sub, ast.AnnAssign):
                            targets = [sub.target]
                            value = sub.value
                        else:
                            continue
                        if not isinstance(value, ast.Call):
                            continue
                        for tgt in targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                name = self._lock_name_from_call(
                                    value, mod, node.name,
                                    tgt.attr)
                                if name:
                                    self.locks[(mod.modname,
                                                node.name,
                                                tgt.attr)] = name
                                    self.attr_index.setdefault(
                                        tgt.attr, set()).add(
                                        (node.name, name))
            for node in mod.tree.body:       # module-level globals
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            name = self._lock_name_from_call(
                                node.value, mod, None, tgt.id)
                            if name:
                                self.locks[(mod.modname, None,
                                            tgt.id)] = name
                                self.attr_index.setdefault(
                                    tgt.id, set()).add(("", name))

    # -- pass 2: per-function acquisition extraction --------------------

    def _resolve_lock_expr(self, expr: ast.AST, mod: _Module,
                           cls: Optional[str],
                           local_hints: Dict[str, str]
                           ) -> Optional[Tuple[str, bool]]:
        """Resolve an expression that *denotes a lock object* to its
        lock name. Returns (name, certain)."""
        if isinstance(expr, ast.Name):
            hit = self.locks.get((mod.modname, None, expr.id))
            if hit:
                return hit, True
            hint = local_hints.get(expr.id)
            if hint:
                return hint, True
            return self._resolve_attr_name(expr.id, expr.id)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and cls is not None:
                hit = self._class_lock(mod.modname, cls, expr.attr)
                if hit:
                    return hit, True
                # self attr never seen assigned a lock in this class
                # OR its bases: fall through to the attr index
            recv = _expr_src(expr.value)
            return self._resolve_attr_name(expr.attr, recv)
        return None

    def _class_lock(self, modname: str, cls: str,
                    attr: str) -> Optional[str]:
        """(class, attr) lock lookup walking the base-class chain by
        name (PartTable inherits Table._lock)."""
        seen = set()
        queue = [(modname, cls)]
        while queue:
            m, c = queue.pop(0)
            if (m, c) in seen:
                continue
            seen.add((m, c))
            hit = self.locks.get((m, c, attr))
            if hit:
                return hit
            for base in self.bases.get((m, c), ()):
                # resolve the base by NAME across every module (class
                # names are unique in this package)
                for (bm, bc) in self.bases:
                    if bc == base:
                        queue.append((bm, bc))
                # a base with no own bases entry (no ClassDef found —
                # e.g. imported) still gets a direct lock probe
                for (lm, lc, la), _n in list(self.locks.items()):
                    if lc == base and la == attr:
                        return self.locks[(lm, lc, la)]
        return None

    def _resolve_attr_name(self, attr: str, receiver: str
                           ) -> Optional[Tuple[str, bool]]:
        """Non-self receiver: all classes owning ``attr`` as a lock;
        disambiguate by receiver-name hint."""
        cands = self.attr_index.get(attr)
        if not cands:
            return None
        if len(cands) == 1:
            return next(iter(cands))[1], True
        rl = receiver.lower().lstrip("_")
        # receiver named exactly like one candidate class wins outright
        # (`table._lock` -> Table, not DistributedTable)
        exact = [(c, n) for c, n in cands
                 if c and c.lower().lstrip("_") == rl]
        if len({n for _, n in exact}) == 1:
            return exact[0][1], True
        if len(rl) >= 3:        # 1-2 char receivers match everything
            hinted = [(c, n) for c, n in cands
                      if c and (rl in c.lower()
                                or c.lower().lstrip("_") in rl)]
            if len({n for _, n in hinted}) == 1:
                return hinted[0][1], True
        self.unresolved.append(f"{receiver}.{attr}")
        return None

    def _acquisitions_in_expr(self, expr: ast.AST, mod: _Module,
                              cls: Optional[str],
                              local_hints: Dict[str, str]
                              ) -> List[Tuple[str, bool]]:
        """Every lock acquisition denoted anywhere inside a with-item
        expression (handles IfExp, enter_context, read()/write(),
        bare lock references). Returns [(lock name, blocking)]."""
        out: List[Tuple[str, bool]] = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    if fn.attr in ("read", "write"):
                        r = self._resolve_lock_expr(
                            fn.value, mod, cls, local_hints)
                        if r is None:
                            src = _expr_src(fn.value)
                            if "latch" in src.lower():
                                r = ("wal.latch", False)
                        if r:
                            out.append((r[0], True))
                    elif fn.attr in _CONTEXT_METHODS:
                        out.append(
                            (_CONTEXT_METHODS[fn.attr], True))
                    elif fn.attr == "acquire":
                        r = self._resolve_lock_expr(
                            fn.value, mod, cls, local_hints)
                        if r:
                            out.append(
                                (r[0], _call_is_blocking(node)))
            elif isinstance(node, (ast.Name, ast.Attribute)):
                # bare lock reference as a context manager: any
                # reference that resolves through the lock tables IS a
                # lock (the tables hold nothing else)
                r = self._resolve_lock_expr(node, mod, cls,
                                            local_hints)
                if r is not None:
                    out.append((r[0], True))
        # dedup, keep first
        seen = set()
        uniq = []
        for name, blocking in out:
            if name not in seen:
                seen.add(name)
                uniq.append((name, blocking))
        return uniq

    def _collect_functions(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._analyze_function(
                                mod, node.name, item)
            for item in mod.tree.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._analyze_function(mod, None, item)

    def _analyze_function(self, mod: _Module, cls: Optional[str],
                          fn: ast.FunctionDef) -> None:
        qual = f"{mod.modname}:{cls + '.' if cls else ''}{fn.name}"
        info = _FuncInfo(qual=qual)
        self.funcs[qual] = info
        if cls is None:
            self.module_funcs[(mod.modname, fn.name)] = qual
        else:
            self.class_methods[(mod.modname, cls, fn.name)] = qual
            self.method_index.setdefault(fn.name, set()).add(qual)

        local_hints: Dict[str, str] = {}

        def site(node: ast.AST) -> str:
            return f"{mod.rel}:{getattr(node, 'lineno', 0)}"

        def note_hints(stmt: ast.stmt) -> None:
            # `latch = getattr(self.db, "_ingest_latch", None)` etc.:
            # remember which lock a local variable denotes
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                tgt = stmt.targets[0].id
                for sub in ast.walk(stmt.value):
                    attr = None
                    if isinstance(sub, ast.Attribute):
                        attr = sub.attr
                    elif isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        attr = sub.value
                    if attr:
                        cands = self.attr_index.get(attr)
                        if cands and len({n for _, n in cands}) == 1:
                            local_hints[tgt] = \
                                next(iter(cands))[1]
                            return

        def walk_block(stmts: Sequence[ast.stmt],
                       held: List[Tuple[str, bool]]) -> None:
            for stmt in stmts:
                note_hints(stmt)
                if isinstance(stmt, (ast.With, ast.AsyncWith)):
                    acquired: List[Tuple[str, bool]] = []
                    for item in stmt.items:
                        acqs = self._acquisitions_in_expr(
                            item.context_expr, mod, cls, local_hints)
                        for name, blocking in acqs:
                            # `with a, b:` acquires left-to-right:
                            # b is taken while a is held, so earlier
                            # items of the SAME statement are part of
                            # the held set for later ones
                            self._note_acquire(
                                info, held + acquired, name,
                                blocking, site(stmt))
                            acquired.append((name, blocking))
                    held_inner = held + acquired
                    # the with-expression itself may contain calls
                    # (enter_context targets resolved above); body:
                    walk_block(stmt.body, held_inner)
                    continue
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # nested defs analyzed separately (closures get
                    # conservative self-context)
                    self._analyze_function(mod, cls, stmt)
                    continue
                # statement-level acquire()/enter_context() that
                # holds for the REST of the block
                stmt_acqs = self._statement_acquisitions(
                    stmt, mod, cls, local_hints)
                if stmt_acqs:
                    for name, blocking in stmt_acqs:
                        self._note_acquire(info, held, name,
                                           blocking, site(stmt))
                    held = held + stmt_acqs
                # blocking calls + held-context calls
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        self._note_call(info, mod, cls, held, sub,
                                        site(sub))
                # recurse into compound statements
                for attr in ("body", "orelse", "finalbody",
                             "handlers"):
                    sub = getattr(stmt, attr, None)
                    if sub:
                        if attr == "handlers":
                            for h in sub:
                                walk_block(h.body, held)
                        else:
                            walk_block(sub, held)

        walk_block(fn.body, [])

    def _statement_acquisitions(self, stmt: ast.stmt, mod: _Module,
                                cls: Optional[str],
                                local_hints: Dict[str, str]
                                ) -> List[Tuple[str, bool]]:
        """`x.acquire(...)` / `stack.enter_context(lockish)` as a bare
        statement or in an if/assign: the lock stays held for the rest
        of the block (releases are not modeled — conservative)."""
        out: List[Tuple[str, bool]] = []
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr == "acquire":
                r = self._resolve_lock_expr(fn.value, mod, cls,
                                            local_hints)
                if r:
                    out.append((r[0], _call_is_blocking(node)))
            elif fn.attr == "enter_context" and node.args:
                out.extend(self._acquisitions_in_expr(
                    node.args[0], mod, cls, local_hints))
        return out

    def _note_acquire(self, info: _FuncInfo,
                      held: List[Tuple[str, bool]], name: str,
                      blocking: bool, site: str) -> None:
        info.acquisitions.append(_Acq(name, site, blocking))
        if blocking:
            for held_name, _b in held:
                if held_name != name:
                    info.edges.append((held_name, name, site))

    def _note_call(self, info: _FuncInfo, mod: _Module,
                   cls: Optional[str],
                   held: List[Tuple[str, bool]], call: ast.Call,
                   site: str) -> None:
        if not held:
            return
        fn = call.func
        display = _expr_src(fn)
        # blocking-call check
        blocked = None
        if isinstance(fn, ast.Attribute):
            dotted = display
            if dotted in _BLOCKING_DOTTED:
                blocked = dotted
            elif fn.attr in _BLOCKING_ATTRS:
                blocked = display
            elif fn.attr in _BLOCKING_HINTED:
                hints = _BLOCKING_HINTED[fn.attr]
                recv = _expr_src(fn.value).lower()
                if any(h in recv for h in hints):
                    blocked = display
        elif isinstance(fn, ast.Name) and fn.id in ("sleep",):
            blocked = fn.id
        if blocked:
            for held_name, _b in held:
                info.blocking.append((held_name, blocked, site))
            return
        # candidate callee keys for one-level expansion
        cands: List[str] = []
        if isinstance(fn, ast.Name):
            q = self.module_funcs.get((mod.modname, fn.id))
            if q:
                cands.append(q)
            else:
                target = mod.imports.get(fn.id)
                if target and target.startswith("theia_tpu"):
                    tmod, _, tfn = target.rpartition(".")
                    q = self.module_funcs.get((tmod, tfn))
                    if q:
                        cands.append(q)
        elif isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and \
                    fn.value.id == "self" and cls is not None:
                q = self.class_methods.get(
                    (mod.modname, cls, fn.attr))
                if q:
                    cands.append(q)
            elif isinstance(fn.value, ast.Name) and \
                    fn.value.id in mod.imports:
                target = mod.imports[fn.value.id]
                if target.startswith("theia_tpu"):
                    q = self.module_funcs.get((target, fn.attr))
                    if q:
                        cands.append(q)
            if not cands:
                # package-unique method name: one definition anywhere.
                # Container/stdlib method names are excluded — a deque
                # also has .clear(), so uniqueness proves nothing.
                owners = self.method_index.get(fn.attr, set())
                if len(owners) == 1 and \
                        not fn.attr.startswith("__") and \
                        fn.attr not in _STDLIB_METHODS:
                    cands.append(next(iter(owners)))
        if cands:
            for held_name, _b in held:
                info.held_calls.append(
                    (held_name, display, cands, site))

    # -- pass 3: merge + report -----------------------------------------

    def _merge_graph(self) -> None:
        for info in self.funcs.values():
            for held, acq, site in info.edges:
                self.graph.setdefault((held, acq), site)
            for held, display, cands, site in info.held_calls:
                for q in cands:
                    callee = self.funcs.get(q)
                    if callee is None:
                        continue
                    for acq in callee.acquisitions:
                        if acq.blocking and acq.name != held:
                            self.graph.setdefault(
                                (held, acq.name),
                                f"{site} via {display}() "
                                f"[{acq.site}]")

    def _cycles(self) -> List[List[str]]:
        """One representative cycle per SCC of the order graph."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.graph:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # Tarjan SCC
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            work = [(v, iter(sorted(adj[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(adj[w]))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(sorted(scc))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)

        cycles = []
        for scc in sccs:
            # walk one concrete cycle inside the SCC for the report
            start = scc[0]
            path = [start]
            seen = {start}
            node = start
            while True:
                nxts = [w for w in sorted(adj[node])
                        if w in scc and (w == start or w not in seen)]
                if not nxts:
                    break
                nxt = nxts[0]
                if nxt == start:
                    path.append(start)
                    break
                seen.add(nxt)
                path.append(nxt)
                node = nxt
            if len(path) > 1 and path[-1] == start:
                cycles.append(path)
            else:
                cycles.append(scc + [scc[0]])
        return cycles

    # -- torn-read check -------------------------------------------------

    def _torn_reads(self) -> List[Finding]:
        findings = []
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    findings.extend(
                        self._torn_reads_in_class(mod, node))
        return findings

    def _torn_reads_in_class(self, mod: _Module,
                             cls: ast.ClassDef) -> List[Finding]:
        has_lock = any((mod.modname, cls.name, a) in self.locks
                       for a in {attr for (m, c, attr) in self.locks
                                 if m == mod.modname
                                 and c == cls.name})
        if not has_lock:
            return []
        # writer side: >=2 distinct self attrs assigned in ONE locked
        # block of a non-__init__ method
        transitions: List[Tuple[str, Tuple[str, ...], str]] = []
        for item in cls.body:
            if not isinstance(item, ast.FunctionDef) or \
                    item.name == "__init__":
                continue
            for sub in ast.walk(item):
                if not isinstance(sub, (ast.With, ast.AsyncWith)):
                    continue
                if not _with_uses_lock(sub, mod, cls.name, self):
                    continue
                attrs = set()
                for s2 in ast.walk(sub):
                    if isinstance(s2, ast.Assign):
                        for tgt in s2.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) \
                                    and tgt.value.id == "self":
                                attrs.add(tgt.attr)
                attrs = {a for a in attrs
                         if (mod.modname, cls.name, a)
                         not in self.locks}
                if len(attrs) >= 2:
                    transitions.append(
                        (item.name, tuple(sorted(attrs)),
                         f"{mod.rel}:{sub.lineno}"))
        if not transitions:
            return []
        findings = []
        reported = set()
        for item in cls.body:
            if not isinstance(item, ast.FunctionDef) or \
                    item.name == "__init__":
                continue
            if item.name.endswith("_locked"):
                # repo convention: a *_locked method is CALLED with
                # the class lock held — its reads are not lock-free
                continue
            reads = _unlocked_attr_reads(item, mod, cls.name, self)
            for writer, attrs, wsite in transitions:
                if item.name == writer:
                    continue
                both = [a for a in attrs if a in reads]
                if len(both) >= 2:
                    pair = ",".join(sorted(both)[:3])
                    key = (f"torn-read:{mod.rel}:{cls.name}:"
                           f"{pair}")
                    if key in reported:
                        continue
                    reported.add(key)
                    findings.append(Finding(
                        check="torn-read",
                        key=key,
                        message=(
                            f"{cls.name}.{writer} transitions "
                            f"({pair}) under the lock but "
                            f"{cls.name}.{item.name} reads them "
                            f"with no lock held — a reader between "
                            f"the two writes sees a torn state"),
                        site=f"{mod.rel}:{item.lineno}",
                        detail=f"locked transition at {wsite}"))
        return findings

    # -- driver -----------------------------------------------------------

    def run(self) -> List[Finding]:
        self._collect_locks()
        self._collect_functions()
        self._merge_graph()
        findings: List[Finding] = []
        for cycle in self._cycles():
            canon = _canonical_cycle(cycle)
            sites = []
            for a, b in zip(cycle, cycle[1:]):
                sites.append(f"{a}->{b} @ "
                             f"{self.graph.get((a, b), '?')}")
            findings.append(Finding(
                check="lock-order-cycle",
                key=f"lock-order-cycle:{'->'.join(canon)}",
                message=(f"lock-order cycle "
                         f"{' -> '.join(cycle)} (deadlock the "
                         f"moment two threads interleave)"),
                site=self.graph.get((cycle[0], cycle[1]), "?")
                .split(" ")[0],
                detail="; ".join(sites)))
        seen_block = set()
        for info in self.funcs.values():
            for held, callname, site in info.blocking:
                relfile = site.split(":")[0]
                key = (f"blocking-under-lock:{relfile}:{held}:"
                       f"{callname}")
                if key in seen_block:
                    continue
                seen_block.add(key)
                findings.append(Finding(
                    check="blocking-under-lock",
                    key=key,
                    message=(f"{callname}() called while holding "
                             f"{held} — every waiter convoys behind "
                             f"this block"),
                    site=site,
                    detail=info.qual))
        findings.extend(self._torn_reads())
        return findings

    def edges_doc(self) -> List[Dict[str, str]]:
        return [{"held": a, "acquired": b, "site": s}
                for (a, b), s in sorted(self.graph.items())]


def _canonical_cycle(cycle: List[str]) -> List[str]:
    """Rotate so the lexicographically-smallest node leads (stable
    waiver keys no matter where the DFS entered the cycle)."""
    body = cycle[:-1] if len(cycle) > 1 and cycle[0] == cycle[-1] \
        else list(cycle)
    i = body.index(min(body))
    rot = body[i:] + body[:i]
    return rot + [rot[0]]


def _call_is_blocking(call: ast.Call) -> bool:
    """acquire(...) blocking-ness: False/0 first arg or blocking=False
    or a timeout kwarg → cannot complete a deadlock cycle."""
    if call.args:
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and not a0.value:
            return False
    for kw in call.keywords:
        if kw.arg == "blocking" and \
                isinstance(kw.value, ast.Constant) and \
                not kw.value.value:
            return False
        if kw.arg == "timeout":
            return False
    return True


def _with_uses_lock(w: ast.With, mod: _Module, cls: str,
                    lg: LockGraph) -> bool:
    for item in w.items:
        for node in ast.walk(item.context_expr):
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    (mod.modname, cls, node.attr) in lg.locks:
                return True
    return False


def _unlocked_attr_reads(fn: ast.FunctionDef, mod: _Module, cls: str,
                         lg: LockGraph) -> Set[str]:
    """self attrs READ in ``fn`` outside every with-lock block."""
    locked_spans: List[Tuple[int, int]] = []
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.With, ast.AsyncWith)) and \
                _with_uses_lock(sub, mod, cls, lg):
            locked_spans.append(
                (sub.lineno, getattr(sub, "end_lineno", sub.lineno)))

    def outside(lineno: int) -> bool:
        return not any(a <= lineno <= b for a, b in locked_spans)

    reads = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Attribute) and \
                isinstance(sub.ctx, ast.Load) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id == "self" and outside(sub.lineno):
            reads.add(sub.attr)
    return reads


def analyze_source(source: str, modname: str = "fixture",
                   rel: str = "fixture.py") -> List[Finding]:
    """Run the full pass over ONE in-memory module (test fixtures)."""
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as f:
        f.write(source)
        path = f.name
    try:
        mod = _Module(path, rel, modname)
    finally:
        os.unlink(path)
    lg = LockGraph(package_dir=".", modules=[mod])
    return lg.run()
