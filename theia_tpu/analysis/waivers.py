"""Versioned waiver file for the static analysis passes.

Every entry matches finding KEYS (``fnmatch`` glob against the stable
key, never line numbers) and MUST cite the invariant that makes the
flagged code safe — validation rejects a waiver whose ``invariant``
does not spell it out. A waiver that matches nothing is STALE and
fails the gate: the code it described has changed, so the file must
change with it.

Grammar:

    {"check": "<check name>",       # one of base.KNOWN_CHECKS
     "match": "<key glob>",         # fnmatch against Finding.key
     "invariant": "<why this specific code cannot deadlock/race/lose
                    the error — a reviewer should be able to FALSIFY
                    the sentence>"}

Waivers are reviewed like code: deleting the code a waiver covers
deletes the waiver (the stale check enforces it), and weakening an
invariant is a red flag in review.
"""

WAIVERS = [
    # -- blocking-under-lock ---------------------------------------------
    {
        "check": "blocking-under-lock",
        "match": "blocking-under-lock:theia_tpu/store/wal.py:"
                 "wal.io:os.fsync",
        "invariant": (
            "The io lock IS the durability serialization point: "
            "fsync must cover exactly the bytes appended under the "
            "same lock hold, or a concurrent append could be "
            "acknowledged against an fsync that never covered it. "
            "Appends overlap their (dominant) body-checksum work "
            "OUTSIDE this lock by design; only the write+fsync tail "
            "serializes, and the sync policy bounds how often."),
    },
    # -- torn-read -------------------------------------------------------
    {
        "check": "torn-read",
        "match": "torn-read:theia_tpu/cluster/node.py:ClusterNode:*",
        "invariant": (
            "Role transitions (promote/step_down) rebind each of "
            "role/term/leader/follower in single assignments under "
            "cluster.node. Every lock-free reader snapshots ONE "
            "attribute into a local, None-checks it, and tolerates "
            "staleness by protocol: a stale role answer yields a 307 "
            "redirect or ClusterStateError that the producer/peer "
            "retries, and step_down/promote re-validate role under "
            "the lock before acting. No reader dereferences a "
            "role-dependent attribute without its own None-check, so "
            "a torn (role, leader) pair cannot crash — it can only "
            "produce a retried refusal."),
    },
    {
        "check": "torn-read",
        "match": "torn-read:theia_tpu/store/wal.py:WriteAheadLog:"
                 "_dirty_records,_last_sync_t",
        "invariant": (
            "_policy_sync's lock-free read is a double-checked "
            "throttle: it only decides whether to CALL sync(), and "
            "sync() re-reads _dirty_records under the io lock before "
            "doing anything. A torn read can at worst schedule one "
            "extra no-op sync or delay one interval-policy sync by "
            "one append — both inside the policy's documented loss "
            "bound."),
    },
    {
        "check": "torn-read",
        "match": "torn-read:theia_tpu/store/wal.py:WriteAheadLog:"
                 "*synced_lsn*",
        "invariant": (
            "stats() is the /healthz monitoring surface: it reports "
            "point-in-time counters (last_lsn, synced_lsn, dirty "
            "counts) that are each written atomically (int rebinds "
            "under the io lock) and never fed back into control "
            "decisions. A scrape racing an append may see lsn N with "
            "synced N-1 for one render — monitoring staleness, not "
            "state corruption. The durability gate itself reads "
            "positions under the io lock via wal_position()."),
    },
    {
        "check": "torn-read",
        "match": "torn-read:theia_tpu/store/wal.py:WriteAheadLog:"
                 "_dirty_bytes,_dirty_records,last_lsn",
        "invariant": (
            "Same stats()-surface read as the synced_lsn waiver: "
            "single-assignment ints rebound under the io lock, read "
            "lock-free only to render /healthz numbers; no control "
            "path consumes the racy pair."),
    },
    # -- swallowed-except ------------------------------------------------
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/cli/__main__.py:"
                 "_urlopen",
        "invariant": (
            "Parsing the error BODY of an already-failed HTTP "
            "request: the fallback keeps the raw body as the detail "
            "string, so no information is lost — the except only "
            "guards against non-JSON error bodies, and the original "
            "HTTPError is re-raised as the CLI error taxonomy "
            "either way."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/cli/__main__.py:main",
        "invariant": (
            "BrokenPipeError cleanup: stdout's consumer (`| head`) "
            "is gone; close() can itself raise EPIPE on the "
            "already-broken stream. The handler exists precisely to "
            "exit 0 quietly — there is nobody left to report to."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/cluster/node.py:"
                 "handle_resync",
        "invariant": (
            "Best-effort term extraction from an inbound resync "
            "payload while this node still believes it leads: on "
            "parse failure term stays 0 and the code path falls "
            "through to raising ClusterStateError — the sender "
            "retries after the heartbeat settles who leads. Failing "
            "to parse can only REFUSE a resync, never accept a bad "
            "one."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/cluster/replication.py:"
                 "stats",
        "invariant": (
            "Monitoring surface: wal_position() can raise while the "
            "store is resyncing/closed; stats() reports pos=0 for "
            "that render instead of failing /healthz. The durability "
            "gate reads the position through its own locked path."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/cluster/transport.py:"
                 "close",
        "invariant": (
            "Teardown of pooled keep-alive sockets: close() on an "
            "already-reset connection raises in some stdlib paths; "
            "every socket in the list must still get its close "
            "attempt (stopping at the first failure would leak the "
            "rest), and the process is shutting the transport down "
            "— there is no caller to surface the error to."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/ingest/client.py:"
                 "parse_retry_after",
        "invariant": (
            "Parsing an optional retryAfterSeconds field out of a "
            "429 body: on any parse failure the function falls "
            "through to the integer Retry-After header and then the "
            "documented 1s default — the contract is 'best hint "
            "available', and a malformed hint must not turn a "
            "retryable 429 into a client crash."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/manager/api.py:"
                 "refresh_scrape_gauges",
        "invariant": (
            "Scrape-time store gauges with every replica down: the "
            "gauges go stale for that render but the rest of the "
            "registry must stay scrapeable — /metrics serving "
            "through an outage is a PR-3 review-hardening "
            "requirement with its own regression test."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/manager/stats.py:"
                 "device_infos",
        "invariant": (
            "Per-device memory-stats probe: CPU devices and some "
            "backends expose no memory_stats(); the info dict "
            "simply omits the memory fields for that device. The "
            "surrounding loop must report every OTHER device either "
            "way."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/obs/history.py:scrape",
        "invariant": (
            "refresh() re-evaluates scrape-time callback gauges "
            "before snapshotting the registry: a callback throwing "
            "(e.g. store momentarily closed) leaves that gauge's "
            "last value in the snapshot — stale scrape-time gauges "
            "beat a lost metrics-history tick, and the tick itself "
            "records counters/histograms regardless."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/query/engine.py:"
                 "table_fingerprints",
        "invariant": (
            "Fingerprinting every queryable table on a store that "
            "may predate one (an old snapshot without __metrics__): "
            "the absent table is omitted from the digest map, which "
            "is exactly the correct cache key for a store that "
            "cannot answer queries over it."),
    },
    {
        "check": "swallowed-except",
        "match": "swallowed-except:theia_tpu/store/flow_store.py:"
                 "wal_tail_tagged_records",
        "invariant": (
            "The demoted-leader tail walk decodes each surviving WAL "
            "record to re-ingest it through the new leader; a record "
            "that fails to decode (torn/corrupt tail past the "
            "checksum horizon) is skipped so the REST of the tail "
            "still re-ingests — the skipped batch was by definition "
            "never acknowledged durable with a valid frame, and "
            "dedup makes the re-post idempotent either way."),
    },
    # -- raw-clock -------------------------------------------------------
    {
        "check": "raw-clock",
        "match": "raw-clock:theia_tpu/store/wal.py:read:"
                 "time.monotonic",
        "invariant": (
            "The latch's lockdep-witness wait/hold measurement: it "
            "observes REAL wall contention for /debug/locks stats "
            "and is compiled out when THEIA_LOCKDEP is off. No test "
            "or control path consumes these durations; injecting a "
            "clock here would measure the injected clock, not the "
            "contention."),
    },
    {
        "check": "raw-clock",
        "match": "raw-clock:theia_tpu/store/wal.py:write:"
                 "time.monotonic",
        "invariant": (
            "Same witness measurement as the read() waiver: "
            "observability-only wall-clock timing of real latch "
            "contention, active only under THEIA_LOCKDEP, never "
            "consumed by tests or control logic."),
    },
]
