"""Runtime lockdep witness — named locks that prove ordering, not luck.

Every lock in the package is created through the factories here
(``named_lock`` / ``named_rlock`` / ``named_condition``), giving each
lock a stable *class name* ("store.table", "wal.io", ...). With
``THEIA_LOCKDEP`` unset the factories return the bare ``threading``
primitives — strictly zero cost, byte-for-byte the objects the code
always used. With ``THEIA_LOCKDEP=1`` they return witness wrappers
that:

  * record the per-thread held-set on every acquire/release;
  * accumulate a global acquisition-order graph (held-name ->
    acquired-name edges, recorded only for UNBOUNDED blocking
    acquires — a trylock or timed acquire cannot complete a deadlock
    cycle, and the opportunistic-acquire pattern the ingest shards use
    would otherwise read as an inversion);
  * flag an inversion the moment the graph gains a cycle — i.e. as
    soon as both orders have EVER been observed, no actual deadlock
    needed.  The whole tier-1 suite runs with the witness armed, so
    every test run doubles as a deadlock hunt;
  * keep per-lock contention and hold-time statistics (power-of-two
    bucket histograms, the obs/metrics bucket scheme) served on
    ``GET /debug/locks`` and as scrape-time gauges on ``/metrics``.

Cost discipline (the witness must stay armable in production):
inversion/edge detection is EXACT — that is the correctness core —
but the statistics are deliberately best-effort: counters are
maintained lock-free under the GIL (mutations on the acquire side are
additionally serialized by the user's own lock per instance; two
instances of the same class can race and very occasionally lose an
increment), and hold-time histograms are sampled 1-in-16 acquisitions
per lock instance (contended waits are always recorded — they are the
signal). p95s from sampled buckets converge for any lock hot enough
to matter.

Nested acquisition of two *instances* of the same lock class (a
sharded store walking its shard tables) is recorded as a self-edge
and reported in the stats doc, but is not an inversion: name-level
ordering cannot see instance order, the same reason Linux lockdep
requires nesting annotations for it.

This module imports ONLY the stdlib: every module in the package
imports it (that is the point), so it must sit below everything —
including obs/metrics, whose own locks are witnessed too.
"""

from __future__ import annotations

import contextlib
import math
import os
import sys
import threading
from time import monotonic as _mono
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "enabled", "named_lock", "named_rlock", "named_condition",
    "note_acquire", "note_release", "inversions", "order_edges",
    "stats", "stats_doc", "reset", "lock_names", "scoped",
    "register_name", "held_names",
]

#: power-of-two bucket bounds for wait/hold seconds: 2^k for k in
#: [_EXP_MIN, _EXP_MIN + _N_BUCKETS), ~1us .. ~16s, +Inf last — the
#: obs/metrics scheme, reimplemented locally because this module must
#: not import anything above the stdlib.
_EXP_MIN = -20
_N_BUCKETS = 25

#: hold-time sampling mask: record timing on acquisitions where
#: (per-name counter & MASK) == 1 — the first acquisition of a fresh
#: stats object is always sampled, so rarely-taken locks still get a
#: hold number
_SAMPLE_MASK = 15


def _bucket_index(value: float) -> int:
    if value <= 2.0 ** _EXP_MIN:
        return 0
    m, e = math.frexp(value)
    k = e - 1 if m == 0.5 else e
    idx = k - _EXP_MIN
    return idx if idx < _N_BUCKETS else _N_BUCKETS


def _bucket_quantile(counts: List[int], q: float) -> float:
    """Upper bucket bound at quantile ``q`` (0 when empty)."""
    n = sum(counts)
    if n == 0:
        return 0.0
    target = q * n
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        if acc >= target:
            return 2.0 ** (_EXP_MIN + min(i, _N_BUCKETS - 1))
    return 2.0 ** (_EXP_MIN + _N_BUCKETS - 1)


def enabled() -> bool:
    """Whether the witness is armed (checked at lock CREATION: already
    constructed locks keep whatever they were born as)."""
    return os.environ.get(
        "THEIA_LOCKDEP", "").strip().lower() in ("1", "true", "yes")


# -- global witness state ------------------------------------------------

class _LockStats:
    """Per-lock-class accounting. All fields mutated LOCK-FREE under
    the GIL (see the module docstring's cost discipline)."""

    __slots__ = ("n", "acquires", "contended", "wait_total",
                 "wait_max", "hold_total", "hold_max", "wait_buckets",
                 "hold_buckets")

    def __init__(self) -> None:
        self.n = 0                  # sampling counter
        self.acquires = 0
        self.contended = 0
        self.wait_total = 0.0
        self.wait_max = 0.0
        self.hold_total = 0.0
        self.hold_max = 0.0
        self.wait_buckets = [0] * (_N_BUCKETS + 1)
        self.hold_buckets = [0] * (_N_BUCKETS + 1)

    def note_wait(self, wait: float) -> None:
        self.contended += 1
        self.wait_total += wait
        if wait > self.wait_max:
            self.wait_max = wait
        self.wait_buckets[_bucket_index(wait)] += 1

    def note_hold(self, hold: float) -> None:
        self.hold_total += hold
        if hold > self.hold_max:
            self.hold_max = hold
        self.hold_buckets[_bucket_index(hold)] += 1

    def doc(self) -> Dict[str, object]:
        return {
            "acquires": self.acquires,
            "contended": self.contended,
            "waitTotalSeconds": self.wait_total,
            "waitMaxSeconds": self.wait_max,
            "waitP95Seconds": _bucket_quantile(
                self.wait_buckets, 0.95),
            "holdTotalSeconds": self.hold_total,
            "holdMaxSeconds": self.hold_max,
            "holdP95Seconds": _bucket_quantile(
                self.hold_buckets, 0.95),
            "holdSampled": True,
        }


#: held-name -> {acquired-name}: blocking acquisition-order edges.
#: Readers probe without the graph lock (GIL-atomic dict/set reads);
#: mutations (rare — first observation of an edge) serialize below.
_edges: Dict[str, Set[str]] = {}
#: (held, acquired) -> "file:line" of the acquire that minted the edge
_edge_sites: Dict[Tuple[str, str], str] = {}
#: inversion reports (cycle closed in the order graph)
_inversions: List[Dict[str, object]] = []
#: same-name nesting observations: name -> count
_self_edges: Dict[str, int] = {}
_stats: Dict[str, _LockStats] = {}
_graph_lock = threading.Lock()
#: every name the factories have minted (even before first acquire)
_known_names: Set[str] = set()
_tls = threading.local()


def _held() -> List[list]:
    """This thread's held stack: [owner_token, name, t_acquire, count]
    entries, outermost first (t_acquire 0.0 = hold timing unsampled
    for this acquisition)."""
    try:
        return _tls.held
    except AttributeError:
        _tls.held = []
        return _tls.held


def _caller_site() -> str:
    """file:line of the nearest frame outside the witness machinery —
    this module, contextlib (the latch's @contextmanager plumbing),
    and the ``_Latch`` read/write generators themselves, so a
    latch-closed edge names the CALLER that took the latch, not the
    latch implementation."""
    f = sys._getframe(1)
    while f is not None:
        mod = f.f_globals.get("__name__", "")
        if mod == __name__ or mod == "contextlib":
            f = f.f_back
            continue
        code = f.f_code
        if getattr(code, "co_qualname",
                   "").startswith("_Latch.") or (
                code.co_name in ("read", "write")
                and type(f.f_locals.get("self")).__name__
                == "_Latch"):
            f = f.f_back
            continue
        break
    if f is None:
        return "?"
    fn = f.f_code.co_filename
    for marker in ("theia_tpu", "tests"):
        i = fn.rfind(marker)
        if i >= 0:
            fn = fn[i:]
            break
    return f"{fn}:{f.f_lineno}"


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS path src -> dst over the order graph (graph lock held)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _add_edges(held: List[list], name: str) -> None:
    """Record held->name for every distinct held lock class. Called
    only for unbounded blocking acquires — the only kind that can
    complete a deadlock cycle. Fast path (edge already known) is one
    dict.get + set membership per held entry, no locks."""
    for entry in held:
        held_name = entry[1]
        if held_name == name:
            # same-class nesting: tracked, but name-level ordering
            # cannot adjudicate instance order (see module docstring)
            with _graph_lock:
                _self_edges[name] = _self_edges.get(name, 0) + 1
            continue
        peers = _edges.get(held_name)
        if peers is not None and name in peers:
            continue
        with _graph_lock:
            peers = _edges.setdefault(held_name, set())
            if name in peers:
                continue
            site = _caller_site()
            # Does acquiring `name` while holding `held_name` close a
            # cycle? Look for an existing path name -> ... -> held_name
            # BEFORE inserting, so the report names the exact inversion.
            path = _find_path(name, held_name)
            peers.add(name)
            _edge_sites[(held_name, name)] = site
            if path is not None:
                cycle = path + [name]
                _inversions.append({
                    "cycle": cycle,
                    "edge": [held_name, name],
                    "site": site,
                    "priorSites": {
                        f"{a}->{b}": _edge_sites.get((a, b), "?")
                        for a, b in zip(path, path[1:])},
                    "thread": threading.current_thread().name,
                })
                msg = (f"lockdep: lock-order inversion: "
                       f"{' -> '.join(cycle)} (new edge "
                       f"{held_name} -> {name} at {site})")
                print(msg, file=sys.stderr)
                if os.environ.get("THEIA_LOCKDEP_RAISE", "") == "1":
                    raise RuntimeError(msg)


def _stats_for(name: str) -> _LockStats:
    s = _stats.get(name)
    if s is None:
        with _graph_lock:
            s = _stats.get(name)
            if s is None:
                s = _stats[name] = _LockStats()
    return s


def check_before_acquire(token: object, name: str) -> None:
    """Order validation for a blocking acquire, run BEFORE the
    underlying primitive is taken: the held->name edges exist the
    moment the attempt blocks, and — critically — a
    ``THEIA_LOCKDEP_RAISE=1`` inversion raises here with NOTHING
    acquired, so the error propagates cleanly instead of wedging the
    half-taken lock/latch for every later acquirer. The post-acquire
    bookkeeping finds the edges already present (one dict probe) and
    never re-raises."""
    held = _held()
    for entry in held:
        if entry[0] is token:             # reentrant: no new edges
            return
    if held:
        _add_edges(held, name)


def note_acquire(token: object, name: str, *, blocking: bool = True,
                 wait: float = 0.0, contended: bool = False) -> None:
    """Record that this thread now holds the lock/region ``name``
    (identified by ``token`` — the same object must be passed to
    ``note_release``). Non-lock blocking regions (the ingest latch)
    and the RLock wrapper integrate through this pair; the plain-Lock
    wrapper inlines an equivalent fast path."""
    held = _held()
    for entry in held:
        if entry[0] is token:             # reentrant (RLock) acquire
            entry[3] += 1
            return
    if blocking and held:
        _add_edges(held, name)
    st = _stats.get(name)
    if st is None:
        st = _stats_for(name)
    st.acquires += 1
    st.n += 1
    if contended:
        st.note_wait(wait)
    t0 = _mono() if (st.n & _SAMPLE_MASK) == 1 or contended else 0.0
    held.append([token, name, t0, 1])


def note_release(token: object, name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        entry = held[i]
        if entry[0] is token:
            entry[3] -= 1
            if entry[3] == 0:
                del held[i]
                if entry[2]:
                    st = _stats.get(name)
                    if st is not None:
                        st.note_hold(_mono() - entry[2])
            return
    # release of a never-noted token (e.g. lockdep armed between
    # acquire and release in a test): ignore rather than corrupt state


# -- witness wrappers ----------------------------------------------------

class _WitnessLock:
    """threading.Lock wrapper feeding the witness. Context-manager and
    acquire/release compatible; not reentrant (matching Lock — a
    same-thread re-acquire blocks exactly like the bare primitive, so
    the held-stack never needs a reentrancy scan here)."""

    __slots__ = ("_lock", "name", "_st")

    def __init__(self, name: str) -> None:
        self._lock = threading.Lock()
        self.name = name
        self._st = _stats_for(name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and timeout < 0:
            st = self._st
            held = _held()
            if held:
                # order validation BEFORE taking the lock: a raise
                # (THEIA_LOCKDEP_RAISE) must leave nothing acquired
                _add_edges(held, self.name)
            # uncontended fast path: a trylock that succeeds costs no
            # clock read; only a contended acquire times its wait
            if not self._lock.acquire(False):
                t0 = _mono()
                self._lock.acquire()
                st.note_wait(_mono() - t0)
                sampled = True
            else:
                sampled = False
            st.acquires += 1
            n = st.n = st.n + 1
            held.append([
                self, self.name,
                _mono() if sampled or (n & _SAMPLE_MASK) == 1
                else 0.0, 1])
            return True
        got = self._lock.acquire(blocking, timeout)
        if got:
            # try/timed acquires cannot complete a deadlock cycle:
            # held, but no order edge
            st = self._st
            st.acquires += 1
            st.n += 1
            _held().append([self, self.name, 0.0, 1])
        return got

    def release(self) -> None:
        held = _held()
        i = len(held) - 1                 # common case: innermost
        while i >= 0 and held[i][0] is not self:
            i -= 1
        if i >= 0:
            t0 = held[i][2]
            del held[i]
            if t0:
                # stats update BEFORE the inner release: serialized
                # by the lock we still hold
                self._st.note_hold(_mono() - t0)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WitnessLock {self.name} {self._lock!r}>"


class _WitnessRLock:
    """threading.RLock wrapper. Implements the private Condition
    protocol (_release_save/_acquire_restore/_is_owned) so
    ``named_condition`` can wrap it: a ``cond.wait()`` fully releases
    the held entry and restores it on wakeup, keeping the per-thread
    held-set truthful across waits."""

    __slots__ = ("_lock", "name")

    def __init__(self, name: str) -> None:
        self._lock = threading.RLock()
        self.name = name
        _stats_for(name)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking and timeout < 0:
            check_before_acquire(self, self.name)
            if self._lock.acquire(False):
                note_acquire(self, self.name, blocking=True)
                return True
            t0 = _mono()
            self._lock.acquire()
            note_acquire(self, self.name, blocking=True,
                         wait=_mono() - t0, contended=True)
            return True
        got = self._lock.acquire(blocking, timeout)
        if got:
            note_acquire(self, self.name, blocking=False)
        return got

    def release(self) -> None:
        self._lock.release()
        note_release(self, self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol ---------------------------------------------

    def _release_save(self):
        held = _held()
        count = 1
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                count = held[i][3]
                t0 = held[i][2]
                del held[i]
                if t0:
                    st = _stats.get(self.name)
                    if st is not None:
                        st.note_hold(_mono() - t0)
                break
        return (self._lock._release_save(), count)

    def _acquire_restore(self, state) -> None:
        inner, count = state
        # re-acquire after wait IS a blocking acquire: record edges
        # from whatever else this thread still holds — validated
        # BEFORE the restore so a raise-mode inversion leaves the
        # condition's lock untaken
        held = _held()
        if held:
            _add_edges(held, self.name)
        t0 = _mono()
        self._lock._acquire_restore(inner)
        wait = _mono() - t0
        st = _stats.get(self.name)
        if st is None:
            st = _stats_for(self.name)
        st.acquires += 1
        st.n += 1
        if wait > 0.0001:
            st.note_wait(wait)
        held.append([self, self.name, 0.0, count])

    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def __repr__(self) -> str:
        return f"<WitnessRLock {self.name} {self._lock!r}>"


# -- factories -----------------------------------------------------------

def named_lock(name: str):
    """A mutex with a lockdep class name. Disabled -> a bare
    ``threading.Lock()`` (zero cost, identical semantics)."""
    if not enabled():
        return threading.Lock()
    with _graph_lock:
        _known_names.add(name)
    return _WitnessLock(name)


def named_rlock(name: str):
    if not enabled():
        return threading.RLock()
    with _graph_lock:
        _known_names.add(name)
    return _WitnessRLock(name)


def named_condition(name: str):
    """A Condition whose underlying (reentrant) lock is witnessed:
    waiters drop their held entry for the duration of the wait."""
    if not enabled():
        return threading.Condition()
    with _graph_lock:
        _known_names.add(name)
    return threading.Condition(_WitnessRLock(name))


def register_name(name: str) -> None:
    """Register a non-factory witnessed region (the ingest latch) so
    it shows up in the stats doc before its first acquisition."""
    if enabled():
        with _graph_lock:
            _known_names.add(name)


# -- reporting -----------------------------------------------------------

def inversions() -> List[Dict[str, object]]:
    with _graph_lock:
        return [dict(i) for i in _inversions]


def order_edges() -> List[Tuple[str, str]]:
    with _graph_lock:
        return sorted((a, b) for a, peers in _edges.items()
                      for b in peers)


def lock_names() -> List[str]:
    with _graph_lock:
        return sorted(_known_names | set(_stats))


def stats() -> Dict[str, Dict[str, object]]:
    with _graph_lock:
        items = list(_stats.items())
    return {name: s.doc() for name, s in sorted(items)}


def held_names() -> List[str]:
    """This thread's currently-held lock classes, outermost first
    (test/debug introspection)."""
    return [e[1] for e in _held()]


def stats_doc() -> Dict[str, object]:
    """The GET /debug/locks document."""
    if not enabled():
        return {"enabled": False}
    with _graph_lock:
        edges = sorted((a, b) for a, peers in _edges.items()
                       for b in peers)
        doc = {
            "enabled": True,
            "locks": sorted(_known_names | set(_stats)),
            "orderEdges": [
                {"held": a, "acquired": b,
                 "site": _edge_sites.get((a, b), "?")}
                for a, b in edges],
            "selfNesting": dict(sorted(_self_edges.items())),
            "inversions": [dict(i) for i in _inversions],
        }
    doc["stats"] = stats()
    return doc


def reset() -> None:
    """Clear the order graph, inversion log, and stats (tests).
    Held-sets of live threads are preserved. Live wrapper instances
    keep feeding their original stats objects (which are no longer
    reported) — acceptable for test isolation."""
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        _inversions.clear()
        _self_edges.clear()
        _stats.clear()


@contextlib.contextmanager
def scoped():
    """Swap in FRESH witness state for the duration (restoring the
    real graph after): tests that build deliberate inversions must
    not trip the suite-wide zero-inversion gate, and the suite's real
    graph must not mask a fixture's cycle. Locks created inside the
    scope register their stats in the scoped tables.

    Background threads (maintenance loops, servers from earlier
    tests) keep running while a scope is active; their REAL ordering
    observations must not be discarded with the fixture state. On
    exit, any scoped edge whose both endpoints were already known to
    the real graph (i.e. not fixture-created — fixture lock names
    never pre-exist in the real stats) is merged back through the
    same cycle check, so an inversion first witnessed during a scope
    still fails the suite-wide gate."""
    global _edges, _edge_sites, _inversions, _self_edges, _stats, \
        _known_names
    with _graph_lock:
        saved = (_edges, _edge_sites, _inversions, _self_edges,
                 _stats, _known_names)
        # names that pre-exist the scope: only THEIR edges merge back
        # (fixture locks are minted inside the scope — including into
        # the swapped name set, so a reused fixture name from an
        # earlier scope can never qualify)
        real_names = set(_stats) | set(_known_names)
        (_edges, _edge_sites, _inversions, _self_edges, _stats,
         _known_names) = ({}, {}, [], {}, {}, set(_known_names))
    try:
        yield
    finally:
        with _graph_lock:
            scoped_edges = _edges
            scoped_sites = _edge_sites
            (_edges, _edge_sites, _inversions, _self_edges,
             _stats, _known_names) = saved
            # merge-back: real-lock observations made during the scope
            for a, peers in scoped_edges.items():
                if a not in real_names:
                    continue
                for b in peers:
                    if b not in real_names:
                        continue
                    dst = _edges.setdefault(a, set())
                    if b in dst:
                        continue
                    site = scoped_sites.get((a, b), "?")
                    path = _find_path(b, a)
                    dst.add(b)
                    _edge_sites[(a, b)] = site
                    if path is not None:
                        cycle = path + [b]
                        _inversions.append({
                            "cycle": cycle,
                            "edge": [a, b],
                            "site": site,
                            "priorSites": {
                                f"{x}->{y}":
                                    _edge_sites.get((x, y), "?")
                                for x, y in zip(path, path[1:])},
                            "thread": "(merged from scoped window)",
                        })
                        print(f"lockdep: lock-order inversion "
                              f"(observed during a scoped window): "
                              f"{' -> '.join(cycle)}",
                              file=sys.stderr)
