"""``python -m theia_tpu.analysis`` — run the static passes.

Exit status 0 = every finding waived (with a cited invariant) and no
stale waivers; 1 = unwaived findings or waiver-file problems. Tier-1
asserts the clean run (tests/test_analysis.py), so the gate rides
every CI pass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .base import apply_waivers, validate_waivers
from .lint import Lint
from .lockgraph import LockGraph
from .waivers import WAIVERS


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def run_all(root: str):
    """(findings, lockgraph) over the package at ``root``."""
    pkg = os.path.join(root, "theia_tpu")
    lg = LockGraph(pkg)
    findings = lg.run()
    findings.extend(Lint(pkg, os.path.join(root, "docs"),
                         extra=[os.path.join(root, "bench.py")]).run())
    return findings, lg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m theia_tpu.analysis",
        description="static concurrency/lint analysis for theia_tpu")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--all", action="store_true",
                    help="show waived findings too")
    ap.add_argument("--edges", action="store_true",
                    help="print the static lock-order edge graph")
    ap.add_argument("--root", default=None,
                    help="repo root (default: autodetect)")
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    findings, lg = run_all(root)
    problems = validate_waivers(WAIVERS)
    unwaived, waived, stale = apply_waivers(findings, WAIVERS)

    if args.json:
        print(json.dumps({
            "findings": [f.doc() for f in unwaived],
            "waived": [{"finding": f.doc(),
                        "invariant": w["invariant"]}
                       for f, w in waived],
            "staleWaivers": stale,
            "waiverProblems": problems,
            "edges": lg.edges_doc(),
            "locks": sorted(set(lg.locks.values())),
            "unresolvedRefs": sorted(set(lg.unresolved)),
        }, indent=2))
        return 1 if (unwaived or stale or problems) else 0

    print(f"theia_tpu analysis: {len(lg.locks)} lock attrs "
          f"({len(set(lg.locks.values()))} classes), "
          f"{len(lg.graph)} static order edges, "
          f"{len(findings)} findings "
          f"({len(waived)} waived)")
    if args.edges:
        for e in lg.edges_doc():
            print(f"  edge {e['held']} -> {e['acquired']}  "
                  f"[{e['site']}]")
    if lg.unresolved:
        print(f"  note: {len(set(lg.unresolved))} unresolved lock "
              f"refs (receiver ambiguous): "
              f"{', '.join(sorted(set(lg.unresolved))[:8])}")
    for f in unwaived:
        print(f"FINDING {f.check}: {f.message}")
        print(f"    key:  {f.key}")
        print(f"    site: {f.site}")
        if f.detail:
            print(f"    detail: {f.detail}")
    if args.all:
        for f, w in waived:
            print(f"waived {f.check}: {f.key}")
            print(f"    invariant: {w['invariant']}")
    for p in problems:
        print(f"WAIVER PROBLEM: {p}")
    for w in stale:
        print(f"STALE WAIVER (matches nothing — code changed?): "
              f"{w.get('check')}:{w.get('match')}")
    if unwaived or stale or problems:
        print(f"\nFAIL: {len(unwaived)} unwaived finding(s), "
              f"{len(stale)} stale waiver(s), "
              f"{len(problems)} waiver problem(s)")
        return 1
    print("clean: every finding waived with a cited invariant")
    return 0


if __name__ == "__main__":
    sys.exit(main())
