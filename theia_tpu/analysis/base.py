"""Shared finding/waiver machinery for the static analysis passes.

A *finding* is one defect report with a stable ``key`` (no line
numbers — keys survive unrelated edits) plus a human site reference.
A *waiver* (analysis/waivers.py) matches finding keys by ``fnmatch``
glob and MUST cite the invariant that makes the waived code safe —
an empty or hand-wavy invariant fails validation, because a waiver
without a written invariant is just a silenced bug.

Waiver semantics are strict in both directions: an unwaived finding
fails the gate, and a waiver that matches nothing is STALE and fails
too (the code it described changed; the file must be updated with it).
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Dict, List, Sequence, Tuple

#: a waiver invariant shorter than this cannot plausibly state WHY the
#: flagged code is safe
MIN_INVARIANT_CHARS = 40


@dataclasses.dataclass
class Finding:
    check: str           # e.g. "lock-order-cycle", "undeclared-env"
    key: str             # stable id the waiver file matches against
    message: str         # human one-liner
    site: str = ""       # file:line of the primary evidence
    detail: str = ""     # optional expansion (cycle path, call chain)

    def doc(self) -> Dict[str, str]:
        return dataclasses.asdict(self)


class WaiverError(Exception):
    """The waiver file itself is malformed (missing invariant, stale
    entry, unknown check)."""


KNOWN_CHECKS = (
    "lock-order-cycle",
    "blocking-under-lock",
    "torn-read",
    "undeclared-env",
    "unregistered-fault-site",
    "stale-fault-site",
    "bare-except",
    "swallowed-except",
    "raw-clock",
)


def validate_waivers(waivers: Sequence[Dict[str, str]]) -> List[str]:
    """Structural validation; returns a list of problems (empty =
    valid)."""
    problems = []
    seen = set()
    for i, w in enumerate(waivers):
        where = f"waiver #{i + 1}"
        check = w.get("check", "")
        match = w.get("match", "")
        invariant = w.get("invariant", "")
        if check not in KNOWN_CHECKS:
            problems.append(f"{where}: unknown check {check!r} "
                            f"(known: {', '.join(KNOWN_CHECKS)})")
        if not match:
            problems.append(f"{where}: empty match pattern")
        if len(invariant.strip()) < MIN_INVARIANT_CHARS:
            problems.append(
                f"{where} ({check}:{match}): invariant must spell out "
                f"WHY the flagged code is safe "
                f"(≥{MIN_INVARIANT_CHARS} chars)")
        if (check, match) in seen:
            problems.append(f"{where}: duplicate of ({check}, {match})")
        seen.add((check, match))
    return problems


def apply_waivers(
    findings: Sequence[Finding],
    waivers: Sequence[Dict[str, str]],
) -> Tuple[List[Finding], List[Tuple[Finding, Dict[str, str]]],
           List[Dict[str, str]]]:
    """Partition into (unwaived findings, waived (finding, waiver)
    pairs, stale waivers that matched nothing)."""
    used = [False] * len(waivers)
    unwaived: List[Finding] = []
    waived: List[Tuple[Finding, Dict[str, str]]] = []
    for f in findings:
        hit = None
        for i, w in enumerate(waivers):
            if w.get("check") == f.check and \
                    fnmatch.fnmatchcase(f.key, w.get("match", "")):
                used[i] = True
                if hit is None:
                    hit = w
        if hit is None:
            unwaived.append(f)
        else:
            waived.append((f, hit))
    stale = [w for i, w in enumerate(waivers) if not used[i]]
    return unwaived, waived, stale
