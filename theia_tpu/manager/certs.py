"""TLS certificate subsystem for the manager.

Re-provides pkg/apiserver/certificate/: self-signed serving certificates
generated at startup (generateSelfSignedCertificate, certificate.go:103),
or operator-provided cert/key pairs (ApplyServerCert :52), with the CA
certificate published to a well-known location so clients can trust the
server — the reference publishes to the `theia-ca` ConfigMap
(cacert_controller.go); here it's a PEM file the CLI reads via
--ca-cert. Rotation = regenerate when the cert is within
`rotate_before` of expiry.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

DEFAULT_VALIDITY_DAYS = 365
DEFAULT_ROTATE_BEFORE = datetime.timedelta(days=30)
CA_CERT_FILENAME = "theia-ca.crt"   # the `theia-ca` ConfigMap analogue


def generate_self_signed(
        common_name: str = "theia-manager",
        dns_names: Tuple[str, ...] = ("localhost", "theia-manager"),
        validity_days: int = DEFAULT_VALIDITY_DAYS) -> Tuple[bytes, bytes]:
    """(cert_pem, key_pem) for a self-signed serving certificate."""
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    san = x509.SubjectAlternativeName(
        [x509.DNSName(d) for d in dns_names]
        + [x509.IPAddress(ipaddress.ip_address("127.0.0.1"))])
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(
                days=validity_days))
            .add_extension(san, critical=False)
            .add_extension(x509.BasicConstraints(ca=True,
                                                 path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())
    return cert_pem, key_pem


def cert_expiry(cert_pem: bytes) -> datetime.datetime:
    return x509.load_pem_x509_certificate(
        cert_pem).not_valid_after_utc


def needs_rotation(cert_pem: bytes,
                   rotate_before: datetime.timedelta =
                   DEFAULT_ROTATE_BEFORE) -> bool:
    now = datetime.datetime.now(datetime.timezone.utc)
    return cert_expiry(cert_pem) - now < rotate_before


def apply_server_cert(cert_dir: str,
                      provided_cert: Optional[str] = None,
                      provided_key: Optional[str] = None,
                      provided_ca: Optional[str] = None
                      ) -> Tuple[str, str, str]:
    """Ensure serving cert/key exist; returns (cert, key, ca) paths.

    Provided cert/key are used as-is (reference ApplyServerCert's
    provided-secret path) with `provided_ca` as the published issuer
    bundle; otherwise a self-signed pair is generated, reusing an
    existing one unless it needs rotation, and the cert itself is the
    CA. The CA is published to CA_CERT_FILENAME (the `theia-ca`
    ConfigMap analogue).
    """
    os.makedirs(cert_dir, exist_ok=True)
    ca_path = os.path.join(cert_dir, CA_CERT_FILENAME)
    if bool(provided_cert) != bool(provided_key):
        raise ValueError(
            "provided cert/key must be given together "
            f"(cert={provided_cert!r}, key={provided_key!r})")
    if provided_cert and provided_key:
        # Publish the issuing CA when given; a non-self-signed leaf in
        # a client trust store is not generally accepted.
        ca_src = provided_ca or provided_cert
        with open(ca_src, "rb") as f:
            ca_bytes = f.read()
        with open(ca_path, "wb") as f:
            f.write(ca_bytes)
        return provided_cert, provided_key, ca_path

    cert_path = os.path.join(cert_dir, "tls.crt")
    key_path = os.path.join(cert_dir, "tls.key")
    regenerate = True
    if os.path.exists(cert_path) and os.path.exists(key_path):
        with open(cert_path, "rb") as f:
            existing = f.read()
        regenerate = needs_rotation(existing)
    if regenerate:
        cert_pem, key_pem = generate_self_signed()
        with open(cert_path, "wb") as f:
            f.write(cert_pem)
        fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC,
                     0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key_pem)
    with open(cert_path, "rb") as f:
        cert_bytes = f.read()
    with open(ca_path, "wb") as f:
        f.write(cert_bytes)
    return cert_path, key_path, ca_path
