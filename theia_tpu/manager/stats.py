"""Store statistics — the ClickHouseStats API equivalent.

Re-provides pkg/apiserver/utils/stats/clickhouse_stats.go:35-117, whose
four canned queries read system.disks / system.tables / system.query_log
/ system.stack_trace. Here the "shard" is the in-process store:

  * diskInfos   — store bytes vs a configured capacity
  * tableInfos  — rows/bytes/columns per table and materialized view
  * insertRates — rows/s and bytes/s since the previous sample
  * stackTraces — current Python thread stacks (the reference dumps
                  ClickHouse thread stacks)

String-typed values mirror the reference API (pkg/apis/stats/v1alpha1).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List

from ..store import FlowDatabase
from ..analysis.lockdep import named_lock


class StatsProvider:
    def __init__(self, db: FlowDatabase,
                 capacity_bytes: int = 8 << 30,
                 shard: str = "0") -> None:
        self.db = db
        self.capacity_bytes = capacity_bytes
        self.shard = shard
        self._lock = named_lock("manager.stats")
        self._last_sample = (time.time(), self._row_byte_totals())

    def _row_byte_totals(self):
        """CUMULATIVE inserted rows/bytes, not net table size: net size
        made insert_rates under-report after any delete (a retention
        trim of N rows masked the next N inserted rows — the rate
        read 0 while ingest ran hot). The cumulative counters only
        grow, so the delta between samples is exactly what arrived.
        Falls back to net size for stores that predate the counters
        (e.g. a bare Table stub in tests)."""
        db = self.db
        rows = getattr(db, "rows_inserted_total", None)
        if rows is not None:
            return int(rows), int(db.bytes_inserted_total)
        return len(db.flows), db.flows.nbytes

    def disk_infos(self) -> List[Dict[str, str]]:
        used = self.db.flows.nbytes + sum(
            t.nbytes for t in self.db.result_tables.values())
        free = max(self.capacity_bytes - used, 0)
        return [{
            "shard": self.shard,
            "name": "default",
            "path": "memory://flows",
            "freeSpace": str(free),
            "totalSpace": str(self.capacity_bytes),
            "usedPercentage": f"{used / self.capacity_bytes * 100:.2f}",
        }]

    def table_infos(self) -> List[Dict[str, str]]:
        out = []
        for table in (self.db.flows, *self.db.result_tables.values()):
            out.append({
                "shard": self.shard,
                "database": "default",
                "tableName": table.name,
                "totalRows": str(len(table)),
                "totalBytes": str(table.nbytes),
                "totalCols": str(len(table.schema)),
            })
        for name, view in self.db.views.items():
            batch = view.scan()
            nbytes = sum(v.nbytes for v in batch.columns.values())
            out.append({
                "shard": self.shard,
                "database": "default",
                "tableName": name,
                "totalRows": str(len(batch)),
                "totalBytes": str(nbytes),
                "totalCols": str(len(batch.columns)),
            })
        return out

    def insert_rates(self) -> List[Dict[str, str]]:
        now = time.time()
        rows, nbytes = self._row_byte_totals()
        with self._lock:
            then, (prev_rows, prev_bytes) = self._last_sample
            self._last_sample = (now, (rows, nbytes))
        dt = max(now - then, 1e-9)
        # Cumulative totals are monotone, so the max() guard only
        # protects against a swapped-out db object, not deletes.
        return [{
            "shard": self.shard,
            "rowsPerSec": str(int(max(rows - prev_rows, 0) / dt)),
            "bytesPerSec": str(int(max(nbytes - prev_bytes, 0) / dt)),
        }]

    def stack_traces(self) -> List[Dict[str, str]]:
        out = []
        for tid, frame in sys._current_frames().items():
            out.append({
                "shard": self.shard,
                "threadId": str(tid),
                "trace": "".join(traceback.format_stack(frame, limit=12)),
            })
        return out

    def device_infos(self) -> List[Dict[str, str]]:
        """Accelerator inventory + HBM usage — observability the
        reference has no equivalent for (its compute tier is opaque
        Spark executors; ours is a visible device mesh). Served as the
        `deviceInfo` stats component."""
        out = []
        try:
            import jax
            devices = jax.devices()
        except Exception as e:  # no backend available (e.g. bare CI)
            return [{"shard": self.shard, "error": str(e)}]
        for dev in devices:
            info = {
                "shard": self.shard,
                "deviceId": str(dev.id),
                "platform": dev.platform,
                "deviceKind": dev.device_kind,
                "processIndex": str(dev.process_index),
            }
            try:
                mem = dev.memory_stats() or {}
                if "bytes_in_use" in mem:
                    info["memoryBytesInUse"] = str(mem["bytes_in_use"])
                if "bytes_limit" in mem:
                    info["memoryBytesLimit"] = str(mem["bytes_limit"])
                    limit = max(int(mem["bytes_limit"]), 1)
                    info["memoryUsedPercentage"] = (
                        f"{int(mem.get('bytes_in_use', 0)) / limit * 100:.2f}")
            except Exception:
                pass  # CPU devices and some backends expose no stats
            out.append(info)
        return out
