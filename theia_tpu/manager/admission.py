"""Overload control for the ingest/API path: admission, backpressure,
brownout degradation, and the exactly-once dedup window.

The reference platform survives traffic spikes because ClickHouse
bounds its insert queues and sheds load explicitly (`max_concurrent_
queries`, `TOO_MANY_SIMULTANEOUS_QUERIES` → the client backs off and
retries); a manager that admits every POST unconditionally does not
degrade — it collapses (the insert backlog grows without bound, then
everything times out at once). This module gives the manager the same
discipline, built from three pieces:

**Admission + backpressure.** A per-manager token bucket in rows/sec
(`THEIA_INGEST_RATE`, burst `THEIA_INGEST_BURST`, default 2x rate) and
bytes/sec (`THEIA_INGEST_BYTES_RATE`/`THEIA_INGEST_BYTES_BURST`).
Bytes are charged at admission time (the payload length is known
before decode); rows are charged AFTER decode — the bucket may go into
debt, and a bucket in debt rejects until it refills, so sustained
overload converges on the configured rate without needing to know row
counts up front. A rejected request gets **429 + Retry-After** (a
capacity condition the producer should retry), never 503 (which means
the store itself is unavailable). Per-stream fair-share accounting (a
decayed per-stream rate estimate) keeps one hot producer from draining
the shared bucket dry while 63 polite streams starve: under bucket
contention, a stream consuming more than twice its fair share
(rate / active streams) is rejected first, and a stream running UNDER
its fair share keeps being admitted while the bucket pays off the
hog's debt — down to a floor of one extra burst of debt, so a fleet
minting fresh stream ids cannot make the rate unenforceable.

**Pressure watermarks.** Live signals the manager already has — the
in-flight store-insert backlog (`THEIA_INGEST_INFLIGHT_HIGH`, default
2x the insert pool), the WAL's unsynced-record lag behind `syncedLsn`
(`THEIA_WAL_LAG_HIGH`), and the job queue depth
(`THEIA_JOB_QUEUE_HIGH`) — each normalize to current/high; the
pressure score is the worst of them.

**Brownout ladder.** Under sustained pressure the manager degrades
deliberately instead of collapsing, durability-first (shed work is
always the *scoring* leg — rows still hit WAL + store and are
acknowledged):

    rung 0  ok             full service
    rung 1  sampled        detector/scoring leg runs on a declining
                           fraction of batches (fraction falls as
                           pressure rises through the band)
    rung 2  shed_detector  scoring fully shed; ingest stays durable;
                           heavy `/query` reads answer 429 (deferrable
                           analytics shed one rung before ingest does)
    rung 3  reject         new ingest answers 429 + Retry-After

Rung transitions are hysteretic: escalation is immediate, de-escalation
steps down one rung at a time only after the pressure has stayed below
the rung's entry threshold (minus a margin) for
`THEIA_ADMISSION_HOLD` seconds — a flapping signal cannot oscillate
the ladder. The current rung is served on `/healthz` (`admission`),
as the `theia_admission_level` gauge, and in `theia top`. The
`admission.pressure` fault site (utils/faults.py grammar) forces the
reject rung deterministically for drills, and
`THEIA_ADMISSION_FORCE_LEVEL=<rung|name>` pins any rung.

Control/observability endpoints (`/healthz`, `/readyz`, `/metrics`,
`/alerts`) are never shed — admission gates only `POST /ingest` — so
the operator can always see *why* the manager is rejecting.

**Exactly-once retried ingest.** Producers stamp batches with
`?stream=<id>&seq=<n>`; `DedupWindow` keeps a bounded per-stream
window (`THEIA_INGEST_DEDUP_WINDOW`, default 1024 seqs) of
acknowledged batches, so a retry of a timed-out, shed, or already-
acked batch is answered `{"duplicate": true}` instead of inserting
twice. The `(stream, seq)` tag rides the WAL record header
(store/wal.py `pack_dedup_tag`) and is restored on recovery, so the
idempotency guarantee survives kill -9: a producer retrying across a
crash cannot double-apply a batch whose WAL record was replayed.
"""

from __future__ import annotations

import collections
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as _metrics
from ..utils.env import env_float, env_int
from ..utils.faults import FaultError
from ..utils.faults import fire as _fire_fault
from ..utils.logging import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("admission")

#: brownout ladder rungs, least to most degraded
LEVEL_OK, LEVEL_SAMPLED, LEVEL_SHED, LEVEL_REJECT = range(4)
LEVEL_NAMES = ("ok", "sampled", "shed_detector", "reject")

#: pressure score at which each rung engages (rung 0 has no entry)
LEVEL_THRESHOLDS = (0.0, 0.5, 0.75, 1.0)
#: de-escalation hysteresis: pressure must drop this far below a
#: rung's entry threshold before the ladder steps down
HYSTERESIS_MARGIN = 0.1

_M_LEVEL = _metrics.gauge(
    "theia_admission_level",
    "Current brownout rung (0 ok, 1 sampled, 2 shed_detector, "
    "3 reject)")
_M_PRESSURE = _metrics.gauge(
    "theia_admission_pressure",
    "Worst pressure-signal ratio (current/high watermark; >= 1 means "
    "a signal is past its watermark)")
_M_REJECTED = _metrics.counter(
    "theia_admission_rejected_total",
    "Ingest requests rejected with 429 + Retry-After, by reason",
    labelnames=("reason",))
_M_DEDUP_HITS = _metrics.counter(
    "theia_ingest_dedup_hits_total",
    "Retried (stream, seq) batches answered duplicate:true instead "
    "of re-inserting")
_M_DUP_ROWS = _metrics.counter(
    "theia_ingest_duplicate_rows_total",
    "Rows a retrying producer would have double-inserted without the "
    "dedup window")


class AdmissionRejected(Exception):
    """Request refused for CAPACITY (HTTP 429 + Retry-After), as
    opposed to unavailability (503). Retryable after `retry_after`
    seconds."""

    def __init__(self, reason: str, retry_after: float,
                 detail: str = "") -> None:
        super().__init__(
            f"ingest over capacity ({reason}): retry after "
            f"{retry_after:.2f}s" + (f" — {detail}" if detail else ""))
        self.reason = reason
        self.retry_after = float(retry_after)


class TokenBucket:
    """Deterministic token bucket (injectable clock). Supports the
    charge-after-the-fact discipline the row bucket needs: `charge()`
    may push the balance negative (the caller learns the true cost
    only after decode), and `wait_for_positive()` reports how long
    until the debt clears."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(max(burst, 1.0))
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()
        self._lock = named_lock("admission.bucket")

    def _refill_locked(self) -> None:
        now = self._clock()
        dt = now - self._t
        if dt > 0:
            self._tokens = min(self.burst,
                               self._tokens + dt * self.rate)
        self._t = now

    def tokens(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens

    def try_charge(self, n: float) -> float:
        """Charge `n` tokens if covered; returns 0.0 on success, else
        the seconds until `n` tokens will be available. A request
        larger than the whole burst is admitted from a full bucket
        (into debt) — otherwise it could never be admitted at all."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= min(n, self.burst):
                self._tokens -= n
                return 0.0
            return (min(n, self.burst) - self._tokens) / self.rate

    def charge(self, n: float) -> None:
        """Unconditional charge (post-decode row accounting); the
        balance may go negative — debt rejects future admissions until
        the refill clears it."""
        with self._lock:
            self._refill_locked()
            self._tokens -= n

    def wait_for_positive(self) -> float:
        """0.0 when the bucket holds at least one token, else seconds
        until it will."""
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                return 0.0
            return (1.0 - self._tokens) / self.rate


class DedupWindow:
    """Bounded per-stream window of acknowledged `(seq -> rows)`
    batches. `lookup` answers a retry without touching decoder, store,
    or detector state; beyond the window (or for unstamped batches)
    ingest degrades to at-least-once, which is the pre-existing
    contract.

    Cardinality hardening (the ROADMAP item-5 pre-work): every
    operation is O(1) — streams are an OrderedDict LRU
    (`THEIA_INGEST_DEDUP_STREAMS`, default 8192), total entries carry
    a RUNNING count (stats() no longer walks every stream), and a
    GLOBAL entry budget (`THEIA_INGEST_DEDUP_ENTRIES`, default 2^20)
    bounds aggregate memory by evicting whole least-recently-active
    streams — so ~100k distinct stream ids (a router mesh's
    `stream@origin` sub-streams, a fleet minting producer ids) cost
    bounded memory and constant-time ops, not 100k × window dicts."""

    def __init__(self, window: Optional[int] = None,
                 max_streams: Optional[int] = None,
                 max_entries: Optional[int] = None) -> None:
        self.window = (env_int("THEIA_INGEST_DEDUP_WINDOW", 1024)
                       if window is None else int(window))
        self.max_streams = (env_int("THEIA_INGEST_DEDUP_STREAMS", 8192)
                            if max_streams is None
                            else int(max_streams))
        self.max_entries = (env_int("THEIA_INGEST_DEDUP_ENTRIES",
                                    1 << 20)
                            if max_entries is None
                            else int(max_entries))
        self._streams: "collections.OrderedDict[str, collections.OrderedDict[int, int]]" = (
            collections.OrderedDict())
        self._entries = 0
        self._lock = named_lock("admission.dedup")
        self.hits = 0
        self.misses = 0
        self.evicted_streams = 0

    def lookup(self, stream: str, seq: Optional[int]) -> Optional[int]:
        """Rows acked for `(stream, seq)`, or None (unseen/evicted/
        unstamped — proceed with the insert)."""
        if seq is None or self.window <= 0:
            return None
        with self._lock:
            win = self._streams.get(stream)
            rows = None if win is None else win.get(int(seq))
            if rows is None:
                self.misses += 1
                return None
            # a hit is activity too: a producer replaying an
            # already-acked tail (lookups only, no new records) must
            # not age out of the stream LRU mid-replay
            self._streams.move_to_end(stream)
            self.hits += 1
            return rows

    def record(self, stream: str, seq: Optional[int],
               rows: int) -> None:
        if seq is None or self.window <= 0:
            return
        with self._lock:
            win = self._streams.get(stream)
            if win is None:
                win = self._streams[stream] = collections.OrderedDict()
            else:
                self._streams.move_to_end(stream)
            seq = int(seq)
            if seq not in win:
                self._entries += 1
            win[seq] = int(rows)
            win.move_to_end(seq)
            while len(win) > self.window:
                win.popitem(last=False)
                self._entries -= 1
            self._evict_over_budget_locked()

    def _evict_over_budget_locked(self) -> None:
        """Drop whole least-recently-active streams until both the
        stream LRU and the global entry budget hold — amortized O(1):
        each stream is inserted once and evicted at most once."""
        while (len(self._streams) > self.max_streams
               or (self.max_entries > 0
                   and self._entries > self.max_entries
                   and len(self._streams) > 1)):
            evicted, win = self._streams.popitem(last=False)
            self._entries -= len(win)
            self.evicted_streams += 1
            logger.v(1).info(
                "dedup window evicted idle stream %r (%d entries)",
                evicted, len(win))

    def dump(self, limit: int = 1 << 20) -> List[Tuple[str, int, int]]:
        """(stream, seq, rows) snapshot of every live entry — shipped
        inside a cluster resync so a freshly-synced follower answers
        producer retries duplicate:true after a failover. Bounded by
        `limit` newest-stream-first."""
        out: List[Tuple[str, int, int]] = []
        with self._lock:
            for stream in reversed(self._streams):
                win = self._streams[stream]
                for seq, rows in win.items():
                    out.append((stream, seq, rows))
                if len(out) >= limit:
                    break
        return out[:limit]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "window": self.window,
                "streams": len(self._streams),
                "maxStreams": self.max_streams,
                "entries": self._entries,
                "maxEntries": self.max_entries,
                "evictedStreams": self.evicted_streams,
                "hits": self.hits,
                "misses": self.misses,
            }


class AdmissionController:
    """The overload-control plane: token buckets + pressure ladder +
    fair share. One instance per IngestManager; every knob has an env
    default so a bare constructor is production-configured.

    Thread-safe; `clock` is injectable so every transition is
    deterministic under test."""

    #: decay constant for the per-stream rate estimate (seconds)
    STREAM_TAU = 5.0
    #: a stream may burst to this multiple of its fair share before
    #: fair-share rejection kicks in (under bucket contention only)
    FAIR_SHARE_SLACK = 2.0

    def __init__(self,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 byte_rate: Optional[float] = None,
                 byte_burst: Optional[float] = None,
                 hold_seconds: Optional[float] = None,
                 retry_after_hint: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        rate = env_float("THEIA_INGEST_RATE", 0.0) \
            if rate is None else float(rate)
        byte_rate = env_float("THEIA_INGEST_BYTES_RATE", 0.0) \
            if byte_rate is None else float(byte_rate)
        self._clock = clock
        self.rows = None
        if rate > 0:
            b = env_float("THEIA_INGEST_BURST", 0.0) \
                if burst is None else float(burst)
            self.rows = TokenBucket(rate, b if b > 0 else 2 * rate,
                                    clock=clock)
        self.bytes = None
        if byte_rate > 0:
            b = env_float("THEIA_INGEST_BYTES_BURST", 0.0) \
                if byte_burst is None else float(byte_burst)
            self.bytes = TokenBucket(byte_rate,
                                     b if b > 0 else 2 * byte_rate,
                                     clock=clock)
        self.hold_seconds = (env_float("THEIA_ADMISSION_HOLD", 1.0)
                             if hold_seconds is None
                             else float(hold_seconds))
        self.retry_after_hint = (
            env_float("THEIA_ADMISSION_RETRY_AFTER", 1.0)
            if retry_after_hint is None else float(retry_after_hint))
        #: name -> (current-value callable, high watermark)
        self._signals: Dict[str, Tuple[Callable[[], float], float]] = {}
        self._lock = named_lock("admission.controller")
        self._level = LEVEL_OK
        self._level_since = clock()
        #: first time pressure was seen below the de-escalation
        #: threshold (None while at/above it) — de-escalation needs
        #: hold_seconds of SUSTAINED low pressure, not one lucky dip
        self._below_since: Optional[float] = None
        self._score_credit = 0.0
        self._last_fraction = 1.0
        #: stream -> (decayed row count, last update) — estimate of a
        #: stream's recent rows/sec is acc / STREAM_TAU
        self._stream_acc: Dict[str, Tuple[float, float]] = {}
        self.rejected = 0
        self.admitted = 0

    # -- pressure signals --------------------------------------------------

    def add_signal(self, name: str, fn: Callable[[], float],
                   high: float) -> None:
        """Register a pressure signal: `fn()` is the live value, `high`
        the watermark at which it alone forces the reject rung."""
        if high <= 0:
            return
        self._signals[name] = (fn, float(high))

    def signal_ratios(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, (fn, high) in self._signals.items():
            try:
                out[name] = max(0.0, float(fn())) / high
            except Exception:
                # a broken signal must not take ingest down with it
                out[name] = 0.0
        return out

    def pressure(self) -> float:
        """Worst signal ratio (>= 1.0 means some watermark is hit)."""
        ratios = self.signal_ratios()
        return max(ratios.values()) if ratios else 0.0

    # -- brownout ladder ---------------------------------------------------

    @staticmethod
    def _forced_level() -> Optional[int]:
        raw = os.environ.get("THEIA_ADMISSION_FORCE_LEVEL", "").strip()
        if not raw:
            return None
        if raw.lower() in LEVEL_NAMES:
            return LEVEL_NAMES.index(raw.lower())
        try:
            n = int(raw)
        except ValueError:
            return None
        return min(LEVEL_REJECT, max(LEVEL_OK, n))

    def evaluate(self) -> int:
        """Recompute the brownout rung from live pressure (with
        hysteresis) and publish the gauges. Escalation is immediate;
        de-escalation is one rung at a time, and only after pressure
        has stayed a margin below the current rung's entry threshold
        for `hold_seconds` CONTINUOUSLY (as observed by evaluate
        calls) — a single dip of a flapping signal does not step the
        ladder down."""
        forced = self._forced_level()
        p = self.pressure()
        with self._lock:
            if forced is not None:
                if forced != self._level:
                    # reset the age only on an actual change:
                    # /healthz levelAgeSeconds should report how long
                    # the drill has been pinned, not ~0 forever
                    self._level = forced
                    self._level_since = self._clock()
                self._below_since = None
            else:
                target = LEVEL_OK
                for lvl in (LEVEL_REJECT, LEVEL_SHED, LEVEL_SAMPLED):
                    if p >= LEVEL_THRESHOLDS[lvl]:
                        target = lvl
                        break
                now = self._clock()
                if target > self._level:
                    self._level = target
                    self._level_since = now
                    self._below_since = None
                    logger.warning(
                        "admission escalated to %s (pressure %.2f: %s)",
                        LEVEL_NAMES[target], p,
                        ", ".join(f"{k}={v:.2f}" for k, v
                                  in self.signal_ratios().items()))
                elif target < self._level:
                    # de-escalation needs pressure SUSTAINED below the
                    # current rung's entry threshold (minus margin)
                    # for hold_seconds — a single dip of a flapping
                    # signal must not step the ladder down
                    entry = LEVEL_THRESHOLDS[self._level]
                    if p > entry - HYSTERESIS_MARGIN:
                        self._below_since = None
                    else:
                        if self._below_since is None:
                            self._below_since = now
                        if (now - self._below_since
                                >= self.hold_seconds):
                            self._level -= 1   # one rung at a time
                            self._level_since = now
                            # the dip continues: restart its clock at
                            # the step-down so the NEXT rung needs its
                            # own hold_seconds of sustained calm (the
                            # next evaluate re-derives against the new
                            # rung's threshold)
                            self._below_since = now
                            logger.info(
                                "admission de-escalated to %s "
                                "(pressure %.2f)",
                                LEVEL_NAMES[self._level], p)
                else:
                    self._below_since = None
            level = self._level
            self._last_fraction = self._score_fraction_locked(level, p)
        _M_LEVEL.set(level)
        _M_PRESSURE.set(p)
        return level

    def level(self) -> int:
        with self._lock:
            return self._level

    def _score_fraction_locked(self, level: int, p: float) -> float:
        """Fraction of batches the detector leg should score at this
        rung: 1.0 at ok, declining linearly across the sampled band
        (floor 0.25), 0.0 at shed/reject."""
        if level == LEVEL_OK:
            return 1.0
        if level != LEVEL_SAMPLED:
            return 0.0
        lo = LEVEL_THRESHOLDS[LEVEL_SAMPLED]
        hi = LEVEL_THRESHOLDS[LEVEL_SHED]
        frac = 1.0 - (p - lo) / (hi - lo)
        return min(1.0, max(0.25, frac))

    def should_score(self, level: int) -> bool:
        """Deterministic sampling decision for one batch at `level`:
        a credit accumulator admits exactly the configured fraction
        (no RNG — the same pressure trajectory always sheds the same
        batches)."""
        if level == LEVEL_OK:
            return True
        if level >= LEVEL_SHED:
            return False
        with self._lock:
            self._score_credit += self._last_fraction
            if self._score_credit >= 1.0:
                self._score_credit -= 1.0
                return True
            return False

    # -- admission ---------------------------------------------------------

    def admit(self, stream: str, nbytes: int,
              rows_hint: Optional[int] = None) -> int:
        """Gate one ingest request BEFORE decode. Returns the current
        brownout rung on success; raises AdmissionRejected (→ HTTP 429
        + Retry-After) when the request must not proceed. Charges the
        byte bucket (payload size is known here); rows are charged
        after decode via `charge_rows` — UNLESS `rows_hint` gives the
        exact row count up front (a TBLK block header, validated
        against the payload size by `wire.peek_counts`), in which case
        the row bucket and the stream rate estimate are charged here
        and the caller skips `charge_rows` entirely: admission for a
        self-contained block never needs the decode."""
        try:
            _fire_fault("admission.pressure", stream=stream)
        except FaultError as e:
            self.reject("fault", self.retry_after_hint, str(e))
        level = self.evaluate()
        if level >= LEVEL_REJECT:
            self.reject("pressure", self.retry_after_hint,
                         f"brownout rung {LEVEL_NAMES[level]}, "
                         f"pressure {self.pressure():.2f}")
        if self.rows is not None:
            # fair share first: a hog over 2x its share under
            # contention gets the SPECIFIC rejection (it should slow
            # down), not the generic debt one (everyone should)
            self._check_fair_share(stream)
            wait = self.rows.wait_for_positive()
            if wait > 0.0 and not (
                    self._under_fair_share(stream)
                    and self.rows.tokens() > -self.rows.burst):
                # Bucket in debt — a stream running UNDER its fair
                # share is not the one that put it there, so it keeps
                # being admitted, but only down to ONE extra burst of
                # debt: without that floor, a fleet minting fresh
                # stream ids (each with no rate history, so trivially
                # "under share") could push the debt arbitrarily deep
                # and make the configured rate unenforceable.
                self.reject("rows", wait, "row budget in debt")
        if self.bytes is not None:
            wait = self.bytes.try_charge(max(nbytes, 0))
            if wait > 0.0:
                self.reject("bytes", wait,
                             f"{nbytes} payload bytes over budget")
        if rows_hint is not None:
            self.charge_rows(stream, int(rows_hint))
        with self._lock:
            self.admitted += 1
        return level

    def admit_query(self) -> int:
        """Gate one `/query` request. Analytics queries are DEFERRABLE
        read work, so they ride the pressure ladder one rung ahead of
        ingest: at `shed_detector` (rung 2) — where ingest is still
        accepted, just unscored — queries already answer 429 +
        Retry-After, and at `reject` likewise. Control/observability
        endpoints (/healthz, /readyz, /metrics, /alerts) never shed;
        only the heavy read path does. Returns the rung on success."""
        try:
            _fire_fault("admission.pressure", stream="__query__")
        except FaultError as e:
            self.reject("fault", self.retry_after_hint, str(e))
        level = self.evaluate()
        if level >= LEVEL_SHED:
            self.reject(
                "query_shed", self.retry_after_hint,
                f"brownout rung {LEVEL_NAMES[level]} sheds analytics "
                f"queries (pressure {self.pressure():.2f})")
        with self._lock:
            self.admitted += 1
        return level

    def note_rejected(self) -> None:
        """Count a rejection raised OUTSIDE this controller (e.g. the
        ingest layer's in-flight duplicate) so /healthz
        `admission.rejected` stays in lockstep with
        theia_admission_rejected_total."""
        with self._lock:
            self.rejected += 1

    def reject(self, reason: str, retry_after: float,
               detail: str = "") -> None:
        """Count and raise one rejection."""
        self.note_rejected()
        _M_REJECTED.labels(reason=reason).inc()
        raise AdmissionRejected(reason, max(retry_after, 0.05), detail)

    def charge_rows(self, stream: str, rows: int) -> None:
        """Post-decode accounting: debit the row bucket by the actual
        row count (possibly into debt) and feed the stream's decayed
        rate estimate."""
        if rows <= 0:
            return
        if self.rows is not None:
            self.rows.charge(rows)
        now = self._clock()
        with self._lock:
            acc, last = self._stream_acc.get(stream, (0.0, now))
            acc *= math.exp(-(now - last) / self.STREAM_TAU)
            self._stream_acc[stream] = (acc + rows, now)
            # bound the table: drop streams idle long enough that
            # their estimate decayed to nothing
            if len(self._stream_acc) > 4096:
                cutoff = now - 4 * self.STREAM_TAU
                self._stream_acc = {
                    s: v for s, v in self._stream_acc.items()
                    if v[1] >= cutoff}

    def _stream_rate(self, stream: str, now: float) -> Tuple[float, int]:
        """(decayed rows/sec estimate for `stream`, active streams).
        Caller must NOT hold self._lock."""
        with self._lock:
            horizon = now - 2 * self.STREAM_TAU
            active = sum(1 for _, t in self._stream_acc.values()
                         if t >= horizon)
            acc, last = self._stream_acc.get(stream, (0.0, now))
        est = (acc * math.exp(-(now - last) / self.STREAM_TAU)
               / self.STREAM_TAU)
        return est, active

    def _under_fair_share(self, stream: str) -> bool:
        """True when `stream` consumes no more than its fair share of
        the configured rate (and there IS sharing going on)."""
        bucket = self.rows
        if bucket is None:
            return False
        est, active = self._stream_rate(stream, self._clock())
        return active > 1 and est <= bucket.rate / active

    def _check_fair_share(self, stream: str) -> None:
        """Under bucket contention (< half the burst left), reject the
        streams consuming more than FAIR_SHARE_SLACK × their fair
        share of the configured rate — the polite majority keeps
        landing while the hot producer backs off."""
        bucket = self.rows
        if bucket is None or bucket.tokens() >= bucket.burst / 2:
            return
        est, active = self._stream_rate(stream, self._clock())
        if active <= 1:
            return
        fair = bucket.rate / active
        if est > self.FAIR_SHARE_SLACK * fair:
            wait = min(5.0, max(0.1, (est - fair) / bucket.rate))
            self.reject(
                "fair_share", wait,
                f"stream {stream!r} at {est:.0f} rows/s vs fair share "
                f"{fair:.0f} ({active} active streams)")

    # -- operator surface --------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        """Health-surface doc (served under /healthz `admission`)."""
        with self._lock:
            level = self._level
            since = self._level_since
            admitted, rejected = self.admitted, self.rejected
        doc: Dict[str, object] = {
            "level": level,
            "levelName": LEVEL_NAMES[level],
            "levelAgeSeconds": round(self._clock() - since, 3),
            "pressure": round(self.pressure(), 4),
            "signals": {k: round(v, 4)
                        for k, v in self.signal_ratios().items()},
            "admitted": admitted,
            "rejected": rejected,
        }
        if self.rows is not None:
            doc["rowsPerSec"] = self.rows.rate
            doc["rowTokens"] = round(self.rows.tokens(), 1)
        if self.bytes is not None:
            doc["bytesPerSec"] = self.bytes.rate
            doc["byteTokens"] = round(self.bytes.tokens(), 1)
        return doc
