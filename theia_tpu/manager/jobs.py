"""Job records + controller state machine for NPR/TAD jobs.

Re-provides the reference's CRD controllers
(pkg/controller/networkpolicyrecommendation/controller.go and
pkg/controller/anomalydetector/controller.go): a job CR moves through
NEW → SCHEDULED → RUNNING → COMPLETED/FAILED (state machine
controller.go:375-427), with progress scraped into status while RUNNING
(:429-456), results garbage-collected when the CR is deleted
(cleanupNPRecommendation :390-403), and stale result rows reconciled
against live CRs at startup (HandleStaleDbEntries util.go:239-270).

Instead of submitting SparkApplications to an operator, the controller
runs the analytics jobs on worker threads against the shared
FlowDatabase — the TPU engine is in-process; scheduling is a thread
pool, not a pod fleet.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
import traceback
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..analytics import (TadQuerySpec, run_drop_detection, run_npr,
                         run_tad)
from ..runner.progress import (DD_STAGES, NPR_STAGES, TAD_STAGES,
                               JobProgress)
from ..store import FlowDatabase
from ..utils import get_logger, parse_job_name, validate_policy_type

logger = get_logger("jobs")

STATE_NEW = "NEW"
STATE_SCHEDULED = "SCHEDULED"
STATE_RUNNING = "RUNNING"
STATE_COMPLETED = "COMPLETED"
STATE_FAILED = "FAILED"

KIND_NPR = "npr"
KIND_TAD = "tad"
KIND_DD = "dd"

_NAME_PREFIX = {KIND_NPR: "pr-", KIND_TAD: "tad-", KIND_DD: "dd-"}


class DuplicateJobError(Exception):
    """A job with this name already exists (→ HTTP 409)."""


def job_id_from_name(kind: str, name: str) -> str:
    """pr-<uuid> / tad-<uuid> → <uuid> (reference ParseRecommendationName
    / ParseADAlgorithmName, pkg/util/utils.go)."""
    return parse_job_name(name, _NAME_PREFIX[kind])


@dataclasses.dataclass
class JobRecord:
    name: str
    kind: str                      # KIND_NPR | KIND_TAD
    spec: Dict[str, object]
    state: str = STATE_NEW
    error_msg: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    progress: Optional[JobProgress] = None

    @property
    def job_id(self) -> str:
        return job_id_from_name(self.kind, self.name)

    def status_dict(self) -> Dict[str, object]:
        completed, total = 0, 0
        if self.progress is not None:
            snap = self.progress.snapshot()
            completed = snap["completedStages"]
            total = snap["totalStages"]
        return {
            "state": self.state,
            "sparkApplication": self.job_id,
            "completedStages": completed,
            "totalStages": total,
            "errorMsg": self.error_msg,
            "startTime": self.start_time,
            "endTime": self.end_time,
        }


class JobController:
    """Reconciles job records into analytics runs over a worker pool."""

    def __init__(self, db: FlowDatabase, workers: int = 2) -> None:
        self.db = db
        self._records: Dict[str, JobRecord] = {}
        self._lock = threading.Lock()
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"job-worker-{i}")
            for i in range(workers)]
        for t in self._threads:
            t.start()
        self.gc_stale_results()

    # -- CRUD ------------------------------------------------------------

    def create(self, kind: str, spec: Dict[str, object],
               name: Optional[str] = None) -> JobRecord:
        if name is None:
            name = _NAME_PREFIX[kind] + str(uuid.uuid4())
        job_id_from_name(kind, name)  # validate
        record = JobRecord(name=name, kind=kind, spec=dict(spec),
                           state=STATE_SCHEDULED)
        with self._lock:
            if name in self._records:
                raise DuplicateJobError(f"job {name} already exists")
            self._records[name] = record
        self._queue.put(name)
        return record

    def get(self, name: str) -> JobRecord:
        with self._lock:
            return self._records[name]

    def list(self, kind: Optional[str] = None) -> List[JobRecord]:
        with self._lock:
            records = list(self._records.values())
        if kind:
            records = [r for r in records if r.kind == kind]
        return records

    def delete(self, name: str) -> None:
        """Remove the CR and GC its result rows (reference
        cleanupNPRecommendation deletes recommendations by id)."""
        with self._lock:
            record = self._records.pop(name)
        self._delete_results(record.kind, record.job_id)

    # -- GC --------------------------------------------------------------

    def gc_stale_results(self) -> int:
        """Drop result rows whose job CR no longer exists (reference
        HandleStaleDbEntries, run from the controller gcQueue at
        startup)."""
        with self._lock:
            live = {r.job_id for r in self._records.values()}
        removed = 0
        for table in (self.db.recommendations, self.db.tadetector,
                      self.db.dropdetection):
            data = table.scan()
            if not len(data):
                continue
            ids = data.strings("id")
            stale = ~np.isin(ids, list(live)) if live else np.ones(
                len(ids), bool)
            if stale.any():
                removed += table.delete_where(stale)
        return removed

    def _delete_results(self, kind: str, job_id: str) -> None:
        table = {KIND_NPR: self.db.recommendations,
                 KIND_TAD: self.db.tadetector,
                 KIND_DD: self.db.dropdetection}[kind]
        data = table.scan()
        if len(data):
            table.delete_where(data.strings("id") == job_id)

    # -- result retrieval ------------------------------------------------

    def recommendation_outcome(self, name: str) -> str:
        """Joined policy YAML for a COMPLETED NPR job (reference
        getRecommendationResult joins rows with '---\\n', rest.go:213)."""
        job_id = job_id_from_name(KIND_NPR, name)
        data = self.db.recommendations.scan()
        if not len(data):
            return ""
        rows = data.filter(data.strings("id") == job_id)
        return "---\n".join(rows.strings("policy"))

    def _result_stats(self, kind: str, table,
                      name: str) -> List[Dict[str, str]]:
        """Result rows for a job as string-typed stat entries
        (reference getTADetectorResult, rest.go:249-310)."""
        job_id = job_id_from_name(kind, name)
        data = table.scan()
        if not len(data):
            return []
        rows = data.filter(data.strings("id") == job_id)
        return [{k: str(v) for k, v in row.items()}
                for row in rows.to_rows()]

    def tad_stats(self, name: str) -> List[Dict[str, str]]:
        return self._result_stats(KIND_TAD, self.db.tadetector, name)

    def drop_detection_stats(self, name: str) -> List[Dict[str, str]]:
        return self._result_stats(KIND_DD, self.db.dropdetection, name)

    # -- workers ---------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                name = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                with self._lock:
                    record = self._records.get(name)
                if record is None:    # deleted before it ran
                    continue
                self._run(record)
            finally:
                self._queue.task_done()

    def _run(self, record: JobRecord) -> None:
        record.state = STATE_RUNNING
        record.start_time = time.time()
        logger.v(1).info("job %s started", record.name)
        try:
            if record.kind == KIND_TAD:
                record.progress = JobProgress(record.job_id, TAD_STAGES)
                spec = record.spec
                run_tad(
                    self.db, str(spec.get("jobType", "EWMA")),
                    TadQuerySpec(
                        start_time=spec.get("startInterval") or None,
                        end_time=spec.get("endInterval") or None,
                        ns_ignore_list=spec.get("nsIgnoreList") or (),
                        agg_flow=str(spec.get("aggFlow", "") or ""),
                        pod_label=str(spec.get("podLabel", "") or ""),
                        pod_name=str(spec.get("podName", "") or ""),
                        pod_namespace=str(
                            spec.get("podNameSpace", "") or ""),
                        external_ip=str(spec.get("externalIp", "") or ""),
                        svc_port_name=str(
                            spec.get("servicePortName", "") or ""),
                        cluster_uuid=str(
                            spec.get("clusterUUID", "") or ""),
                        # 0 = auto cadence; absent = reference-exact.
                        refit_every=int(spec["refitEvery"])
                        if spec.get("refitEvery") is not None else 1),
                    tad_id=record.job_id,
                    progress=record.progress)
            elif record.kind == KIND_DD:
                record.progress = JobProgress(record.job_id, DD_STAGES)
                spec = record.spec
                run_drop_detection(
                    self.db,
                    job_type=str(spec.get("jobType", "initial")),
                    detection_id=record.job_id,
                    start_time=spec.get("startInterval") or None,
                    end_time=spec.get("endInterval") or None,
                    cluster_uuid=str(spec.get("clusterUUID", "") or ""),
                    progress=record.progress)
            else:
                record.progress = JobProgress(record.job_id, NPR_STAGES)
                spec = record.spec
                policy_type = validate_policy_type(
                    str(spec.get("policyType", "anp-deny-applied")))
                option = {"anp-deny-applied": 1, "anp-deny-all": 2,
                          "k8s-np": 3}[policy_type]
                run_npr(
                    self.db,
                    recommendation_type=str(spec.get("jobType",
                                                     "initial")),
                    limit=int(spec.get("limit", 0) or 0),
                    option=option,
                    start_time=spec.get("startInterval") or None,
                    end_time=spec.get("endInterval") or None,
                    ns_allow_list=spec.get("nsAllowList") or None,
                    rm_labels=bool(spec.get("excludeLabels", True)),
                    to_services=bool(spec.get("toServices", True)),
                    recommendation_id=record.job_id,
                    progress=record.progress)
            record.state = STATE_COMPLETED
            logger.v(1).info("job %s completed in %.2fs", record.name,
                             time.time() - record.start_time)
        except Exception as e:   # job failure → FAILED CR status
            record.state = STATE_FAILED
            record.error_msg = f"{type(e).__name__}: {e}"
            if record.progress:
                record.progress.fail(record.error_msg)
            logger.error("job %s failed: %s\n%s", record.name,
                         record.error_msg, traceback.format_exc())
        finally:
            record.end_time = time.time()
            # If the CR was deleted while the job ran, its result rows
            # were written after delete()'s GC — clean them up now so
            # in-flight deletes keep the reference's cleanup semantics.
            with self._lock:
                deleted = record.name not in self._records
            if deleted:
                self._delete_results(record.kind, record.job_id)

    def wait_all(self, timeout: float = 60.0) -> bool:
        """Test/CLI helper: block until the queue drains and no job is
        RUNNING."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                busy = any(r.state in (STATE_SCHEDULED, STATE_RUNNING)
                           for r in self._records.values())
            if not busy and self._queue.empty():
                return True
            time.sleep(0.05)
        return False

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)
