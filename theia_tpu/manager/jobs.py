"""Job records + controller state machine for NPR/TAD jobs.

Re-provides the reference's CRD controllers
(pkg/controller/networkpolicyrecommendation/controller.go and
pkg/controller/anomalydetector/controller.go): a job CR moves through
NEW → SCHEDULED → RUNNING → COMPLETED/FAILED (state machine
controller.go:375-427), with progress scraped into status while RUNNING
(:429-456), results garbage-collected when the CR is deleted
(cleanupNPRecommendation :390-403), and stale result rows reconciled
against live CRs at startup (HandleStaleDbEntries util.go:239-270).

Two dispatch modes mirror the reference's two execution tiers:

  dispatch="thread"      — jobs run on in-process worker threads
                           against the shared FlowDatabase (the quick
                           path; no isolation).
  dispatch="subprocess"  — each job runs as a `python -m
                           theia_tpu.runner` child process against a
                           snapshot of the database, with progress
                           scraped from --progress-file and result
                           rows merged back on success. This is the
                           reference's Spark driver/executor process
                           boundary (pkg/controller/util.go:129-159,
                           223-293): a crashing or OOMing kernel kills
                           the RUNNER, not the manager — the record
                           goes FAILED with the child's stderr tail.
                           Device access is serialized across jobs
                           (one child owns the accelerator at a time).
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import os
import queue
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import traceback
import uuid
import zlib
from typing import Dict, List, Optional

import numpy as np

from ..analytics import (TadQuerySpec, run_drop_detection, run_npr,
                         run_pattern_mining, run_spatial, run_tad)
from ..obs import metrics as _obs_metrics
from ..obs import trace as _obs_trace
from ..runner.__main__ import TIME_FORMAT as RUNNER_TIME_FORMAT
from ..runner.__main__ import TRANSIENT_EXIT_CODE
from ..runner.progress import (DD_STAGES, FPM_STAGES, NPR_STAGES,
                               SPATIAL_STAGES, TAD_STAGES,
                               FileProgress, JobProgress)
from ..store import FlowDatabase
from ..utils import get_logger, parse_job_name, validate_policy_type
from ..utils.backoff import capped_backoff
from ..utils.env import env_float, env_int
from ..utils.faults import FaultError
from ..utils.faults import fire as _fire_fault
from ..analysis.lockdep import named_lock

logger = get_logger("jobs")

_M_QUEUE_WAIT = _obs_metrics.histogram(
    "theia_job_queue_wait_seconds",
    "Time from job creation to its first execution attempt")
_M_RUN = _obs_metrics.histogram(
    "theia_job_run_seconds",
    "Wall time of one job execution attempt", labelnames=("kind",))
_M_JOBS = _obs_metrics.counter(
    "theia_jobs_total", "Jobs reaching a terminal state",
    labelnames=("kind", "state"))
_M_RETRIES = _obs_metrics.counter(
    "theia_job_retries_total",
    "Transient job failures re-queued with backoff")
_M_DEADLINE_KILLS = _obs_metrics.counter(
    "theia_job_deadline_kills_total",
    "Runner children killed at deadlineSeconds")

STATE_NEW = "NEW"
STATE_SCHEDULED = "SCHEDULED"
STATE_RUNNING = "RUNNING"
STATE_COMPLETED = "COMPLETED"
STATE_FAILED = "FAILED"

KIND_NPR = "npr"
KIND_TAD = "tad"
KIND_DD = "dd"
KIND_FPM = "fpm"        # frequent flow-pattern mining
KIND_SPATIAL = "sad"    # spatial anomaly detection

_NAME_PREFIX = {KIND_NPR: "pr-", KIND_TAD: "tad-", KIND_DD: "dd-",
                KIND_FPM: "fpm-", KIND_SPATIAL: "sad-"}

#: job kind → its result table in FlowDatabase.result_tables
_RESULT_TABLE = {KIND_NPR: "recommendations", KIND_TAD: "tadetector",
                 KIND_DD: "dropdetection", KIND_FPM: "flowpatterns",
                 KIND_SPATIAL: "spatialnoise"}

_STAGES = {KIND_NPR: NPR_STAGES, KIND_TAD: TAD_STAGES,
           KIND_DD: DD_STAGES, KIND_FPM: FPM_STAGES,
           KIND_SPATIAL: SPATIAL_STAGES}

#: policy mode → job --option (reference recommend_policies_for_
#: unprotected_flows, policy_recommendation_job.py:714); shared by
#: both dispatch paths so they cannot diverge.
POLICY_TYPE_OPTION = {"anp-deny-applied": 1, "anp-deny-all": 2,
                      "k8s-np": 3}


class DuplicateJobError(Exception):
    """A job with this name already exists (→ HTTP 409)."""


class DeadlineExceeded(Exception):
    """The runner child outlived its deadlineSeconds and was killed
    (the Spark Operator's activeDeadlineSeconds role). Terminal: the
    next attempt would hang the same way."""


class TransientJobError(Exception):
    """A failure classification worth retrying — the runner died to a
    signal or fault-injected I/O, never a spec error (those fail
    fast)."""


def _validate_max_len(spec) -> int:
    """Pattern-mining maxLen ∈ {1,2,3}, enforced identically in both
    dispatch modes (the runner's argparse would reject 4+ anyway —
    thread mode must not silently accept what subprocess mode fails).
    Absent → 3; 0 is rejected, not coerced."""
    raw = spec.get("maxLen")
    max_len = 3 if raw is None else int(raw)
    if not 1 <= max_len <= 3:
        raise ValueError(f"maxLen must be 1, 2, or 3, got {max_len}")
    return max_len


def job_id_from_name(kind: str, name: str) -> str:
    """pr-<uuid> / tad-<uuid> → <uuid> (reference ParseRecommendationName
    / ParseADAlgorithmName, pkg/util/utils.go)."""
    return parse_job_name(name, _NAME_PREFIX[kind])


@dataclasses.dataclass
class JobRecord:
    name: str
    kind: str                      # KIND_NPR | KIND_TAD | KIND_DD
    spec: Dict[str, object]
    state: str = STATE_NEW
    error_msg: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    progress: Optional[object] = None   # JobProgress | FileProgress
    runner_pid: int = 0                 # subprocess dispatch only
    runner_log_tail: str = ""           # child stderr tail (bundle)
    max_retries: int = 0                # spec `retries` / controller dflt
    deadline_seconds: float = 0.0       # spec `deadlineSeconds`; 0 = off
    attempts: int = 0                   # completed execution attempts
    last_failure: str = ""              # most recent attempt's failure
    created_time: float = 0.0           # queue-wait measurement anchor

    @property
    def job_id(self) -> str:
        return job_id_from_name(self.kind, self.name)

    def status_dict(self) -> Dict[str, object]:
        completed, total = 0, 0
        if self.progress is not None:
            snap = self.progress.snapshot()
            completed = snap["completedStages"]
            total = snap["totalStages"]
        return {
            "state": self.state,
            "sparkApplication": self.job_id,
            "completedStages": completed,
            "totalStages": total,
            "errorMsg": self.error_msg,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "attempts": self.attempts,
            "retries": self.max_retries,
            "lastFailureReason": self.last_failure,
        }


class JobController:
    """Reconciles job records into analytics runs over a worker pool."""

    def __init__(self, db: FlowDatabase, workers: int = 2,
                 dispatch: str = "thread",
                 alert_sink=None,
                 retries: Optional[int] = None,
                 deadline_seconds: Optional[float] = None,
                 retry_backoff_base: float = 0.5,
                 retry_backoff_cap: float = 30.0) -> None:
        if dispatch not in ("thread", "subprocess"):
            raise ValueError(f"unknown dispatch mode {dispatch!r}")
        self.db = db
        self.dispatch = dispatch
        # Supervision defaults (per-job spec keys override): retry
        # budget for TRANSIENT failures and the runner-child deadline —
        # the Spark Operator's restartPolicy / activeDeadlineSeconds.
        self.default_retries = (env_int("THEIA_JOB_RETRIES", 0)
                                if retries is None else int(retries))
        self.default_deadline = (
            env_float("THEIA_JOB_DEADLINE", 0.0)
            if deadline_seconds is None else float(deadline_seconds))
        self.retry_backoff_base = retry_backoff_base
        self.retry_backoff_cap = retry_backoff_cap
        #: optional callable(dict) — completed spatial jobs push their
        #: noise flows here (the manager wires the ingest alert ring)
        self.alert_sink = alert_sink
        # One job owns the accelerator at a time in subprocess mode:
        # two children would interleave compilations and thrash HBM.
        self._device_lock = named_lock("jobs.device")
        self._records: Dict[str, JobRecord] = {}
        self._lock = named_lock("jobs.controller")
        #: job name → (Timer, record) for retries waiting out their
        #: backoff; cancelled (and the records failed) on shutdown
        self._retry_timers: Dict[str, tuple] = {}
        self._queue: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"job-worker-{i}")
            for i in range(workers)]
        for t in self._threads:
            t.start()
        self.gc_stale_results()

    # -- CRUD ------------------------------------------------------------

    def _spec_retries(self, spec: Dict[str, object]) -> int:
        raw = spec.get("retries")
        n = self.default_retries if raw is None else int(raw)
        if n < 0:
            raise ValueError(f"retries must be >= 0, got {n}")
        return n

    def _spec_deadline(self, spec: Dict[str, object]) -> float:
        raw = spec.get("deadlineSeconds")
        d = self.default_deadline if raw is None else float(raw)
        if d < 0:
            raise ValueError(f"deadlineSeconds must be >= 0, got {d}")
        return d

    def create(self, kind: str, spec: Dict[str, object],
               name: Optional[str] = None) -> JobRecord:
        if name is None:
            name = _NAME_PREFIX[kind] + str(uuid.uuid4())
        job_id_from_name(kind, name)  # validate
        record = JobRecord(name=name, kind=kind, spec=dict(spec),
                           state=STATE_SCHEDULED,
                           max_retries=self._spec_retries(spec),
                           deadline_seconds=self._spec_deadline(spec),
                           created_time=time.time())
        if record.deadline_seconds and self.dispatch == "thread":
            # an in-process job shares our interpreter; Python offers
            # no safe thread kill, so only subprocess dispatch can
            # enforce the deadline — say so instead of silently not
            logger.error("job %s: deadlineSeconds=%g is not "
                         "enforceable under thread dispatch (a hung "
                         "in-process job cannot be killed); use "
                         "--dispatch subprocess for deadline "
                         "supervision", name, record.deadline_seconds)
        with self._lock:
            if name in self._records:
                raise DuplicateJobError(f"job {name} already exists")
            self._records[name] = record
        self._queue.put(name)
        return record

    def get(self, name: str) -> JobRecord:
        with self._lock:
            return self._records[name]

    def list(self, kind: Optional[str] = None) -> List[JobRecord]:
        with self._lock:
            records = list(self._records.values())
        if kind:
            records = [r for r in records if r.kind == kind]
        return records

    def delete(self, name: str) -> None:
        """Remove the CR and GC its result rows (reference
        cleanupNPRecommendation deletes recommendations by id)."""
        with self._lock:
            record = self._records.pop(name)
        self._delete_results(record.kind, record.job_id)

    # -- GC --------------------------------------------------------------

    def gc_stale_results(self) -> int:
        """Drop result rows whose job CR no longer exists (reference
        HandleStaleDbEntries, run from the controller gcQueue at
        startup)."""
        with self._lock:
            live = {r.job_id for r in self._records.values()}
        removed = 0
        for table in self.db.result_tables.values():
            if not any(c.name == "id" for c in table.schema):
                # not a job-results table (the `__metrics__` history
                # table rides result_tables for WAL/replication but
                # has no job id — its own retention owns deletion)
                continue
            # value-based delete: identical logical rows can sit in
            # different physical orders across shards/replicas, so a
            # positional mask would be wrong there
            removed += table.delete_ids(live, invert=True)
        return removed

    def _delete_results(self, kind: str, job_id: str) -> None:
        self.db.result_tables[_RESULT_TABLE[kind]].delete_ids([job_id])

    # -- result retrieval ------------------------------------------------

    def recommendation_outcome(self, name: str) -> str:
        """Joined policy YAML for a COMPLETED NPR job (reference
        getRecommendationResult joins rows with '---\\n', rest.go:213)."""
        job_id = job_id_from_name(KIND_NPR, name)
        data = self.db.recommendations.scan()
        if not len(data):
            return ""
        rows = data.filter(data.strings("id") == job_id)
        return "---\n".join(rows.strings("policy"))

    def _result_stats(self, kind: str, table,
                      name: str) -> List[Dict[str, str]]:
        """Result rows for a job as string-typed stat entries
        (reference getTADetectorResult, rest.go:249-310)."""
        job_id = job_id_from_name(kind, name)
        data = table.scan()
        if not len(data):
            return []
        rows = data.filter(data.strings("id") == job_id)
        return [{k: str(v) for k, v in row.items()}
                for row in rows.to_rows()]

    def tad_stats(self, name: str) -> List[Dict[str, str]]:
        return self._result_stats(KIND_TAD, self.db.tadetector, name)

    def drop_detection_stats(self, name: str) -> List[Dict[str, str]]:
        return self._result_stats(KIND_DD, self.db.dropdetection, name)

    def result_stats(self, kind: str, name: str) -> List[Dict[str, str]]:
        """Generic result rows for any job kind (the per-kind helpers
        above remain for the established call sites)."""
        return self._result_stats(
            kind, self.db.result_tables[_RESULT_TABLE[kind]], name)

    # -- workers ---------------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                name = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                with self._lock:
                    record = self._records.get(name)
                if record is None:    # deleted before it ran
                    continue
                self._run(record)
            finally:
                self._queue.task_done()

    @staticmethod
    def _is_transient(e: BaseException) -> bool:
        """Retry-worthy failure classes: the runner died to a signal or
        injected I/O fault. Spec/validation errors and deadline kills
        stay terminal (they would fail identically on every retry)."""
        return isinstance(e, (TransientJobError, FaultError))

    def _retry_delay(self, record: JobRecord) -> float:
        """Exponential backoff with DETERMINISTIC jitter (crc32 of
        name+attempt → a [1.0, 1.5) factor): a retry herd spreads out,
        and a test replaying the same job sees the same schedule. The
        cap bounds the base schedule and the jitter rides on top —
        clamping after jitter would re-synchronize every capped-out
        retry to exactly the cap, recreating the herd."""
        frac = (zlib.crc32(
            f"{record.name}:{record.attempts}".encode()) % 1000) / 1000.0
        return capped_backoff(self.retry_backoff_base,
                              self.retry_backoff_cap,
                              record.attempts) * (1.0 + 0.5 * frac)

    def _on_failure(self, record: JobRecord, e: BaseException) -> None:
        """FAILED — or, for a transient failure with retry budget left,
        re-queue after a backoff. The backoff runs on a timer, not in
        the worker (a worker parked in sleep would starve healthy
        SCHEDULED jobs); the record stays SCHEDULED through the delay,
        so wait_all() keeps waiting on it."""
        msg = f"{type(e).__name__}: {e}"
        record.last_failure = msg
        retryable = (self._is_transient(e)
                     and record.attempts <= record.max_retries
                     and not self._deleted(record)
                     and not self._stop.is_set())
        if retryable:
            _M_RETRIES.inc()
            delay = self._retry_delay(record)
            record.state = STATE_SCHEDULED
            logger.error("job %s attempt %d/%d failed (%s); retrying "
                         "in %.2fs", record.name, record.attempts,
                         record.max_retries + 1, msg, delay)

            def _requeue() -> None:
                with self._lock:
                    self._retry_timers.pop(record.name, None)
                if self._stop.is_set() or self._deleted(record):
                    record.state = STATE_FAILED
                    record.error_msg = msg
                else:
                    self._queue.put(record.name)

            timer = threading.Timer(delay, _requeue)
            timer.daemon = True
            with self._lock:
                self._retry_timers[record.name] = (timer, record)
            timer.start()
            return
        record.state = STATE_FAILED
        record.error_msg = msg
        _M_JOBS.labels(kind=record.kind, state="failed").inc()
        if record.progress:
            record.progress.fail(msg)
        logger.error("job %s failed: %s\n%s", record.name, msg,
                     traceback.format_exc())

    def _run(self, record: JobRecord) -> None:
        record.state = STATE_RUNNING
        record.attempts += 1
        record.start_time = time.time()
        if record.attempts == 1 and record.created_time:
            _M_QUEUE_WAIT.observe(
                max(0.0, record.start_time - record.created_time))
        logger.v(1).info("job %s started (%s, attempt %d)", record.name,
                         self.dispatch, record.attempts)
        try:
            # a trace ingress: each run is its own trace root, so the
            # spans of whatever the job touches stitch under one id
            with _obs_trace.ingress_span("job.run", job=record.name,
                                         kind=record.kind,
                                         attempt=record.attempts):
                if self.dispatch == "subprocess":
                    self._run_subprocess(record)
                else:
                    self._run_inprocess(record)
            record.state = STATE_COMPLETED
            _M_JOBS.labels(kind=record.kind, state="completed").inc()
            logger.v(1).info("job %s completed in %.2fs", record.name,
                             time.time() - record.start_time)
            if record.kind == KIND_SPATIAL and self.alert_sink:
                try:
                    # best-effort side effect: a sink failure must not
                    # flip a COMPLETED job to FAILED
                    self._push_spatial_alerts(record)
                except Exception:
                    logger.error("job %s: alert push failed\n%s",
                                 record.name, traceback.format_exc())
        except Exception as e:   # job failure → FAILED CR or retry
            self._on_failure(record, e)
        finally:
            record.end_time = time.time()
            _M_RUN.labels(kind=record.kind).observe(
                max(0.0, record.end_time - record.start_time))
            # If the CR was deleted while the job ran, its result rows
            # were written after delete()'s GC — clean them up now so
            # in-flight deletes keep the reference's cleanup semantics.
            # (Identity check: a same-named recreation owns the name
            # and its results now.)
            if self._deleted(record):
                self._delete_results(record.kind, record.job_id)

    def _push_spatial_alerts(self, record: JobRecord) -> None:
        """Surface a completed spatial job's noise flows on the live
        alert surface (GET /alerts) — batch results feed the streaming
        ring the way the reference's batch TAD never could. Reads the
        result table directly (result_stats stringifies every value;
        alerts carry native types like the other alert kinds)."""
        table = self.db.result_tables[_RESULT_TABLE[KIND_SPATIAL]]
        data = table.scan()
        if not len(data):
            return
        rows = data.filter(data.strings("id") == record.job_id)
        # Cap the push: the alert ring is a bounded shared surface
        # (ingest.MAX_ALERTS slots) — one large batch result must not
        # evict every live streaming/heavy-hitter alert. Keep the
        # highest-volume noise flows; the full set stays queryable via
        # the job's results.
        cap = 100
        if len(rows) > cap:
            logger.info(
                "job %s: %d noise flows; publishing top %d by bytes",
                record.name, len(rows), cap)
            top = np.argsort(
                np.asarray(rows["octetDeltaCount"]))[-cap:][::-1]
            rows = rows.take(top)
        src = rows.strings("sourceIP")
        dst = rows.strings("destinationIP")
        ports = np.asarray(rows["destinationTransportPort"])
        octets = np.asarray(rows["octetDeltaCount"])
        for i in range(len(rows)):
            self.alert_sink({
                "kind": "spatial_noise",
                "job": record.name,
                "sourceIP": str(src[i]),
                "destinationIP": str(dst[i]),
                "destinationTransportPort": int(ports[i]),
                "octetDeltaCount": int(octets[i]),
            })

    def _run_inprocess(self, record: JobRecord) -> None:
        # same site the runner child fires in subprocess dispatch, so
        # a transient execution fault is injectable in both modes
        _fire_fault("runner.exec", job=record.name)
        spec = record.spec
        if record.kind == KIND_FPM:
            from ..analytics.itemsets import DEFAULT_COLUMNS
            record.progress = JobProgress(record.job_id, FPM_STAGES)
            run_pattern_mining(
                self.db,
                min_support=int(spec.get("minSupport", 0) or 0),
                columns=tuple(spec.get("columns") or DEFAULT_COLUMNS),
                max_len=_validate_max_len(spec),
                start_time=spec.get("startInterval") or None,
                end_time=spec.get("endInterval") or None,
                mining_id=record.job_id,
                progress=record.progress)
            return
        if record.kind == KIND_SPATIAL:
            from ..analytics.spatial import (DEFAULT_EPS,
                                             DEFAULT_MIN_SAMPLES)
            record.progress = JobProgress(record.job_id,
                                          SPATIAL_STAGES)
            run_spatial(
                self.db,
                eps=float(spec.get("eps") or DEFAULT_EPS),
                min_samples=int(spec.get("minSamples")
                                or DEFAULT_MIN_SAMPLES),
                start_time=spec.get("startInterval") or None,
                end_time=spec.get("endInterval") or None,
                spatial_id=record.job_id,
                progress=record.progress)
            return
        if record.kind == KIND_TAD:
            record.progress = JobProgress(record.job_id, TAD_STAGES)
            run_tad(
                self.db, str(spec.get("jobType", "EWMA")),
                TadQuerySpec(
                    start_time=spec.get("startInterval") or None,
                    end_time=spec.get("endInterval") or None,
                    ns_ignore_list=spec.get("nsIgnoreList") or (),
                    agg_flow=str(spec.get("aggFlow", "") or ""),
                    pod_label=str(spec.get("podLabel", "") or ""),
                    pod_name=str(spec.get("podName", "") or ""),
                    pod_namespace=str(
                        spec.get("podNameSpace", "") or ""),
                    external_ip=str(spec.get("externalIp", "") or ""),
                    svc_port_name=str(
                        spec.get("servicePortName", "") or ""),
                    cluster_uuid=str(
                        spec.get("clusterUUID", "") or ""),
                    # 0 = auto cadence; absent = reference-exact.
                    refit_every=int(spec["refitEvery"])
                    if spec.get("refitEvery") is not None else 1),
                tad_id=record.job_id,
                progress=record.progress)
        elif record.kind == KIND_DD:
            record.progress = JobProgress(record.job_id, DD_STAGES)
            run_drop_detection(
                self.db,
                job_type=str(spec.get("jobType", "initial")),
                detection_id=record.job_id,
                start_time=spec.get("startInterval") or None,
                end_time=spec.get("endInterval") or None,
                cluster_uuid=str(spec.get("clusterUUID", "") or ""),
                progress=record.progress)
        else:
            record.progress = JobProgress(record.job_id, NPR_STAGES)
            policy_type = validate_policy_type(
                str(spec.get("policyType", "anp-deny-applied")))
            option = POLICY_TYPE_OPTION[policy_type]
            run_npr(
                self.db,
                recommendation_type=str(spec.get("jobType",
                                                 "initial")),
                limit=int(spec.get("limit", 0) or 0),
                option=option,
                start_time=spec.get("startInterval") or None,
                end_time=spec.get("endInterval") or None,
                ns_allow_list=spec.get("nsAllowList") or None,
                rm_labels=bool(spec.get("excludeLabels", True)),
                to_services=bool(spec.get("toServices", True)),
                recommendation_id=record.job_id,
                progress=record.progress)

    # -- subprocess dispatch ---------------------------------------------

    def _fmt_time(self, value) -> str:
        # RUNNER_TIME_FORMAT: the runner CLI's own constant, so the
        # controller's formatting can't drift from its parsing.
        return datetime.datetime.fromtimestamp(
            int(value), tz=datetime.timezone.utc
        ).strftime(RUNNER_TIME_FORMAT)

    def _runner_args(self, record: JobRecord) -> List[str]:
        """record.spec → the runner's Spark-job-shaped CLI argv
        (reverse of the controllers' arg-build,
        pkg/controller/anomalydetector/controller.go:525-620).
        Validation errors raise here, before a process is spawned."""
        spec = record.spec
        args: List[str] = []
        if record.kind == KIND_TAD:
            args += ["tad", "--algo", str(spec.get("jobType", "EWMA"))]
            if spec.get("nsIgnoreList"):
                args += ["-n", json.dumps(spec["nsIgnoreList"])]
            for flag, key in (
                    ("--agg-flow", "aggFlow"),
                    ("--pod-label", "podLabel"),
                    ("--pod-name", "podName"),
                    ("--pod-namespace", "podNameSpace"),
                    ("--external-ip", "externalIp"),
                    ("--svc-port-name", "servicePortName"),
                    ("--cluster-uuid", "clusterUUID")):
                if spec.get(key):
                    args += [flag, str(spec[key])]
            if spec.get("refitEvery") is not None:
                args += ["--refit-every", str(int(spec["refitEvery"]))]
        elif record.kind == KIND_DD:
            args += ["dropdetection",
                     "-t", str(spec.get("jobType", "initial"))]
            if spec.get("clusterUUID"):
                args += ["--cluster-uuid", str(spec["clusterUUID"])]
        elif record.kind == KIND_FPM:
            args += ["patterns",
                     "-m", str(int(spec.get("minSupport", 0) or 0)),
                     "--max-len", str(_validate_max_len(spec))]
            if spec.get("columns"):
                args += ["-c", ",".join(spec["columns"])]
        elif record.kind == KIND_SPATIAL:
            args += ["spatial"]
            if spec.get("eps"):
                args += ["--eps", str(float(spec["eps"]))]
            if spec.get("minSamples"):
                args += ["--min-samples", str(int(spec["minSamples"]))]
        else:
            policy_type = validate_policy_type(
                str(spec.get("policyType", "anp-deny-applied")))
            option = POLICY_TYPE_OPTION[policy_type]
            args += ["npr",
                     "-t", str(spec.get("jobType", "initial")),
                     "-l", str(int(spec.get("limit", 0) or 0)),
                     "-o", str(option),
                     "--rm_labels",
                     "true" if spec.get("excludeLabels", True)
                     else "false",
                     "--to_services",
                     "true" if spec.get("toServices", True)
                     else "false"]
            if spec.get("nsAllowList"):
                args += ["-n", json.dumps(spec["nsAllowList"])]
        if spec.get("startInterval"):
            args += ["-s", self._fmt_time(spec["startInterval"])]
        if spec.get("endInterval"):
            args += ["-e", self._fmt_time(spec["endInterval"])]
        args += ["-i", record.job_id]
        return args

    def _runner_cmd(self, record: JobRecord, snap: str,
                    progress_file: str) -> List[str]:
        """Full child argv. Split out so tests can substitute a
        controllable child process."""
        return ([sys.executable, "-m", "theia_tpu.runner"]
                + self._runner_args(record)
                + ["--db", snap, "--progress-file", progress_file,
                   "--out", snap + ".results.npz"])

    def _deleted(self, record: JobRecord) -> bool:
        """True when THIS record left the table — identity, not name:
        a same-named recreation must not keep a doomed child alive
        (or let a deleted one's results land)."""
        with self._lock:
            return self._records.get(record.name) is not record

    def _run_subprocess(self, record: JobRecord) -> None:
        """One job = one runner child over a database snapshot; the
        process boundary is the failure domain (reference Spark
        driver/executor isolation)."""
        stages = _STAGES[record.kind]
        workdir = tempfile.mkdtemp(
            prefix=f"theia-job-{record.job_id[:8]}-")
        try:
            snap = os.path.join(workdir, "db.npz")
            progress_file = os.path.join(workdir, "progress.json")
            # argv build doubles as spec validation — errors raise here,
            # before the snapshot/spawn costs.
            cmd = self._runner_cmd(record, snap, progress_file)
            record.progress = FileProgress(record.job_id, stages,
                                           progress_file)
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            env = {**os.environ,
                   "PYTHONPATH": pkg_root + os.pathsep +
                   os.environ.get("PYTHONPATH", "")}
            # Snapshot outside the device lock (table scans are
            # thread-safe): only the child's device tenure serializes.
            # Uncompressed — a short-lived handoff file, not a durable
            # checkpoint.
            self.db.save(snap, compress=False)
            # Child output goes to files, not PIPEs: an undrained pipe
            # fills at ~64 KiB and deadlocks a chatty child against
            # our wait() loop.
            err_path = os.path.join(workdir, "stderr.log")
            _fire_fault("runner.spawn", job=record.name)
            deadline_s = record.deadline_seconds
            deadline_hit = False
            with open(os.path.join(workdir, "stdout.log"), "wb") as out_f, \
                    open(err_path, "wb") as err_f, \
                    self._device_lock:
                t_spawn = time.monotonic()
                proc = subprocess.Popen(
                    cmd, stdout=out_f, stderr=err_f, env=env,
                    cwd=workdir)
                record.runner_pid = proc.pid
                try:
                    while True:
                        try:
                            proc.wait(timeout=0.2)
                            break
                        except subprocess.TimeoutExpired:
                            if self._deleted(record):  # delete cancels
                                proc.kill()
                            elif self._stop.is_set():
                                # controller shutdown must not orphan
                                # a running child (it would keep the
                                # accelerator claimed past the
                                # manager's death)
                                proc.kill()
                            elif (deadline_s and not deadline_hit
                                  and time.monotonic() - t_spawn
                                  > deadline_s):
                                # a hung child would otherwise hold
                                # this worker AND the device lock
                                # forever (the Spark Operator's
                                # activeDeadlineSeconds kill)
                                deadline_hit = True
                                proc.kill()
                except BaseException:
                    proc.kill()
                    proc.wait()
                    raise
            # final scrape before the scratch dir goes away
            record.progress.snapshot()
            try:
                # keep the child's stderr tail on the record — the
                # support bundle's runner-log source (the reference
                # dumper copies Spark driver/executor pod logs,
                # pkg/support/dump.go:55-66)
                with open(err_path, "rb") as f:
                    record.runner_log_tail = f.read()[-8192:].decode(
                        errors="replace")
            except OSError:
                pass
            if deadline_hit:
                _M_DEADLINE_KILLS.inc()
                raise DeadlineExceeded(
                    f"runner exceeded deadlineSeconds={deadline_s:g} "
                    f"and was killed")
            if proc.returncode != 0:
                tail = " | ".join(record.runner_log_tail
                                  .strip().splitlines()[-5:])
                suffix = f": {tail}" if tail else ""
                if proc.returncode < 0:
                    # signal deaths (OOM kill, node reaper) are the
                    # transient class the reference's Spark Operator
                    # restartPolicy retries
                    raise TransientJobError(
                        f"runner killed by signal {-proc.returncode}"
                        + suffix)
                if proc.returncode == TRANSIENT_EXIT_CODE:
                    raise TransientJobError(
                        f"runner transient failure (exit "
                        f"{TRANSIENT_EXIT_CODE})" + suffix)
                raise RuntimeError(
                    f"runner exited {proc.returncode}" + suffix)
            self._merge_results(record, snap + ".results.npz")
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def _merge_results(self, record: JobRecord, results: str) -> None:
        """Copy the job's result rows from the runner's results-only
        snapshot into the live database (dictionary re-encode happens
        in Table insert adoption)."""
        try:
            out = FlowDatabase.load(results, build_views=False)
        except FileNotFoundError:
            # Contract violation (rc=0 but no results file) — only
            # reachable with a substituted child; don't fail the job,
            # just record it.
            logger.error("job %s: runner wrote no results file %s",
                         record.name, results)
            return
        table_name = _RESULT_TABLE[record.kind]
        src = out.result_tables[table_name]
        dst = self.db.result_tables[table_name]
        data = src.scan()
        if len(data):
            rows = data.filter(data.strings("id") == record.job_id)
            if len(rows):
                dst.insert(rows)

    def health(self) -> Dict[str, object]:
        """Operator health view (served by GET /healthz): queue depth
        plus record counts by state, with in-backoff retries broken
        out (they are SCHEDULED records that already failed once)."""
        with self._lock:
            records = list(self._records.values())
        states = {STATE_SCHEDULED: 0, STATE_RUNNING: 0,
                  STATE_COMPLETED: 0, STATE_FAILED: 0}
        retrying = 0
        for r in records:
            states[r.state] = states.get(r.state, 0) + 1
            if r.state == STATE_SCHEDULED and r.attempts:
                retrying += 1
        return {
            "queueDepth": self._queue.qsize(),
            "records": len(records),
            "scheduled": states[STATE_SCHEDULED],
            "running": states[STATE_RUNNING],
            "completed": states[STATE_COMPLETED],
            "failed": states[STATE_FAILED],
            "retrying": retrying,
            "workers": len(self._threads),
            "dispatch": self.dispatch,
        }

    def wait_all(self, timeout: float = 60.0) -> bool:
        """Test/CLI helper: block until the queue drains and no job is
        RUNNING."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                busy = any(r.state in (STATE_SCHEDULED, STATE_RUNNING)
                           for r in self._records.values())
            if not busy and self._queue.empty():
                return True
            time.sleep(0.05)
        return False

    def shutdown(self) -> None:
        self._stop.set()
        # Retries parked on a backoff timer will never run now: cancel
        # the timers and fail their records with the last failure (the
        # same terminal state the retry would reach under stop).
        with self._lock:
            pending = list(self._retry_timers.values())
            self._retry_timers.clear()
        for timer, record in pending:
            timer.cancel()
            record.state = STATE_FAILED
            record.error_msg = record.last_failure
        # Generous join: a subprocess worker needs time to kill its
        # child (stop flag is polled every 0.2s in the wait loop) and
        # run its cleanup (workdir rmtree) — a 2s give-up would orphan
        # both.
        for t in self._threads:
            t.join(timeout=15)
        for t in self._threads:
            if t.is_alive():
                logger.error("job worker %s did not stop", t.name)
