"""Control plane: job controllers + aggregated REST API."""

from .api import API_PORT, TheiaManagerServer
from .jobs import (
    KIND_DD,
    KIND_FPM,
    KIND_NPR,
    KIND_SPATIAL,
    KIND_TAD,
    STATE_COMPLETED,
    STATE_FAILED,
    STATE_NEW,
    STATE_RUNNING,
    STATE_SCHEDULED,
    JobController,
    JobRecord,
    job_id_from_name,
)
from .stats import StatsProvider

__all__ = [
    "API_PORT", "TheiaManagerServer",
    "JobController", "JobRecord", "job_id_from_name",
    "KIND_NPR", "KIND_TAD", "KIND_DD", "KIND_FPM", "KIND_SPATIAL",
    "STATE_NEW", "STATE_SCHEDULED", "STATE_RUNNING", "STATE_COMPLETED",
    "STATE_FAILED",
    "StatsProvider",
]
