"""Declarative CR reconciler: desired-state YAML → job records.

The reference's control plane is CRD-driven: operators `kubectl apply`
NetworkPolicyRecommendation / ThroughputAnomalyDetector CRs and the
controllers reconcile them into running jobs via informers + workqueues
(pkg/controller/networkpolicyrecommendation/controller.go:118-130,
336-388). This module provides the same declarative semantics against
a DIRECTORY of CR YAML documents — the GitOps-shaped seam a kube
informer plugs into unchanged:

  * a CR file appearing  → job created (same kinds, same spec keys as
    the REST API)
  * the CR file removed  → job deleted, result rows GC'd
    (cleanupNPRecommendation semantics)
  * status written back as `<name>.status.yaml` beside the CR, carrying
    the NEW→SCHEDULED→RUNNING→COMPLETED/FAILED state machine

Reconciliation is level-triggered and idempotent: every pass compares
the full desired set against the controller's records, exactly like a
resync (controller.go:324-334); only resources this reconciler created
are subject to its deletion logic, so REST-created jobs are never
collected. CR specs are treated as immutable once admitted (the
reference controllers never re-run a mutated CR either).

Enable with `python -m theia_tpu.manager --reconcile-dir <dir>`.
The matching CustomResourceDefinition manifests come from
`deploy/generate_manifest.py --crds`.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..obs import metrics as _metrics
from ..utils import get_logger
from ..utils.backoff import capped_backoff
from ..utils.faults import fire as _fire_fault
from .jobs import (KIND_DD, KIND_FPM, KIND_NPR, KIND_SPATIAL,
                   KIND_TAD, STATE_COMPLETED, STATE_FAILED,
                   DuplicateJobError)

logger = get_logger("reconciler")

_M_PASSES = _metrics.counter(
    "theia_reconciler_passes_total",
    "Reconcile passes over the CR directory, by outcome",
    labelnames=("result",))
_M_OBJECTS = _metrics.counter(
    "theia_reconciler_objects_total",
    "CRs admitted into / deleted from the controller by the "
    "reconciler", labelnames=("action",))

CRD_GROUP = "crd.theia.antrea.io"
API_VERSION = f"{CRD_GROUP}/v1alpha1"

#: CR kind → controller job kind (reference pkg/apis/crd/v1alpha1)
KIND_BY_CR = {
    "NetworkPolicyRecommendation": KIND_NPR,
    "ThroughputAnomalyDetector": KIND_TAD,
    "TrafficDropDetection": KIND_DD,
    "FlowPatternMining": KIND_FPM,
    "SpatialAnomalyDetection": KIND_SPATIAL,
}

_STATUS_SUFFIX = ".status.yaml"


class DeclarativeReconciler:
    """Level-triggered reconcile loop over a CR directory."""

    def __init__(self, controller, directory: str,
                 interval: float = 2.0) -> None:
        self.controller = controller
        self.directory = directory
        self.interval = interval
        #: names this reconciler admitted — the only ones it may delete
        self._owned: set = set()
        #: terminally rejected specs (name → spec) so a bad CR is
        #: logged once, not every pass; retried if the spec changes
        self._rejected: Dict[str, tuple] = {}
        #: last status written per name — unchanged statuses skip the
        #: disk write (and the watcher events it would trigger)
        self._last_status: Dict[str, dict] = {}
        #: CRs whose status file already records a terminal state —
        #: skipped (and logged) once, not re-read every pass
        self._terminal: Dict[str, str] = {}
        #: cap for the consecutive-failure backoff (injectable so
        #: tests exercise the schedule without long sleeps)
        self.backoff_cap = 60.0
        self.consecutive_failures = 0
        self.current_delay = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="theia-reconciler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=15)

    def _loop(self) -> None:
        # Capped exponential backoff on CONSECUTIVE pass failures: a
        # broken directory (unmountable volume, permission flip) is
        # probed, not hammered every 2s; the first clean pass resets
        # to the level-triggered cadence.
        while not self._stop.wait(self.current_delay):
            try:
                self.reconcile_once()
            except Exception as e:   # keep reconciling after bad input
                _M_PASSES.labels(result="error").inc()
                self.consecutive_failures += 1
                self.current_delay = capped_backoff(
                    self.interval * 2, self.backoff_cap,
                    self.consecutive_failures)
                logger.error("reconcile pass failed (%d consecutive): "
                             "%s; backing off %.1fs",
                             self.consecutive_failures, e,
                             self.current_delay)
            else:
                if self.consecutive_failures:
                    logger.info("reconcile recovered after %d failed "
                                "passes", self.consecutive_failures)
                self.consecutive_failures = 0
                self.current_delay = self.interval

    # -- one pass ---------------------------------------------------------

    def _desired(self) -> Dict[str, Tuple[str, dict]]:
        """name → (job kind, spec) from every CR document on disk.
        Malformed files are skipped with a log line (a bad apply must
        not stall the rest of the directory — workqueue semantics)."""
        import yaml

        out: Dict[str, Tuple[str, dict]] = {}
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return out
        for fname in names:
            if not fname.endswith((".yaml", ".yml")) or \
                    fname.endswith(_STATUS_SUFFIX):
                continue
            path = os.path.join(self.directory, fname)
            try:
                with open(path) as f:
                    docs = list(yaml.safe_load_all(f))
            except Exception as e:
                logger.error("skipping unreadable CR file %s: %s",
                             fname, e)
                continue
            for doc in docs:
                if not isinstance(doc, dict):
                    continue
                kind = KIND_BY_CR.get(str(doc.get("kind", "")))
                api = str(doc.get("apiVersion", ""))
                name = (doc.get("metadata") or {}).get("name")
                # exact group/version match: a foreign group or a
                # future v2 must not be silently run under v1alpha1
                # spec semantics
                if kind is None or api != API_VERSION or not name:
                    continue
                spec = doc.get("spec") or {}
                if not isinstance(spec, dict):
                    logger.error("CR %s in %s: spec must be a mapping",
                                 name, fname)
                    continue
                name = str(name)
                if name in out:
                    logger.error(
                        "duplicate CR name %s (also in %s): keeping "
                        "the lexicographically-last file's spec",
                        name, fname)
                out[name] = (kind, spec)
        return out

    def reconcile_once(self) -> Dict[str, int]:
        _fire_fault("reconciler.pass", directory=self.directory)
        desired = self._desired()
        current = {r.name: r for r in self.controller.list()}
        created = deleted = 0

        for name, (kind, spec) in desired.items():
            if name in current:
                continue
            fingerprint = (kind, repr(sorted(spec.items())))
            if self._rejected.get(name) == fingerprint:
                continue   # logged once; retried only if spec changes
            if name not in self._terminal:
                state = self._terminal_state_on_disk(name)
                if state is not None:
                    # The CR already ran to a terminal state in a
                    # previous manager life (the status file beside it
                    # is the durable record — the reference controllers
                    # never re-execute a completed CR either). Claim
                    # ownership so the status file is GC'd with the CR.
                    self._terminal[name] = state
                    self._owned.add(name)
                    logger.v(1).info(
                        "CR %s already %s (status file); not "
                        "re-admitting after restart", name, state)
            if name in self._terminal:
                continue
            try:
                self.controller.create(kind, spec, name=name)
                self._owned.add(name)
                self._rejected.pop(name, None)
                created += 1
                logger.v(1).info("admitted CR %s", name)
            except (DuplicateJobError, ValueError) as e:
                self._rejected[name] = fingerprint
                logger.error("CR %s rejected: %s", name, e)

        # deletion: only resources this reconciler admitted, and only
        # once their CR file is gone
        for name in list(self._owned):
            if name in desired:
                continue
            if name in current:
                try:
                    self.controller.delete(name)
                    deleted += 1
                    logger.v(1).info("deleted CR %s (file removed)",
                                     name)
                except KeyError:
                    pass   # raced a REST delete — already gone
            # drop ownership only after the delete attempt, so a
            # failure here retries next pass instead of orphaning
            # the record and its status file
            self._owned.discard(name)
            self._remove_status(name)
            self._last_status.pop(name, None)
            self._terminal.pop(name, None)

        self._write_statuses(desired)
        _M_PASSES.labels(result="ok").inc()
        if created:
            _M_OBJECTS.labels(action="created").inc(created)
        if deleted:
            _M_OBJECTS.labels(action="deleted").inc(deleted)
        return {"desired": len(desired), "created": created,
                "deleted": deleted}

    # -- status write-back --------------------------------------------------

    def _status_path(self, name: str) -> str:
        return os.path.join(self.directory, name + _STATUS_SUFFIX)

    def _terminal_state_on_disk(self, name: str) -> Optional[str]:
        """COMPLETED/FAILED from `<name>.status.yaml` if the CR already
        ran to completion (written atomically by _write_statuses), else
        None. Unreadable/missing/non-terminal statuses mean the CR is
        still due to run — a crash mid-run re-runs, a finished run
        never does."""
        import yaml

        try:
            with open(self._status_path(name)) as f:
                doc = yaml.safe_load(f)
        except OSError:
            return None
        except Exception:
            return None   # torn/foreign file: treat as no status
        if not isinstance(doc, dict):
            return None
        state = ((doc.get("status") or {}).get("state")
                 if isinstance(doc.get("status"), dict) else None)
        return state if state in (STATE_COMPLETED, STATE_FAILED) \
            else None

    def _remove_status(self, name: str) -> None:
        try:
            os.unlink(self._status_path(name))
        except OSError:
            pass

    def _write_statuses(self, desired) -> None:
        import yaml

        from ..utils import atomic_write
        for name in desired:
            try:
                record = self.controller.get(name)
            except KeyError:
                continue
            doc = {"name": name, "status": record.status_dict()}
            if self._last_status.get(name) == doc:
                continue   # terminal statuses stop churning the disk

            def write(tmp: str, doc=doc) -> None:
                with open(tmp, "w") as f:
                    yaml.safe_dump(doc, f)

            atomic_write(self._status_path(name), write)
            self._last_status[name] = doc
