"""On-demand XLA profiler capture over the system API.

SURVEY §5/§7.7: the reference's only runtime introspection is
scraping the Spark UI REST and ClickHouse system tables
(pkg/apiserver/utils/stats/clickhouse_stats.go:92-117 dumps
system.stack_trace); it has no accelerator profiler at all. Here the
manager can capture a real XLA profile of whatever the engine is
doing — device kernels, host callbacks, transfers — and hand back the
trace directory as a tar.gz that loads straight into TensorBoard /
Perfetto / xprof.

    POST /apis/system.theia.antrea.io/v1alpha1/profiles
        body: {"durationSeconds": N}   (default 3, capped)
    GET  .../profiles                  → {"status": ..., "size": ...}
    GET  .../profiles/theia-manager/download → tar.gz

One capture at a time (the profiler cannot nest); bearer-token
protected with the rest of the system group.
"""

from __future__ import annotations

import io
import os
import shutil
import tarfile
import tempfile
import time
from typing import Dict

from ..utils import get_logger
from .collect import AsyncCollector

logger = get_logger("profiling")

MAX_DURATION_SECONDS = 60.0


class ProfileManager(AsyncCollector):
    """Async single-flight XLA trace collection."""

    kind = "Profile"

    def __init__(self) -> None:
        super().__init__()
        self.duration: float = 0.0

    def create(self, duration_seconds: float = 3.0) -> Dict[str, object]:
        self.duration = min(max(float(duration_seconds), 0.1),
                            MAX_DURATION_SECONDS)
        return super().create(self.duration)

    def _extra_status(self) -> Dict[str, object]:
        return {"durationSeconds": self.duration}

    def _collect(self, duration: float) -> bytes:
        import jax

        tmpdir = tempfile.mkdtemp(prefix="theia-xprof-")
        try:
            jax.profiler.start_trace(tmpdir)
            try:
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                for root, _dirs, files in os.walk(tmpdir):
                    for f in files:
                        full = os.path.join(root, f)
                        tar.add(full,
                                arcname=os.path.relpath(full, tmpdir))
            logger.v(1).info("profile captured: %.1fs", duration)
            return buf.getvalue()
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)
