"""On-demand XLA profiler capture over the system API.

SURVEY §5/§7.7: the reference's only runtime introspection is
scraping the Spark UI REST and ClickHouse system tables
(pkg/apiserver/utils/stats/clickhouse_stats.go:92-117 dumps
system.stack_trace); it has no accelerator profiler at all. Here the
manager can capture a real XLA profile of whatever the engine is
doing — device kernels, host callbacks, transfers — and hand back the
trace directory as a tar.gz that loads straight into TensorBoard /
Perfetto / xprof.

    POST /apis/system.theia.antrea.io/v1alpha1/profiles
        body: {"durationSeconds": N}   (default 3, capped)
    GET  .../profiles                  → {"status": ..., "size": ...}
    GET  .../profiles/theia-manager/download → tar.gz

One capture at a time (the profiler cannot nest); bearer-token
protected with the rest of the system group.
"""

from __future__ import annotations

import io
import os
import shutil
import tarfile
import tempfile
import threading
import time
from typing import Dict, Optional

from ..utils import get_logger

logger = get_logger("profiling")

MAX_DURATION_SECONDS = 60.0


class ProfileManager:
    """Async single-flight XLA trace collection."""

    def __init__(self) -> None:
        self.status = "none"
        self.duration: float = 0.0
        self._data: Optional[bytes] = None
        self._error = ""
        self._lock = threading.Lock()

    def create(self, duration_seconds: float = 3.0) -> Dict[str, object]:
        duration = min(max(float(duration_seconds), 0.1),
                       MAX_DURATION_SECONDS)
        with self._lock:
            # decide under the lock, respond after releasing it —
            # to_api() re-acquires and the lock is not reentrant
            already = self.status == "collecting"
            if not already:
                self.status = "collecting"
                self.duration = duration
                self._error = ""
                self._data = None   # never serve the previous trace
                                    # as if it were this capture
        if not already:
            threading.Thread(target=self._collect, args=(duration,),
                             daemon=True).start()
        return self.to_api()

    def _collect(self, duration: float) -> None:
        import jax

        tmpdir = tempfile.mkdtemp(prefix="theia-xprof-")
        try:
            jax.profiler.start_trace(tmpdir)
            try:
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                for root, _dirs, files in os.walk(tmpdir):
                    for f in files:
                        full = os.path.join(root, f)
                        tar.add(full,
                                arcname=os.path.relpath(full, tmpdir))
            with self._lock:
                self._data = buf.getvalue()
                self.status = "collected"
            logger.v(1).info("profile captured: %.1fs, %d bytes",
                             duration, len(self._data))
        except Exception as e:
            with self._lock:
                self.status = "failed"
                self._error = f"{type(e).__name__}: {e}"
            logger.error("profile capture failed: %s", self._error)
        finally:
            shutil.rmtree(tmpdir, ignore_errors=True)

    def to_api(self) -> Dict[str, object]:
        with self._lock:
            return {
                "kind": "Profile",
                "apiVersion": "system.theia.antrea.io/v1alpha1",
                "metadata": {"name": "theia-manager"},
                "status": self.status,
                "durationSeconds": self.duration,
                "size": len(self._data) if self._data else 0,
                "errorMsg": self._error,
            }

    def data(self) -> Optional[bytes]:
        with self._lock:
            return self._data
