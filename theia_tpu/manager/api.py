"""theia-manager REST API.

Re-provides the reference's aggregated API server
(pkg/apiserver/apiserver.go:131-162 installs three groups) on the same
port (TheiaManagerAPIPort = 11347, pkg/apis/ports.go:7):

  intelligence.theia.antrea.io/v1alpha1
      networkpolicyrecommendations, throughputanomalydetectors
      (registry/intelligence/*/rest.go — Get/List/Create/Delete; Get of
      a COMPLETED job attaches results from the store)
  stats.theia.antrea.io/v1alpha1
      clickhouse (+ /diskInfo /tableInfo /insertRate /stackTraces)
  system.theia.antrea.io/v1alpha1
      supportbundles (async collect + download, reference
      registry/system/supportbundle/rest.go)

Serialization is the same JSON shape the reference's k8s types marshal
to (pkg/apis/intelligence/v1alpha1/types.go), so the CLI talks to either
server. Transport is plain HTTP on a ThreadingHTTPServer; the
reference's delegated authn/TLS sits in front of an equivalent seam.

Authentication: the reference delegates authn/authz to kube-apiserver
(cmd/theia-manager/theia-manager.go:60-83) and the CLI sends a
ServiceAccount bearer token (pkg/theia/commands/utils.go:122-144). The
equivalent here is a static bearer token (`auth_token`): when set,
every request that can mutate state or exfiltrate data — POST (job
create, /ingest, bundle collect), DELETE, the system group's bundle
status/download, AND the telemetry read paths that serve decoded
flow identities (GET /alerts, /dashboards/*) — must carry
`Authorization: Bearer <token>`. A missing/malformed header is 401
(unauthenticated); a well-formed but wrong token is 403
(unauthorized). Coarse read-only observability (healthz, version,
stats, job GETs) stays open, playing the role of the reference's
unauthenticated Grafana read path (Grafana queries ClickHouse
directly, values.yaml:38-40) — but unlike that in-cluster path this
server can bind 0.0.0.0, so anything carrying per-connection IPs is
gated.
"""

from __future__ import annotations

import io
import json
import math
import tarfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from .collect import AsyncCollector
from ..obs import metrics as _obs_metrics
from ..obs import prom as _obs_prom
from ..obs import trace as _obs_trace
from .jobs import (
    KIND_DD,
    KIND_FPM,
    KIND_NPR,
    KIND_SPATIAL,
    KIND_TAD,
    STATE_COMPLETED,
    DuplicateJobError,
    JobController,
    JobRecord,
)
from .stats import StatsProvider
from .. import __version__
from ..store import AllReplicasDownError, ReplicatedFlowDatabase
from ..utils import dump_logs, get_logger
from ..utils import faults as _faults
from ..analysis.lockdep import named_lock

logger = get_logger("apiserver")

API_PORT = 11347


class AuthError(Exception):
    """Request failed authentication (code 401) or authorization
    (code 403)."""

    def __init__(self, code: int, message: str) -> None:
        super().__init__(message)
        self.code = code

GROUP_INTELLIGENCE = "/apis/intelligence.theia.antrea.io/v1alpha1"
GROUP_STATS = "/apis/stats.theia.antrea.io/v1alpha1"
GROUP_SYSTEM = "/apis/system.theia.antrea.io/v1alpha1"

_RESOURCE_KIND = {
    "networkpolicyrecommendations": KIND_NPR,
    "throughputanomalydetectors": KIND_TAD,
    "trafficdropdetections": KIND_DD,
    "flowpatternminings": KIND_FPM,
    "spatialanomalydetections": KIND_SPATIAL,
}
_KIND_NAMES = {
    KIND_NPR: "NetworkPolicyRecommendation",
    KIND_TAD: "ThroughputAnomalyDetector",
    KIND_DD: "TrafficDropDetection",
    KIND_FPM: "FlowPatternMining",
    KIND_SPATIAL: "SpatialAnomalyDetection",
}

# Pre-serialized fragments for the two hot ingest ack shapes
# ({"rows","alerts"[,"traceId"]} and the duplicate variant). The
# ingest ingress answers every batch with one of these; building a
# fresh dict walk + json.dumps per request showed up in profiles next
# to the actual socket write.
_ACK_ROWS = b'{"rows":'
_ACK_ALERTS = b',"alerts":'
_ACK_DUP = b',"duplicate":true'
_ACK_TRACE = b',"traceId":"'


def _fast_ack_bytes(doc: Dict[str, object]) -> Optional[bytes]:
    """Serialize an ingest ack from cached fragments when it has one
    of the two fixed hot shapes; None for anything else (forwardedRows,
    degraded, parked...) — the caller falls back to json.dumps. The
    output is byte-identical to json.dumps(doc, separators=(',',':'))
    for the covered shapes."""
    try:
        rows = doc["rows"]
        alerts = doc["alerts"]
    except KeyError:
        return None
    dup = doc.get("duplicate")
    trace = doc.get("traceId")
    if len(doc) != 2 + (dup is not None) + (trace is not None):
        return None
    if type(rows) is not int or type(alerts) is not int \
            or dup not in (None, True):
        return None
    parts = [_ACK_ROWS, str(rows).encode(), _ACK_ALERTS,
             str(alerts).encode()]
    if dup:
        parts.append(_ACK_DUP)
    if trace is not None:
        if type(trace) is not str:
            return None
        try:
            tid = trace.encode("ascii")
        except UnicodeEncodeError:
            return None
        if b'"' in tid or b"\\" in tid:
            return None
        parts += [_ACK_TRACE, tid, b'"}']
    else:
        parts.append(b"}")
    return b"".join(parts)


def record_to_api(record: JobRecord, controller: JobController,
                  with_result: bool = False) -> Dict[str, object]:
    doc: Dict[str, object] = {
        "kind": _KIND_NAMES[record.kind],
        "apiVersion": "intelligence.theia.antrea.io/v1alpha1",
        "metadata": {"name": record.name},
        "status": record.status_dict(),
    }
    doc.update(record.spec)
    if with_result and record.state == STATE_COMPLETED:
        if record.kind == KIND_NPR:
            doc["status"]["recommendationOutcome"] = (  # type: ignore
                controller.recommendation_outcome(record.name))
        else:
            doc["stats"] = controller.result_stats(record.kind,
                                                   record.name)
    return doc


class SupportBundleManager(AsyncCollector):
    """Async support-bundle collection (reference supportBundleREST:
    Create spawns a collect goroutine, status polls, then download —
    rest.go:115-255,425). Contents mirror the reference ManagerDumper's
    component classes (pkg/support/dump.go:55-66): store stats (whole
    + per shard), device inventory, manager + runner logs, job records
    with progress, and recent alerts."""

    kind = "SupportBundle"

    def __init__(self, controller: JobController,
                 stats: StatsProvider, ingest=None) -> None:
        super().__init__()
        self.controller = controller
        self.stats = stats
        self.ingest = ingest

    def _collect(self) -> bytes:
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            def add(name: str, payload: str) -> None:
                raw = payload.encode()
                info = tarfile.TarInfo(name)
                info.size = len(raw)
                info.mtime = int(time.time())
                tar.addfile(info, io.BytesIO(raw))

            add("stats/diskInfo.json",
                json.dumps(self.stats.disk_infos(), indent=2))
            add("stats/tableInfo.json",
                json.dumps(self.stats.table_infos(), indent=2))
            add("stats/insertRate.json",
                json.dumps(self.stats.insert_rates(), indent=2))
            add("stats/stackTraces.json",
                json.dumps(self.stats.stack_traces(), indent=2))
            try:
                # touches jax.devices(): collected best-effort so a
                # wedged accelerator can't block the whole bundle
                add("stats/deviceInfo.json",
                    json.dumps(self.stats.device_infos(), indent=2))
            except Exception as e:
                add("stats/deviceInfo.json",
                    json.dumps({"error": str(e)}))
            # Per-shard store summary (sharded deployments): which
            # shard holds what — the Distributed-table operator view.
            db = self.controller.db
            if hasattr(db, "shards"):
                add("store/shards.json", json.dumps([
                    {"shard": i,
                     "flows": len(s.flows),
                     "flowBytes": s.flows.nbytes,
                     **{name: len(t) for name, t
                        in s.result_tables.items()}}
                    for i, s in enumerate(db.shards)], indent=2))
            add("jobs.json", json.dumps(
                [record_to_api(r, self.controller)
                 for r in self.controller.list()], indent=2,
                default=str))
            # Recent manager logs — the reference's ManagerDumper
            # copies log files out of the component pods
            # (pkg/support/dump.go:55-66); here the in-process ring
            # buffer is the log source.
            add("logs/theia-manager.log", dump_logs())
            # Runner children's stderr tails (the Spark driver/
            # executor pod-log class), one file per dispatched job.
            for r in self.controller.list():
                if r.runner_log_tail:
                    add(f"logs/runner-{r.name}.log",
                        r.runner_log_tail)
            if self.ingest is not None:
                from .ingest import MAX_ALERTS
                add("alerts.json", json.dumps(
                    self.ingest.recent_alerts(MAX_ALERTS),
                    indent=2, default=str))
            from ..store.migration import CURRENT_SCHEMA_VERSION
            add("version.json", json.dumps({
                "version": __version__,
                "schemaVersion": CURRENT_SCHEMA_VERSION,
                "dispatch": self.controller.dispatch,
            }, indent=2))
        return buf.getvalue()


def refresh_scrape_gauges(controller, ingest, retention) -> None:
    """Refresh the scrape-time gauges — state that is cheaper to read
    on scrape than to maintain on every write. Shared by GET /metrics
    and the metrics-history loop (obs/history.py), so the stored
    series and the live exposition agree at every tick."""
    db = controller.db
    try:
        _obs_metrics.gauge(
            "theia_store_flow_rows",
            "Current flow-table rows").set(len(db.flows))
        _obs_metrics.gauge(
            "theia_store_flow_bytes",
            "Current flow-table column bytes").set(db.flows.nbytes)
    except Exception:
        # e.g. every replica down: the store gauges go stale but
        # the rest of the registry must stay scrapeable — an
        # outage is exactly when the jobs/replica/fault series
        # matter most.
        pass
    health = controller.health()
    _obs_metrics.gauge(
        "theia_job_queue_depth",
        "Jobs waiting for a worker").set(health["queueDepth"])
    _obs_metrics.gauge(
        "theia_jobs_running",
        "Jobs currently executing").set(health["running"])
    if ingest is not None:
        live = ingest.shard_liveness()
        _obs_metrics.gauge(
            "theia_ingest_streams",
            "Active ingest streams").set(live["streams"])
        _obs_metrics.gauge(
            "theia_detector_series",
            "Tracked connection series across detector shards"
        ).set(sum(s["series"] for s in live["perShard"]))
        # Slot saturation pair: live vs capacity — read them with
        # theia_detector_series_dropped_total, which counts the
        # series silently turned away once every slot is taken.
        _obs_metrics.gauge(
            "theia_detector_series_capacity",
            "Total streaming-detector slot capacity across shards"
        ).set(sum(s.get("capacity", 0)
                  for s in live["perShard"]))
        _obs_metrics.gauge(
            "theia_ingest_insert_inflight",
            "Store-insert legs submitted but not finished (the "
            "bounded insert backlog)").set(ingest.inflight_count())
        adm = getattr(ingest, "admission", None)
        if adm is not None:
            # refresh theia_admission_level/_pressure at scrape
            # time (and let an idle manager step the ladder down)
            adm.evaluate()
    if isinstance(db, ReplicatedFlowDatabase):
        m = db.membership()
        _obs_metrics.gauge(
            "theia_replicas_live",
            "Replicas currently serving").set(len(m["live"]))
    if retention is not None:
        _obs_metrics.gauge(
            "theia_retention_usage_percent",
            "Store bytes vs retention capacity").set(
                retention.stats()["usagePercent"])
    try:
        # the getattr itself can raise on a replicated store with
        # every replica down (__getattr__ resolves via `active`)
        parts = db.store_stats().get("parts")
    except Exception:
        parts = None
    if parts:
        _obs_metrics.gauge(
            "theia_store_parts",
            "Sealed column parts in the flows table (parts "
            "engine)").set(parts["count"])
        pb = _obs_metrics.gauge(
            "theia_store_part_bytes",
            "Sealed-part bytes by tier: hot = resident "
            "encoded chunks, cold = on-disk part files",
            labelnames=("tier",))
        pb.labels(tier="hot").set(parts["hotBytes"])
        pb.labels(tier="cold").set(parts["coldBytes"])
    _refresh_lockdep_gauges()


def _refresh_lockdep_gauges() -> None:
    """Lockdep witness exposition (armed runs only): aggregate graph
    gauges plus per-lock cumulative stats. Values come from the
    witness's own accounting at scrape time — the hot path never
    touches the metrics registry for these."""
    from ..analysis import lockdep as _lockdep
    if not _lockdep.enabled():
        return
    stats = _lockdep.stats()
    _obs_metrics.gauge(
        "theia_lockdep_locks",
        "Lock classes the lockdep witness is tracking").set(
        len(_lockdep.lock_names()))
    _obs_metrics.gauge(
        "theia_lockdep_edges",
        "Distinct blocking acquisition-order edges observed").set(
        len(_lockdep.order_edges()))
    _obs_metrics.gauge(
        "theia_lockdep_inversions",
        "Lock-order inversions witnessed since start (any nonzero "
        "value is a latent deadlock)").set(
        len(_lockdep.inversions()))
    acq = _obs_metrics.gauge(
        "theia_lockdep_acquires_total",
        "Witnessed lock acquisitions by lock class (cumulative; "
        "scrape-time snapshot of the witness counters)",
        labelnames=("lock",))
    con = _obs_metrics.gauge(
        "theia_lockdep_contended_total",
        "Witnessed acquisitions that had to wait, by lock class",
        labelnames=("lock",))
    wai = _obs_metrics.gauge(
        "theia_lockdep_wait_seconds_total",
        "Cumulative seconds spent waiting for each lock class",
        labelnames=("lock",))
    hol = _obs_metrics.gauge(
        "theia_lockdep_hold_seconds_total",
        "Cumulative seconds each lock class was held",
        labelnames=("lock",))
    for name, s in stats.items():
        acq.labels(lock=name).set(s["acquires"])
        con.labels(lock=name).set(s["contended"])
        wai.labels(lock=name).set(s["waitTotalSeconds"])
        hol.labels(lock=name).set(s["holdTotalSeconds"])


class ManagerAPIHandler(BaseHTTPRequestHandler):
    server_version = f"theia-tpu-manager/{__version__}"
    # HTTP/1.1: keep-alive, so the cluster transport's persistent
    # per-peer connections (heartbeats at 1 Hz, a frame ship per
    # ingest batch, a partial per distributed query) actually reuse
    # sockets instead of paying a TCP handshake each. Every response
    # path sends Content-Length (the 1.1 framing contract).
    protocol_version = "HTTP/1.1"
    controller: JobController
    stats: StatsProvider
    bundles: SupportBundleManager
    profiles = None   # ProfileManager
    ingest = None     # IngestManager
    retention = None  # RetentionLoop
    maintenance = None  # PartMaintenanceLoop (parts engine)
    queries = None    # QueryEngine
    distqueries = None  # ClusterQueryCoordinator (routing mesh)
    cluster = None    # ClusterNode (multi-node tier)
    history = None    # MetricsHistoryLoop (scrape-to-store series)
    rules = None      # RulesEngine (alert rules over stored series)
    auth_token: Optional[str] = None
    quiet = True
    # Socket timeout (StreamRequestHandler honors it): a client that
    # declares a Content-Length then stalls mid-body would otherwise
    # hold a worker thread forever (slow-loris).
    timeout = 120
    # A response is two small send()s (headers, body); on a
    # keep-alive connection Nagle + the client's delayed ACK would
    # stall each by ~40ms — fatal for the cluster's persistent
    # peer links (heartbeats, frame ships, query partials).
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):  # noqa: N802
        logger.v(2).info("%s %s", self.address_string(), fmt % args)
        if not self.quiet:
            super().log_message(fmt, *args)

    # -- helpers ---------------------------------------------------------

    def _send_json(self, doc, code: int = 200) -> None:
        raw = json.dumps(doc, default=str).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send_ingest_ack(self, doc: Dict[str, object]) -> None:
        """200 ack on the ingest hot path: cached-fragment
        serialization for the two fixed ack shapes, json.dumps
        fallback for the rest."""
        raw = _fast_ack_bytes(doc)
        if raw is None:
            self._send_json(doc)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _send_error_json(self, code: int, message: str) -> None:
        # Error paths can fire BEFORE the request body was consumed
        # (auth, Content-Length validation, armed recv-side faults);
        # under HTTP/1.1 keep-alive the unread body bytes would be
        # parsed as the next request line — close instead of desync.
        self.close_connection = True
        self._send_json({"kind": "Status", "status": "Failure",
                         "message": message, "code": code}, code)

    def _send_retry_after(self, e) -> None:
        """429 Too Many Requests + Retry-After (integer header per
        RFC 9110; the JSON body carries the precise float for clients
        that can use it). Runs only on the reject path — the admit
        path never touches Retry-After math."""
        self.close_connection = True   # body may be unconsumed
        raw = json.dumps({
            "kind": "Status", "status": "Failure", "message": str(e),
            "reason": e.reason, "code": 429,
            "retryAfterSeconds": round(e.retry_after, 3),
        }).encode()
        self.send_response(429)
        self.send_header("Retry-After",
                         str(max(1, math.ceil(e.retry_after))))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _require_auth(self) -> None:
        """Enforce the static bearer token (no-op when auth is off).
        Constant-time comparison; 401 for absent/malformed
        Authorization, 403 for a wrong token."""
        if self.auth_token is None:
            return
        import hmac
        header = self.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            raise AuthError(
                401, "missing or malformed Authorization header "
                     "(expected: Bearer <token>)")
        token = header[len("Bearer "):].strip()
        # compare bytes: compare_digest raises on non-ASCII str input,
        # which would turn a hostile token into a 500
        if not hmac.compare_digest(token.encode(),
                                   self.auth_token.encode()):
            raise AuthError(403, "invalid bearer token")

    def _send_auth_error(self, e: AuthError) -> None:
        self.close_connection = True   # body was never consumed
        raw = json.dumps({"kind": "Status", "status": "Failure",
                          "message": str(e), "code": e.code}).encode()
        self.send_response(e.code)
        if e.code == 401:
            self.send_header("WWW-Authenticate", "Bearer")
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    # 256 MiB: bounds what one request can make the server buffer.
    MAX_BODY_BYTES = 256 << 20

    def _read_raw_body(self) -> bytes:
        """Validated request body (Content-Length must be a sane
        non-negative size — a negative value would make read() block
        until the client hangs up, holding the worker thread)."""
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            raise ValueError("invalid Content-Length")
        if length < 0 or length > self.MAX_BODY_BYTES:
            raise ValueError(
                f"Content-Length {length} outside "
                f"[0, {self.MAX_BODY_BYTES}]")
        return self.rfile.read(length) if length else b""

    def _read_body(self) -> Dict[str, object]:
        raw = self._read_raw_body()
        return json.loads(raw) if raw else {}

    def _query(self) -> Dict[str, str]:
        import urllib.parse
        q = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query)
        return {k: v[0] for k, v in q.items()}

    def _route(self) -> Tuple[str, ...]:
        return tuple(p for p in self.path.split("?")[0].split("/") if p)

    # -- verbs -----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        from ..cluster import StaleReadError
        from ..query import IncompleteResultError
        from .admission import AdmissionRejected
        try:
            self._get()
        except AuthError as e:
            self._send_auth_error(e)
        except AdmissionRejected as e:
            # heavy reads (/query) ride the pressure ladder — over
            # capacity is 429 + Retry-After, distinct from 503
            self._send_retry_after(e)
        except StaleReadError as e:
            # bounded-staleness follower read over budget: retryable
            # here after catch-up, or read from the leader
            self._send_error_json(503, str(e))
        except IncompleteResultError as e:
            # THEIA_QUERY_STRICT=1: a distributed query missing peers
            # refuses rather than answer partial — retry after heal
            self._send_error_json(503, str(e))
        except AllReplicasDownError as e:
            # "retry later", not "server bug": every store copy is out
            self._send_error_json(503, str(e))
        except KeyError:
            self._send_error_json(404, f"not found: {self.path}")
        except ValueError as e:  # malformed query params are the
            self._send_error_json(400, str(e))       # client's fault
        except Exception as e:  # surface handler bugs as 500s
            self._send_error_json(500, f"{type(e).__name__}: {e}")

    def do_POST(self) -> None:  # noqa: N802
        from ..cluster import (
            ClusterStateError,
            ReplicationLagError,
            RouterForwardError,
        )
        from ..query import IncompleteResultError
        from .admission import AdmissionRejected
        from .ingest import StreamCapacityError
        try:
            self._require_auth()   # every POST mutates state
            self._post()
        except AuthError as e:
            self._send_auth_error(e)
        except (DuplicateJobError, ClusterStateError) as e:
            self._send_error_json(409, str(e))
        except AdmissionRejected as e:
            # over CAPACITY (retry later, we are fine) — deliberately
            # distinct from 503 (the store itself is unavailable)
            self._send_retry_after(e)
        except (StreamCapacityError, AllReplicasDownError,
                ReplicationLagError, RouterForwardError,
                IncompleteResultError) as e:
            # retryable capacity/availability condition, not a client
            # payload error: quorum not met, owner unreachable, every
            # replica down — the producer's retry is dedup-idempotent
            self._send_error_json(503, str(e))
        except KeyError:
            self._send_error_json(404, f"not found: {self.path}")
        except (ValueError, json.JSONDecodeError) as e:
            self._send_error_json(400, str(e))
        except Exception as e:
            self._send_error_json(500, f"{type(e).__name__}: {e}")

    def do_DELETE(self) -> None:  # noqa: N802
        try:
            self._require_auth()   # every DELETE mutates state
            self._delete()
        except AuthError as e:
            self._send_auth_error(e)
        except AllReplicasDownError as e:
            self._send_error_json(503, str(e))
        except KeyError:
            self._send_error_json(404, f"not found: {self.path}")
        except Exception as e:
            self._send_error_json(500, f"{type(e).__name__}: {e}")

    # -- routing ---------------------------------------------------------

    def _get(self) -> None:
        parts = self._route()
        if parts == ("alerts",):
            # Alerts carry decoded source/destination IPs — the same
            # sensitivity class as the gated support bundles, so the
            # token (when configured) is required here too.
            self._require_auth()
            limit = int(self._query().get("limit", "100"))
            doc = {"alerts": self.ingest.recent_alerts(limit),
                   "rowsIngested": self.ingest.rows_ingested,
                   "detectorShards": self.ingest.n_shards}
            rules = getattr(self, "rules", None)
            if rules is not None:
                # declarative alert-rule states (obs/rules.py) ride
                # the same surface their firings land on
                doc["rules"] = rules.doc()
            self._send_json(doc)
            return
        if parts == ("metrics",):
            # Prometheus exposition. Latency histograms and trace
            # exemplars narrate traffic shape (and alert kinds carry
            # detector output), so the surface is token-gated when
            # auth is configured — the /alerts precedent.
            self._require_auth()
            self._send_metrics()
            return
        if parts == ("debug", "traces"):
            # Recent + slowest spans; same sensitivity class. With
            # ?trace=<id> the lookup is CLUSTER-AWARE: this node fans
            # out to live peers and stitches every node's spans for
            # that trace into one doc (&local=1 marks a peer-internal
            # lookup so the fan-out never recurses).
            self._require_auth()
            q = self._query()
            trace_id = q.get("trace", "").strip()
            if trace_id:
                local_only = q.get("local", "") in ("1", "true")
                self._send_json(self._trace_doc(trace_id, local_only))
                return
            limit = int(q.get("limit", "100"))
            self._send_json(_obs_prom.traces_doc(limit))
            return
        if parts == ("debug", "slow_queries"):
            # Captured slow-query profiles carry plans (flow
            # identities) — token-gated like /debug/traces.
            self._require_auth()
            from ..query.explain import SLOW_QUERIES
            self._send_json(SLOW_QUERIES.doc())
            return
        if parts == ("debug", "parts"):
            # Storage-engine inspection depth (`theia parts`):
            # per-table part inventories — tiers, formats, sort key,
            # index bytes, granule stats, time ranges. Part time
            # ranges narrate traffic shape and the doc names on-disk
            # paths, so token-gated like the other /debug surfaces.
            self._require_auth()
            limit = int(self._query().get("limit", "256"))
            self._send_json(self._parts_debug_doc(limit))
            return
        if parts == ("debug", "locks"):
            # Lockdep witness at inspection depth (`theia locks`):
            # per-lock contention/hold stats, observed order edges
            # with first-seen sites, inversions. Sites name source
            # files and the stats narrate traffic shape — token-gated
            # like the other /debug surfaces.
            self._require_auth()
            from ..analysis import lockdep as _lockdep
            self._send_json(_lockdep.stats_doc())
            return
        if parts == ("debug", "views"):
            # Declared rollup views at inspection depth (`theia
            # views`): definitions, tiers, per-store part/row counts,
            # maintenance stats, loadError — the /debug/parts shape
            # and sensitivity class (view definitions narrate traffic
            # shape), so token-gated.
            self._require_auth()
            from ..query.rollup import views_doc
            self._send_json(views_doc(self.controller.db))
            return
        if parts == ("query",):
            # Aggregation results decode flow identities (IPs, pods) —
            # the /alerts sensitivity class, so the token (when
            # configured) is required; the query itself rides the
            # admission pressure ladder (heavy reads shed at the
            # shed_detector rung, 429 + Retry-After).
            self._require_auth()
            q = self._query()
            self._serve_query(
                self._plan_from_get(),
                use_cache=self._cache_flag(q.get("cache", "1")),
                explain=self._explain_flag(q.get("explain")),
                use_rollup=self._cache_flag(q.get("rollup", "1")))
            return
        if parts == ("cluster", "ping"):
            # peer liveness + log-matching handshake; open (the
            # /healthz liveness class — no decoded identities). The
            # recv-side fault hook makes partition drills symmetric.
            from ..cluster.transport import NODE_HEADER, fire_recv
            fire_recv(self.headers.get(NODE_HEADER), "/cluster/ping")
            if self.cluster is None:
                raise KeyError(self.path)
            self._send_json(self.cluster.ping_doc())
            return
        if parts == ("healthz",):
            self._send_json(self._health_doc())
            return
        if parts == ("readyz",):
            doc, code = self._ready_doc()
            self._send_json(doc, code)
            return
        if parts == ("version",):
            self._send_json({"version": __version__})
            return
        if self.path.startswith(GROUP_INTELLIGENCE):
            self._get_intelligence(parts)
            return
        if self.path.startswith(GROUP_STATS):
            self._get_stats(parts)
            return
        if self.path.startswith(GROUP_SYSTEM):
            self._get_system(parts)
            return
        if parts and parts[0] == "dashboards":
            self._get_dashboard(parts)
            return
        raise KeyError(self.path)

    def _send_metrics(self) -> None:
        """Render the process registry, refreshing the scrape-time
        gauges first (shared with the metrics-history loop so both
        surfaces agree at the tick)."""
        refresh_scrape_gauges(self.controller, self.ingest,
                              self.retention)
        raw = _obs_prom.render().encode()
        self.send_response(200)
        self.send_header("Content-Type", _obs_prom.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _parts_debug_doc(self, limit: int) -> Dict[str, object]:
        """GET /debug/parts: the parts engine at inspection depth —
        the `theia top` parts header expanded to one entry per part.
        Sharded stores report every shard table; the flat engine
        answers an empty table list (engine "flat") rather than 404,
        so the CLI can say "flat engine" instead of guessing."""
        db = self.controller.db
        flows = db.flows   # replicated: resolves the active replica
        tables = (list(flows.tables) if hasattr(flows, "tables")
                  else [flows])
        docs = []
        for i, t in enumerate(tables):
            ps = getattr(t, "parts_stats", None)
            if not callable(ps):
                continue
            tdoc: Dict[str, object] = {
                "table": t.name,
                "stats": ps(),
                "parts": t.parts_debug_entries(limit),
            }
            if len(tables) > 1:
                tdoc["shard"] = i
            docs.append(tdoc)
        return {"engine": "parts" if docs else "flat",
                "tables": docs}

    def _health_doc(self) -> Dict[str, object]:
        """Liveness + degradation surface (no decoded identities, so it
        stays on the open read path): `status` is "ok" while every
        replica serves and "degraded" when the store is down a copy
        but still serving — distinguishable from down, which /readyz
        reports. Covers replica membership/quarantine, job queue
        depth, ingest detector-shard liveness, and any armed fault
        sites (so an operator can see a fault drill is running)."""
        doc: Dict[str, object] = {
            "status": "ok",
            "jobs": self.controller.health(),
        }
        if self.ingest is not None:
            doc["ingest"] = self.ingest.shard_liveness()
            adm = getattr(self.ingest, "admission", None)
            if adm is not None:
                # current brownout rung + the pressure signals that
                # put it there (refreshed here so a scrape-only
                # manager still de-escalates); above rung 0 the
                # manager is serving but degraded
                adm.evaluate()
                doc["admission"] = adm.snapshot()
                if adm.level() > 0 and doc["status"] == "ok":
                    doc["status"] = "degraded"
            dedup = getattr(self.ingest, "dedup", None)
            if dedup is not None:
                doc["dedup"] = dedup.stats()
        db = self.controller.db
        if isinstance(db, ReplicatedFlowDatabase):
            m = db.membership()
            doc["replicas"] = m
            if m["down"] or m["quarantined"]:
                doc["status"] = "degraded"
        if self.retention is not None:
            doc["retention"] = self.retention.stats()
        # Metrics-history loop: scrape cadence, stored rows, rollup/
        # retention totals, failures — plus the rule engine's firing
        # count (the detail lives on GET /alerts).
        history = getattr(self, "history", None)
        if history is not None:
            hdoc = history.stats()
            rules = getattr(self, "rules", None)
            if rules is not None:
                hdoc["rulesFiring"] = len(rules.firing())
            doc["metricsHistory"] = hdoc
        # Query engine: executed count, worker/cold-buffer sizing,
        # kernel in use, and result-cache occupancy/hit counters.
        # (getattr like `maintenance` below: stub handler objects in
        # tests don't carry every binding)
        queries = getattr(self, "queries", None)
        if queries is not None:
            qdoc = queries.stats()
            dist = getattr(self, "distqueries", None)
            if dist is not None:
                qdoc["distributed"] = dist.stats()
            doc["query"] = qdoc
        # Storage engine + tier summary (parts engine: part counts,
        # hot/cold bytes, memtable, merge/seal/demote totals). The
        # attribute lookup itself can raise on a replicated store with
        # every replica down — healthz must keep serving `degraded`.
        try:
            store_doc = db.store_stats()
        except Exception:
            store_doc = None
        if store_doc:
            maint = getattr(self, "maintenance", None)
            if maint is not None:
                store_doc["maintenance"] = maint.stats()
            doc["store"] = store_doc
        # WAL health: segment count/bytes and the ack-durability lag
        # (records/bytes appended but not yet fsynced under the sync
        # policy) — the operator's read on the current loss bound.
        wal_stats = getattr(db, "wal_stats", None)
        if callable(wal_stats):
            try:
                ws = wal_stats()
            except Exception:
                ws = None
            if ws:
                doc["wal"] = ws
        # Cluster tier: role/term, peer liveness, replication lag or
        # follower staleness, router counters. A down peer or a
        # non-streaming follower degrades the node (it still serves).
        cluster = getattr(self, "cluster", None)
        if cluster is not None:
            cdoc = cluster.health_doc()
            if cdoc.pop("degraded", False) and doc["status"] == "ok":
                doc["status"] = "degraded"
            doc["cluster"] = cdoc
        armed = _faults.armed_sites()
        if armed:
            doc["faults"] = {"armed": armed}
        return doc

    def _ready_doc(self) -> Tuple[Dict[str, object], int]:
        """Readiness: can this manager serve reads/writes at all? All
        replicas down → 503 (take it out of rotation); degraded but
        serving → 200 (healthz carries the detail)."""
        db = self.controller.db
        try:
            if isinstance(db, ReplicatedFlowDatabase):
                db.live()
        except AllReplicasDownError as e:
            return {"ready": False, "reason": str(e)}, 503
        return {"ready": True}, 200

    def _trace_doc(self, trace_id: str,
                   local_only: bool) -> Dict[str, object]:
        """One trace's spans — local ring plus (unless `local_only`)
        every live peer's, fetched over the persistent cluster
        transport and stitched into one doc. Per-span `node` ids come
        from each recording process; timestamps are each node's OWN
        wall clock, so cross-node ordering inside the skew envelope is
        noted, not 'corrected' — fabricating an ordering would be a
        lie the renderer cannot check."""
        import urllib.parse

        from ..obs import trace as _t
        quoted = urllib.parse.quote(trace_id, safe="")
        spans = _t.spans_for_trace(trace_id)
        self_id = _t.node_id() or "local"
        for s in spans:
            if not s.get("node"):
                s["node"] = self_id
        doc: Dict[str, object] = {"trace": trace_id}
        cluster = getattr(self, "cluster", None)
        if cluster is not None and not local_only:
            from ..utils.pool import get_pool
            failed = []
            live = [p for p in cluster.cmap.others()
                    if cluster.cmap.is_alive(p)]
            failed.extend(p for p in cluster.cmap.others()
                          if p not in live)
            # concurrent fetches (the query fan-out discipline): one
            # hung peer costs one transport timeout, not its place in
            # a serial chain
            pool = get_pool("trace-fanout", 4)
            futs = [(p, pool.submit(
                cluster.transport.request, p,
                f"/debug/traces?trace={quoted}&local=1"))
                for p in live]
            for peer, fut in futs:
                try:
                    remote = fut.result()
                except Exception as e:
                    failed.append(peer)
                    logger.warning("trace fetch from %s failed: %s",
                                   peer, e)
                    continue
                # dedupe on span id: in-process test meshes share one
                # process-global ring, and a real peer re-answering a
                # retried fetch must not double its spans either
                seen = {s.get("spanId") for s in spans}
                for s in remote.get("spans") or []:
                    if s.get("spanId") in seen:
                        continue
                    if not s.get("node"):
                        s["node"] = peer
                    spans.append(s)
            if failed:
                doc["peersMissing"] = sorted(failed)
        spans.sort(key=lambda s: (s.get("startTime") or 0))
        doc["spans"] = spans
        doc["nodes"] = sorted({str(s.get("node")) for s in spans})
        if len(doc["nodes"]) > 1:
            doc["clockNote"] = (
                "span timestamps are per-node wall clocks; cross-node "
                "ordering within the nodes' clock skew is as-reported, "
                "not corrected")
        return doc

    def _get_dashboard(self, parts) -> None:
        """/dashboards/[<name>] → HTML page;
        /dashboards/api/<name>[?start=..&end=..&limit=..&k=..] → the
        underlying JSON data (the Grafana-datasource equivalent of the
        reference's read path; start/end play the $__timeFilter role);
        /dashboards/api/<name>?format=grafana → a Grafana-importable
        dashboard JSON (the reference's provisioned *.json equivalent,
        build/charts/theia/provisioning/dashboards/)."""
        # Dashboard pages and their JSON datasource serve the same
        # decoded per-flow identities the alerts do (the HTML embeds
        # the data server-side), so the whole surface is token-gated
        # when auth is configured.
        self._require_auth()
        import inspect

        from ..dashboards import DASHBOARDS, grafana_dashboard, render
        if len(parts) >= 3 and parts[1] == "api":
            qs = self._query()
            if qs.get("format") == "grafana":
                self._send_json(grafana_dashboard(parts[2]))
                return
            fn = DASHBOARDS[parts[2]]
            accepted = inspect.signature(fn).parameters
            kwargs = {name: int(qs[name]) for name
                      in ("start", "end", "limit", "k")
                      if name in qs and name in accepted}
            self._send_json({"dashboard": parts[2],
                             "data": fn(self.controller.db, **kwargs)})
            return
        name = parts[1] if len(parts) > 1 else "homepage"
        page = render(name, self.controller.db).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(page)))
        self.end_headers()
        self.wfile.write(page)

    def _get_intelligence(self, parts) -> None:
        resource = parts[3]
        kind = _RESOURCE_KIND[resource]
        if len(parts) == 4:   # list
            items = [record_to_api(r, self.controller)
                     for r in self.controller.list(kind)]
            self._send_json({
                "kind": _KIND_NAMES[kind] + "List",
                "apiVersion": "intelligence.theia.antrea.io/v1alpha1",
                "items": items})
        elif len(parts) == 5:
            record = self.controller.get(parts[4])
            if record.kind != kind:
                raise KeyError(parts[4])
            self._send_json(record_to_api(record, self.controller,
                                          with_result=True))
        else:
            raise KeyError(self.path)

    _STATS_COMPONENTS = ("diskInfo", "tableInfo", "insertRate",
                         "stackTraces", "deviceInfo", "detectorInfo")

    def _get_stats(self, parts) -> None:
        if len(parts) < 4 or parts[3] != "clickhouse":
            raise KeyError(self.path)
        component = parts[4] if len(parts) > 4 else None
        if component is not None and \
                component not in self._STATS_COMPONENTS:
            raise KeyError(self.path)
        doc: Dict[str, object] = {
            "kind": "ClickHouseStats",
            "apiVersion": "stats.theia.antrea.io/v1alpha1",
        }
        if component in (None, "diskInfo"):
            doc["diskInfos"] = self.stats.disk_infos()
        if component in (None, "tableInfo"):
            doc["tableInfos"] = self.stats.table_infos()
        if component in (None, "insertRate"):
            doc["insertRates"] = self.stats.insert_rates()
        if component in (None, "stackTraces"):
            doc["stackTraces"] = self.stats.stack_traces()
        if component in (None, "detectorInfo"):
            # Shard counts and per-shard series occupancy of the
            # ingest-path detector ensemble (no decoded identities —
            # stays on the open read path with the rest of stats).
            doc["detectorInfos"] = self.ingest.detector_stats()
        if component == "deviceInfo":
            # Opt-in only (not part of the bare-resource GET): touching
            # jax.devices() initializes a backend, which an operator
            # polling basic store stats shouldn't pay for.
            doc["deviceInfos"] = self.stats.device_infos()
        self._send_json(doc)

    def _get_system(self, parts) -> None:
        # Bundles/profiles carry logs/stats/traces — exfiltration
        # surface, so even their GETs require the token (reference
        # bundles sit behind the aggregated apiserver's delegated
        # authn).
        self._require_auth()

        def stream(data: Optional[bytes], what: str) -> None:
            if data is None:
                raise KeyError(f"{what} not collected")
            self.send_response(200)
            self.send_header("Content-Type", "application/gzip")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        if len(parts) >= 4 and parts[3] == "supportbundles":
            if len(parts) == 6 and parts[5] == "download":
                stream(self.bundles.data(), "bundle")
                return
            self._send_json(self.bundles.to_api())
            return
        if len(parts) >= 4 and parts[3] == "profiles":
            if len(parts) == 6 and parts[5] == "download":
                stream(self.profiles.data(), "profile")
                return
            self._send_json(self.profiles.to_api())
            return
        raise KeyError(self.path)

    def _plan_from_get(self):
        from ..query import plan_from_params
        return plan_from_params(self._query())

    @staticmethod
    def _cache_flag(raw) -> bool:
        """`cache=0|false|no` (GET param / POST body key) bypasses the
        result cache for one query — the bench's timed windows measure
        execution, not cache hits."""
        return str(raw).strip().lower() not in ("0", "false", "no")

    @staticmethod
    def _explain_flag(raw) -> bool:
        """`explain=1|true|yes` (GET param) / `"explain": true` (POST
        body): attach the execution profile to the result doc."""
        if raw is True:
            return True
        return str(raw).strip().lower() in ("1", "true", "yes")

    def _serve_query(self, plan, use_cache: bool = True,
                     explain: bool = False,
                     use_rollup: bool = True) -> None:
        """Shared GET/POST /query tail: admission, execution, timing
        headers. 400s (PlanError is a ValueError) and 429s surface
        through the verb handlers' taxonomy. On a routing-mesh node
        the query coordinator scatter-gathers the whole cluster;
        everywhere else the local engine answers. The request's
        traceparent (if any) flows into the engine's ingress span, so
        a caller-supplied trace continues through the fan-out."""
        if self.queries is None:
            raise KeyError(self.path)
        if self.cluster is not None:
            # bounded-staleness follower reads: a copy that lost its
            # leader answers 503, not silently stale data
            self.cluster.check_query_staleness()
        adm = getattr(self.ingest, "admission", None) \
            if self.ingest is not None else None
        if adm is not None:
            adm.admit_query()
        dist = getattr(self, "distqueries", None)
        engine = dist if dist is not None else self.queries
        self._send_json(engine.execute(
            plan, use_cache=use_cache, explain=explain,
            traceparent=self.headers.get("traceparent"),
            use_rollup=use_rollup))

    def _send_ingest_redirect(self) -> None:
        """307 + Location at the current leader: this node is a
        follower and must not take writes (the Distributed-table
        'wrong shard' answer). Body carries the leader for clients
        that read JSON instead of headers."""
        target = self.cluster.leader_addr()
        if not target:
            raise AllReplicasDownError(
                "this node is a follower and no leader is known yet")
        location = target + self.path
        raw = json.dumps({
            "kind": "Status", "status": "Failure", "code": 307,
            "message": f"node {self.cluster.cmap.self_id} is a "
                       f"follower; ingest at the leader",
            "location": location}).encode()
        self.send_response(307)
        self.send_header("Location", location)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _post(self) -> None:
        parts = self._route()
        if parts == ("query",):
            from ..query import parse_plan
            body = self._read_body()
            self._serve_query(
                parse_plan(body),
                use_cache=self._cache_flag(body.get("cache", "1")),
                explain=self._explain_flag(body.get("explain")),
                use_rollup=self._cache_flag(body.get("rollup", "1")))
            return
        if parts == ("query", "partial"):
            self._post_query_partial()
            return
        if parts == ("ingest",):
            if self.cluster is not None and \
                    not self.cluster.accepts_ingest():
                # drain the body first: answering 307 mid-upload makes
                # some clients choke on the connection reset
                self._read_raw_body()
                self._send_ingest_redirect()
                return
            q = self._query()
            stream = q.get("stream", "default")
            seq_raw = q.get("seq")
            try:
                seq = int(seq_raw) if seq_raw is not None else None
            except ValueError:
                raise ValueError(f"seq={seq_raw!r} is not an integer")
            payload = self._read_raw_body()
            if not payload:
                raise ValueError("empty ingest payload")
            self._send_ingest_ack(self.ingest.ingest(
                payload, stream=stream, seq=seq,
                traceparent=self.headers.get("traceparent")))
            return
        if parts and parts[0] == "cluster":
            self._post_cluster(parts)
            return
        if self.path.startswith(GROUP_INTELLIGENCE) and len(parts) == 4:
            kind = _RESOURCE_KIND[parts[3]]
            body = self._read_body()
            name = (body.get("metadata") or {}).get("name")
            spec = {k: v for k, v in body.items()
                    if k not in ("kind", "apiVersion", "metadata",
                                 "status", "stats")}
            record = self.controller.create(kind, spec, name=name)
            self._send_json(record_to_api(record, self.controller), 201)
            return
        if self.path.startswith(GROUP_SYSTEM) and len(parts) >= 4 \
                and parts[3] == "supportbundles":
            self._send_json(self.bundles.create(), 201)
            return
        if self.path.startswith(GROUP_SYSTEM) and len(parts) >= 4 \
                and parts[3] == "profiles":
            body = self._read_body()
            self._send_json(self.profiles.create(
                float(body.get("durationSeconds", 3.0) or 3.0)), 201)
            return
        raise KeyError(self.path)

    def _post_query_partial(self) -> None:
        """Cluster-internal scatter-gather server half: execute the
        posted plan over the LOCAL store only and answer mergeable
        per-group partial aggregates as one binary TQPF frame (group
        keys + lowered count/sum/min/max columns — never rows).
        Token-gated like every POST; admission rides one rung ahead
        of ingest HERE TOO, so a shed peer answers 429 and the
        coordinator degrades to partial:true; the recv-side fault
        hook makes partition drills sever the read path
        symmetrically."""
        from ..cluster.transport import NODE_HEADER, fire_recv
        from ..query import parse_plan
        from ..query.distributed import serve_partial
        if self.queries is None:
            raise KeyError(self.path)
        fire_recv(self.headers.get(NODE_HEADER), "/query/partial")
        body = self._read_body()
        plan = parse_plan(body.get("plan") or {})
        adm = getattr(self.ingest, "admission", None) \
            if self.ingest is not None else None
        if adm is not None:
            adm.admit_query()
        node_id = (self.cluster.cmap.self_id
                   if self.cluster is not None else "")
        # trace ingress: the coordinator's context arrives on the
        # request, so this node's partial-execution span joins the
        # originating query's cross-node trace
        with _obs_trace.ingress_span(
                "query.partial",
                traceparent=self.headers.get("traceparent"),
                coordinator=self.headers.get(NODE_HEADER) or ""):
            raw = serve_partial(
                self.queries, plan, node_id=node_id,
                use_rollup=self._cache_flag(body.get("rollup", "1")))
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _post_cluster(self, parts) -> None:
        """Cluster control/replication plane (token-gated with every
        other POST): /cluster/replicate takes a batch of raw WAL
        frames, /cluster/resync a wholesale catch-up stream,
        /cluster/promote the WAL-delimited failover cutover."""
        from ..cluster.transport import NODE_HEADER, fire_recv
        if self.cluster is None or len(parts) < 2:
            raise KeyError(self.path)
        fire_recv(self.headers.get(NODE_HEADER),
                  "/" + "/".join(parts))
        # trace ingress: a leader's ship/resync span context arrives
        # on the request (cluster/replication.py mints it), so the
        # apply side of every replication RPC joins the same trace
        op = "cluster." + parts[1]
        with _obs_trace.ingress_span(
                op, traceparent=self.headers.get("traceparent"),
                peer=self.headers.get(NODE_HEADER) or ""):
            if parts == ("cluster", "replicate"):
                self._send_json(self.cluster.handle_replicate(
                    self._read_raw_body(), self.headers))
                return
            if parts == ("cluster", "resync"):
                self._send_json(self.cluster.handle_resync(
                    self._read_raw_body(), self.headers))
                return
            if parts == ("cluster", "promote"):
                body = self._read_body()
                at = body.get("atLsn")
                self._send_json(self.cluster.promote(
                    int(at) if at is not None else None))
                return
        raise KeyError(self.path)

    def _delete(self) -> None:
        parts = self._route()
        if self.path.startswith(GROUP_INTELLIGENCE) and len(parts) == 5:
            kind = _RESOURCE_KIND[parts[3]]
            record = self.controller.get(parts[4])
            if record.kind != kind:
                raise KeyError(parts[4])
            self.controller.delete(parts[4])
            self._send_json({"kind": "Status", "status": "Success"})
            return
        raise KeyError(self.path)


def resolve_auth_token(auth_token: Optional[str],
                       auth_token_file: Optional[str]) -> Optional[str]:
    """An explicit token wins; else read the token file, minting a
    fresh random token into it when absent (the deployment analogue of
    the reference's ServiceAccount token Secret, which kube generates
    and the CLI reads — pkg/theia/commands/utils.go:122-144). Returns
    None (auth off) only when neither source is configured."""
    if auth_token:
        return auth_token
    if not auth_token_file:
        return None
    import os
    import secrets
    try:
        with open(auth_token_file) as f:
            token = f.read().strip()
        if token:
            return token
    except FileNotFoundError:
        pass
    token = secrets.token_hex(32)
    fd = os.open(auth_token_file,
                 os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(token + "\n")
    logger.info("generated API bearer token at %s", auth_token_file)
    return token


class _TLSCapableServer(ThreadingHTTPServer):
    """HTTP server that performs the TLS handshake per connection on
    the worker thread — wrapping the *listening* socket would run the
    handshake inside accept() on the serve_forever thread, letting one
    silent client stall the entire API.

    Live connections are tracked so `server_close()` can SEVER them:
    with HTTP/1.1 keep-alive (the cluster transport's persistent
    per-peer connections) a handler thread otherwise keeps serving an
    established socket long after the listening socket closed — a
    shut-down node must go dark, not half-dark."""

    ssl_context = None
    handshake_timeout = 10.0

    def __init__(self, *args, **kwargs) -> None:
        self._conns: set = set()
        self._conns_lock = named_lock("api.conns")
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        import socket as _socket
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    def finish_request(self, request, client_address):
        if self.ssl_context is not None:
            request.settimeout(self.handshake_timeout)
            request = self.ssl_context.wrap_socket(request,
                                                   server_side=True)
            request.settimeout(None)
        super().finish_request(request, client_address)


class TheiaManagerServer:
    """Wires controller + stats + bundles into one HTTP server."""

    def __init__(self, db, port: int = API_PORT, workers: int = 2,
                 capacity_bytes: int = 8 << 30,
                 address: str = "127.0.0.1",
                 dispatch: str = "thread",
                 tls_cert_dir: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_ca: Optional[str] = None,
                 auth_token: Optional[str] = None,
                 auth_token_file: Optional[str] = None,
                 ingest_shards: Optional[int] = None,
                 cluster_peers: Optional[str] = None,
                 cluster_self: Optional[str] = None,
                 cluster_role: Optional[str] = None,
                 cluster_acks: Optional[str] = None) -> None:
        import os as _os

        from .ingest import IngestManager
        self.ingest = IngestManager(db, n_shards=ingest_shards)
        self.controller = JobController(
            db, workers=workers, dispatch=dispatch,
            alert_sink=self.ingest.push_alert)
        if self.ingest.admission is not None:
            # third pressure signal (the ingest manager wired the
            # insert backlog + WAL lag itself): a deep job queue means
            # the workers are saturated — stop piling ingest on top
            from ..utils.env import env_int as _env_int
            self.ingest.admission.add_signal(
                "jobQueue", self.controller._queue.qsize,
                _env_int("THEIA_JOB_QUEUE_HIGH", 64))
        self.stats = StatsProvider(db, capacity_bytes=capacity_bytes)
        # Vectorized read path: filtered aggregations over the store
        # (part-native on the parts engine, reference executor on
        # flat) behind GET/POST /query.
        from ..query import QueryEngine
        self.queries = QueryEngine(db)
        self.bundles = SupportBundleManager(self.controller, self.stats,
                                            ingest=self.ingest)
        from .profiling import ProfileManager
        self.profiles = ProfileManager()
        self.auth_token = resolve_auth_token(auth_token,
                                             auth_token_file)
        self.repairer = None
        # Capacity-based retention, supervised (the reference runs the
        # clickhouse-monitor sidecar unconditionally; here the loop is
        # on unless THEIA_RETENTION_INTERVAL <= 0 disables it).
        # THEIA_STORE_CAPACITY_BYTES overrides the API capacity arg as
        # the trim threshold's denominator. Constructed here (cannot
        # fail meaningfully), STARTED after the socket bind below.
        from ..utils.env import env_float, env_int
        self.retention = None
        retention_interval = env_float("THEIA_RETENTION_INTERVAL",
                                       60.0)
        if retention_interval > 0:
            from ..store import RetentionLoop
            monitor = db.monitor(
                env_int("THEIA_STORE_CAPACITY_BYTES",
                        capacity_bytes))
            self.retention = RetentionLoop(monitor,
                                           interval=retention_interval)
        # Parts engine → supervised background merge loop (compacts
        # small sealed parts; THEIA_STORE_MERGE_INTERVAL <= 0
        # disables). Constructed here, STARTED after the socket bind.
        self.maintenance = None
        merge_interval = env_float("THEIA_STORE_MERGE_INTERVAL", 5.0)
        store_stats = getattr(db, "store_stats", None)
        if merge_interval > 0 and callable(store_stats) and \
                callable(getattr(db, "maintenance_tick", None)):
            try:
                engine = store_stats().get("engine")
            except Exception:
                engine = None
            from ..query.rollup import rollup_configured
            if engine == "parts" or rollup_configured(db):
                # rollup views need the maintenance cadence (config
                # hot reload + tier folds + rollup-part compaction)
                # even on a flat flows engine — their tables are
                # parts-backed regardless, and a config source whose
                # file is torn/missing AT BOOT still needs the
                # cadence that will pick up its repair
                from ..store import PartMaintenanceLoop
                self.maintenance = PartMaintenanceLoop(
                    db, interval=merge_interval)

        # Multi-node cluster tier (theia_tpu/cluster): membership +
        # heartbeats, and per role the replication leader (WAL
        # shipping + quorum acks wired into the ingest durability
        # gate), the follower applier, or the ingest router. Off
        # entirely without a peer list — single-node managers carry
        # zero cluster overhead.
        self.cluster = None
        self.distqueries = None
        peers_spec = (cluster_peers
                      if cluster_peers is not None
                      else _os.environ.get("THEIA_CLUSTER_PEERS", ""))
        if peers_spec.strip():
            from ..cluster import ClusterNode
            self.cluster = ClusterNode(
                db, self.ingest, peers=peers_spec,
                self_id=cluster_self, role=cluster_role,
                acks=cluster_acks, token=self.auth_token or "",
                query_engine=self.queries)
            # stamp this node's id on every span it records, so the
            # cluster-stitched trace view attributes each span to the
            # node that ran it
            _obs_trace.set_node_id(self.cluster.cmap.self_id)
            # Scatter-gather /query on the routing mesh: data is
            # spread by destination hash, so the receiving node
            # coordinates a cluster-wide answer (leader/follower
            # topologies replicate the whole store — their local
            # engine already answers cluster-wide).
            if self.cluster.role == "peer" and \
                    len(self.cluster.cmap.order) > 1:
                from ..query import ClusterQueryCoordinator
                self.distqueries = ClusterQueryCoordinator(
                    self.cluster, self.queries)
            # wired unconditionally: the gate checks the node's role
            # at CALL time, so a follower promoted to leader later
            # starts enforcing the quorum without rewiring
            self.ingest.durability_gate = self.cluster.durability_gate
            if self.ingest.admission is not None:
                from ..utils.env import env_int as _env_int
                self.ingest.admission.add_signal(
                    "replLag", self.cluster.repl_lag,
                    _env_int("THEIA_REPL_LAG_HIGH", 10_000))

        # Metrics history: the scrape-to-store loop (obs/history.py)
        # snapshots the process registry into the parts-backed
        # `__metrics__` table on a cadence, downsamples/expires it,
        # and drives the declarative alert rules (obs/rules.py) over
        # the stored series THROUGH the same engine /query serves —
        # cluster-wide on a routing mesh. A non-positive
        # THEIA_METRICS_SCRAPE_INTERVAL disables the whole plane.
        # Constructed here, STARTED after the socket bind.
        self.history = None
        self.rules = None
        from ..obs.history import MetricsHistoryLoop, scrape_interval
        if scrape_interval() > 0:
            from ..obs.rules import RulesEngine
            from ..query import parse_plan

            rules_engine = (self.distqueries if self.distqueries
                            is not None else self.queries)
            self.rules = RulesEngine(
                lambda doc: rules_engine.execute(
                    parse_plan(doc), use_cache=False),
                alert_sink=self.ingest.push_alert)
            self.history = MetricsHistoryLoop(
                db,
                node=(self.cluster.cmap.self_id
                      if self.cluster is not None else ""),
                refresh=lambda: refresh_scrape_gauges(
                    self.controller, self.ingest, self.retention),
                accepts_writes=(self.cluster.accepts_ingest
                                if self.cluster is not None else None),
                rules=self.rules)

        handler = type("BoundHandler", (ManagerAPIHandler,), {
            "controller": self.controller,
            "stats": self.stats,
            "bundles": self.bundles,
            "profiles": self.profiles,
            "ingest": self.ingest,
            "retention": self.retention,
            "maintenance": self.maintenance,
            "queries": self.queries,
            "distqueries": self.distqueries,
            "cluster": self.cluster,
            "history": self.history,
            "rules": self.rules,
            "auth_token": self.auth_token,
        })
        self.httpd = _TLSCapableServer((address, port), handler)
        self.ca_cert_path: Optional[str] = None
        if tls_cert_dir is not None:
            # Self-signed (or provided) serving cert, reference
            # certificate.ApplyServerCert (manager/certs.py).
            import ssl

            from .certs import apply_server_cert
            cert, key, ca = apply_server_cert(
                tls_cert_dir, tls_cert, tls_key, tls_ca)
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.minimum_version = ssl.TLSVersion.TLSv1_2
            ctx.load_cert_chain(cert, key)
            self.httpd.ssl_context = ctx
            self.ca_cert_path = ca
        self.port = self.httpd.server_address[1]
        # Replicated store → background self-healing: resync and
        # re-admit replicas auto-quarantined by failed fan-out writes
        # (manual set_replica_down marks are left alone). Started
        # last, after the socket bind and TLS setup can no longer
        # raise — a constructor failure must not leak a live repair
        # thread nothing can stop.
        if isinstance(db, ReplicatedFlowDatabase):
            from ..store import ReplicaRepairLoop
            self.repairer = ReplicaRepairLoop(db)
            self.repairer.start()
        if self.retention is not None:
            self.retention.start()
        if self.maintenance is not None:
            self.maintenance.start()
        if self.cluster is not None:
            # after the socket bind: peers probe us back immediately
            self.cluster.start()
        if self.history is not None:
            self.history.start()
        self._thread: Optional[threading.Thread] = None
        self._serving = False

    def start_background(self) -> None:
        self._serving = True
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="theia-manager-api")
        self._thread.start()

    def serve_forever(self) -> None:
        self._serving = True
        self.httpd.serve_forever()

    def shutdown(self) -> None:
        # BaseServer.shutdown() blocks forever unless serve_forever is
        # running — guard so a never-started server can still shut down.
        if self._serving:
            self.httpd.shutdown()
        self.httpd.server_close()
        if self.repairer is not None:
            self.repairer.stop()
        if self.history is not None:
            self.history.stop()
        if self.retention is not None:
            self.retention.stop()
        if self.maintenance is not None:
            self.maintenance.stop()
        if self.cluster is not None:
            self.cluster.stop()
        self.ingest.close()
        self.controller.shutdown()
        if self._thread:
            self._thread.join(timeout=2)
