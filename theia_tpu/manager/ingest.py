"""Network ingest into a running manager + live alerting.

Plays the role of the reference's flow ingestion contract (the Flow
Aggregator inserts into ClickHouse over its native TCP protocol,
pkg/util/clickhouse/clickhouse.go:125; schema create_table.sh:31-84):
producers POST flow batches to the manager —

    POST /ingest
        body: a TFB2 binary columnar block (application/octet-stream)
              or TabSeparated rows (text/tab-separated-values)
        response: {"rows": N, "alerts": K}

Every ingested batch fans out to the store (materialized views, TTL)
AND advances the streaming detectors — the heavy-hitter / DDoS sketch
AND the per-connection EWMA anomaly engine — whose alerts are served
from a bounded ring:

    GET /alerts?limit=N      most recent alerts, newest first

Alert kinds: "heavy_hitter" / "ddos_shape" (volume + traffic-shape,
analytics/heavy_hitters.py) and "connection_anomaly" (per-connection
throughput spike with decoded connection identity and the arrival→alert
latency_s, analytics/streaming.py). The reference has no streaming
alert surface at all — its analytics are batch jobs
(plugins/anomaly-detection/anomaly_detection.py); this is the
sub-second path the BASELINE north star asks for, made reachable over
the wire.

Concurrency shape (the shard-parallel, pipelined path):

  * Detector state is partitioned by destination into N_SHARDS
    independent shards (THEIA_INGEST_SHARDS, default min(8, cores)),
    each holding its own HeavyHitterDetector + StreamingDetector and
    its own lock — concurrent producer streams score concurrently
    instead of queueing on one global detector lock.
  * Within one request the two independent legs — the store insert
    (MV fan-out, TTL) and detector scoring — run overlapped, so
    request latency is max(legs), not their sum.
  * The ingest-global dictionary remap has its own fine-grained lock;
    minting a new global code never stalls another shard's scoring.

Overload control (manager/admission.py): every `/ingest` request
passes the admission plane first — token buckets (THEIA_INGEST_RATE /
THEIA_INGEST_BURST), pressure watermarks over the insert backlog, WAL
sync lag, and job queue, and a brownout ladder that sheds the scoring
leg before rejecting (429 + Retry-After; durability is never shed).
Producers that stamp batches with `?seq=<n>` get exactly-once retried
ingest through a bounded per-stream dedup window that survives crash
recovery via the WAL record tags.

Ordering guarantee: alerts are deterministic PER CONNECTION. A
destination always hashes to the same shard (a stable string hash,
not a dictionary code — so the assignment survives restarts), the
connection 6-tuple contains the destination, and a shard applies one
stream's batches in ack order; so each connection's EWMA/CMS state
sees its own points in exactly the order the producer sent them,
whatever other streams do concurrently. There is no GLOBAL alert
order across connections, and heavy-hitter shares are evaluated
against an eventually-consistent cluster-total volume (a shard reads
its peers' last-published totals without locking them).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading

from ..analysis.lockdep import named_lock
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ..analytics.heavy_hitters import HeavyHitterDetector
from ..analytics.streaming import StreamingDetector
from ..ingest.native import BLOCK_MAGIC, BLOCK_MAGIC_V1, TsvDecoder
from ..store import wire as _wire
from ..store.wal import RECORD_MAGIC
from ..obs import metrics as _metrics
from ..obs import trace as _trace
from ..schema import ColumnarBatch, DictionaryMapper, StringDictionary
from ..utils import get_logger
from ..utils.env import env_int
from . import admission as _admission
from .admission import (
    LEVEL_NAMES,
    LEVEL_OK,
    AdmissionController,
    DedupWindow,
)

logger = get_logger("ingest")

# Per-stage latency of the pipelined ingest path. The three stages of
# one request overlap (store-insert ∥ detector), so their histograms
# are independent distributions, not a partition of request time.
_M_STAGE = _metrics.histogram(
    "theia_ingest_stage_seconds",
    "Per-stage ingest latency (decode under the stream lock; "
    "store_insert and detector run overlapped)",
    labelnames=("stage",))
_M_STAGE_DECODE = _M_STAGE.labels(stage="decode")
_M_STAGE_STORE = _M_STAGE.labels(stage="store_insert")
_M_STAGE_DET = _M_STAGE.labels(stage="detector")
_M_REQUEST = _metrics.histogram(
    "theia_ingest_request_seconds",
    "Whole POST /ingest request latency (decode + max(legs))")
_M_ROWS = _metrics.counter(
    "theia_ingest_rows_total", "Rows acked on the ingest path")
_M_BATCHES = _metrics.counter(
    "theia_ingest_batches_total", "Ingest payloads decoded and acked")
_M_ERRORS = _metrics.counter(
    "theia_ingest_errors_total",
    "Failed ingest requests (decode errors reset the stream; insert "
    "errors keep detector state advanced)", labelnames=("stage",))
_M_ALERTS = _metrics.counter(
    "theia_ingest_alerts_total", "Alerts published to the ring",
    labelnames=("kind",))
# Shard-scored rows use the striped increment path: the caller holds
# the shard lock, so stripe=shard.index has exactly one writer.
_M_SCORED = _metrics.counter(
    "theia_ingest_scored_rows_total",
    "Rows scored by the detector shards (striped per shard)")
_M_LOCK_MISS = _metrics.counter(
    "theia_ingest_shard_lock_misses_total",
    "Opportunistic shard-lock acquisitions that found the shard busy "
    "(the request moved on to a free shard)")
_M_LOCK_WAIT = _metrics.counter(
    "theia_ingest_shard_lock_waits_total",
    "Forced blocking shard-lock acquisitions (every remaining shard "
    "was busy — the convoy case)")
_M_SHED_ROWS = _metrics.counter(
    "theia_ingest_shed_rows_total",
    "Rows whose detector/scoring leg was shed by the brownout ladder "
    "(the rows themselves were stored and acknowledged)",
    labelnames=("mode",))

MAX_ALERTS = 1000


MAX_STREAMS = 64


def default_ingest_shards() -> int:
    """Detector shard count: THEIA_INGEST_SHARDS wins, else one shard
    per host core up to 8 (past that the slices get too small to beat
    the per-slice dispatch overhead)."""
    n = env_int("THEIA_INGEST_SHARDS", 0)
    if n <= 0:
        n = min(8, os.cpu_count() or 1)
    return max(1, n)


#: selectable scoring engines (THEIA_DETECTOR_ENGINE): "sharded" is
#: today's per-shard-lock path; "fused" is the device-resident
#: coalescing pipeline (ingest/device_path.py) — a drop-in with the
#: same alert semantics; "auto" resolves per backend at construction
#: (fused on TPU/GPU, sharded on CPU-only — the PR-16 crossover
#: measurement in docs/ingest.md)
DETECTOR_ENGINES = ("sharded", "fused", "auto")


def default_detector_engine() -> str:
    name = os.environ.get("THEIA_DETECTOR_ENGINE", "").strip().lower()
    return name or "sharded"


def resolve_auto_engine() -> str:
    """`auto` → concrete engine for this host: the fused single-
    dispatch pipeline wins on accelerator backends, while CPU-only
    hosts measure faster on the sharded per-lock path (448k vs 642k
    rows/s detector-leg on the 2-core reference host — the crossover
    docs/ingest.md records). Unprobeable backend resolves sharded:
    the conservative engine is the one that cannot need a device."""
    try:
        import jax
        backend = jax.default_backend()
    except Exception:
        return "sharded"
    return "fused" if backend in ("tpu", "gpu") else "sharded"


class StreamCapacityError(Exception):
    """All stream slots are held by active producers (→ HTTP 503:
    retryable capacity condition, not a payload error)."""


class _Stream:
    def __init__(self) -> None:
        self.decoder = TsvDecoder()
        self.lock = named_lock("ingest.stream")
        self.last_used = time.monotonic()


class DetectorShard:
    """One independently-lockable partition of detector state: its own
    CMS/k-means heavy-hitter detector and its own EWMA slot table.
    Keys are routed here by stable destination hash, so a given
    destination's (and therefore connection's) whole history lives in
    exactly one shard — per-key update order is preserved however many
    shards run concurrently."""

    def __init__(self, index: int, heavy: HeavyHitterDetector,
                 streaming: StreamingDetector) -> None:
        self.index = index
        self.heavy = heavy
        self.streaming = streaming
        self.lock = named_lock("ingest.shard")


class IngestManager:
    """Shard-parallel ingest path: wire bytes → store ∥ detectors.

    Each producer is a *stream* (`?stream=<id>`, default "default")
    with its own decoder, because a TFB2 block sequence carries
    dictionary DELTAS relative to that producer's own stream — the
    same discipline as one ClickHouse native-protocol connection. Any
    payload type advances its stream's dictionaries, so keep block and
    TSV producers on separate streams.

    Failure/lifetime semantics (again mirroring a native-protocol
    connection): a payload that fails to decode RESETS the stream (the
    decoder is discarded — a partially-applied decode would otherwise
    desync the dictionary chain for good) and the producer restarts
    with a fresh encoder. When the stream table is full, only a stream
    idle for > IDLE_EVICT_SECONDS is evicted to admit the new one;
    with MAX_STREAMS active producers a new stream is refused with
    StreamCapacityError (HTTP 503, retryable) rather than breaking an
    active producer's delta chain. Decoded batches re-encode into the
    store's dictionaries on insert (Table adoption), so streams never
    need to know store state."""

    #: streams idle longer than this may be evicted to admit new ones
    IDLE_EVICT_SECONDS = 300.0

    #: string key columns remapped to ingest-global codes before
    #: scoring (both detectors key on them; see _remap_global)
    GLOBAL_COLUMNS = ("sourceIP", "destinationIP")

    def __init__(self, db, detector: Optional[HeavyHitterDetector] = None,
                 streaming: Optional[StreamingDetector] = None,
                 n_shards: Optional[int] = None,
                 admission: Optional[AdmissionController] = None,
                 engine: Optional[str] = None,
                 streaming_capacity: Optional[int] = None
                 ) -> None:
        self.db = db
        self._streams: Dict[str, _Stream] = {}
        self._registry_lock = named_lock("ingest.registry")
        # Injected detector instances pin the manager to ONE shard
        # (there is a single state table to keep coherent); otherwise
        # detector state shards n_shards ways.
        if detector is not None or streaming is not None:
            n_shards = 1
        elif n_shards is None:
            n_shards = default_ingest_shards()
        self.n_shards = max(1, int(n_shards))
        engine = (engine or default_detector_engine()).strip().lower()
        if engine not in DETECTOR_ENGINES:
            raise ValueError(
                f"unknown detector engine {engine!r} "
                f"(THEIA_DETECTOR_ENGINE): expected one of "
                f"{DETECTOR_ENGINES}")
        self.engine_requested = engine
        if engine == "auto":
            engine = resolve_auto_engine()
            logger.info("detector engine auto → %s", engine)
        self.engine_name = engine
        _stream_kwargs = ({"capacity": int(streaming_capacity)}
                          if streaming_capacity else {})
        # Working-set state tier (THEIA_STATE_TIER=1,
        # ingest/state_tier.py): per-shard three-tier state stores —
        # slot overflow spills LRU state to DRAM + the `detstate`
        # result table (durable through WAL/snapshot/resync) instead
        # of permanently dropping new series. Constructed only for
        # manager-owned detectors; injected instances keep whatever
        # tiering their creator chose.
        self._tiers: List = []
        _tiers: List = []
        if detector is None and streaming is None:
            from ..ingest import state_tier as _state_tier
            if _state_tier.enabled():
                cfg = _state_tier.TierConfig.from_env()
                table = getattr(db, "result_tables", {}) or {}
                table = table.get(_state_tier.DETSTATE_TABLE)
                cold = _state_tier.SpillStore.recover_cold_indexes(
                    table, self.n_shards, self.shard_of_destination)
                spilled = sum(len(c) for c in cold)
                if spilled:
                    logger.info(
                        "state tier recovered %d spilled series from "
                        "the %s table", spilled,
                        _state_tier.DETSTATE_TABLE)
                _tiers = [
                    _state_tier.WorkingSetTier(
                        cfg,
                        store=(_state_tier.SpillStore(table)
                               if table is not None else None),
                        key_resolver=self._resolve_keys,
                        cold_index=cold[i])
                    for i in range(self.n_shards)]
                self._tiers = _tiers
        self.shards: List[DetectorShard] = [
            DetectorShard(i,
                          detector if detector is not None
                          else HeavyHitterDetector(),
                          streaming if streaming is not None
                          else StreamingDetector(
                              tier=_tiers[i] if _tiers else None,
                              **_stream_kwargs))
            for i in range(self.n_shards)]
        # Last-published CMS total per shard: peers read these without
        # taking the owner's lock, so heavy-hitter shares measure an
        # eventually-consistent cluster total instead of serializing
        # every shard on every batch.
        self._shard_totals = np.zeros(self.n_shards, np.float64)
        # Fused engine: same DetectorShard state objects, scored by
        # the coalescing single-dispatch pipeline instead of the
        # per-shard-lock loop below. Imported lazily — the module
        # pulls in the fused kernels, which a sharded-only manager
        # never needs.
        self._fused = None
        if engine == "fused":
            from ..ingest.device_path import FusedDetectorEngine
            self._fused = FusedDetectorEngine(
                self.shards, self._shard_totals,
                on_scored=lambda n, stripe: _M_SCORED.inc(
                    n, stripe=stripe))
        # The alert ring has its own cheap lock: GET /alerts never
        # waits behind scoring or JIT compilation.
        self._alerts_lock = named_lock("ingest.alerts")
        self._alerts: Deque[Dict[str, object]] = collections.deque(
            maxlen=MAX_ALERTS)
        self.rows_ingested = 0
        # Detector keys must be stable across streams and stream
        # resets; stream-local dictionary codes are neither, so the
        # key columns re-encode against these ingest-global
        # dictionaries before scoring (cached incremental mappings,
        # schema.DictionaryMapper — no string objects on the hot
        # path). Sized to survive reset churn across MAX_STREAMS
        # producers. The remap has its OWN fine-grained lock so dict
        # maintenance for one batch never blocks another batch's
        # shard scoring.
        self._dict_lock = named_lock("ingest.dict")
        self._global_dicts: Dict[str, StringDictionary] = {
            c: StringDictionary() for c in self.GLOBAL_COLUMNS}
        self._mappers: Dict[str, DictionaryMapper] = {
            c: DictionaryMapper(self._global_dicts[c],
                                max_entries=2 * MAX_STREAMS)
            for c in self.GLOBAL_COLUMNS}
        # destination global code → shard, extended lazily as codes
        # are minted (each new destination string is hashed ONCE; the
        # per-row partition is then a pure integer gather).
        self._dst_shard = np.zeros(1, np.int64)   # code 0: ""
        # Pipelining pool for the store-insert leg (the groupsum MV
        # fan-out releases the GIL, so it genuinely overlaps the
        # detector leg's numpy/XLA work). Each in-flight request holds
        # at most one insert, so size to request concurrency — host
        # parallelism with headroom, capped at the stream slot count —
        # NOT to the detector shard count, which is unrelated to
        # insert parallelism.
        self._insert_workers = min(MAX_STREAMS,
                                   max(4, 2 * (os.cpu_count() or 1)))
        self._insert_pool = ThreadPoolExecutor(
            max_workers=self._insert_workers,
            thread_name_prefix="theia-ingest-insert")
        # In-flight store-insert legs, tracked so close() can drain
        # them with a BOUND (ThreadPoolExecutor.shutdown(wait=True)
        # has none, and one wedged insert must not hang SIGTERM
        # forever past the WAL-fsync/final-checkpoint steps).
        self._inflight_lock = named_lock("ingest.inflight")
        self._inflight: set = set()
        # -- overload-control plane (manager/admission.py) -----------
        # Explicit backlog bound: the insert pool's queue used to grow
        # without limit during a store stall; crossing the high
        # watermark now drives the admission ladder to reject instead.
        self.inflight_high = env_int("THEIA_INGEST_INFLIGHT_HIGH",
                                     0) or 2 * self._insert_workers
        if os.environ.get("THEIA_ADMISSION_DISABLED", "") == "1":
            self.admission: Optional[AdmissionController] = None
        else:
            self.admission = (admission if admission is not None
                              else AdmissionController())
        if self.admission is not None:
            self.admission.add_signal("insertBacklog",
                                      self.inflight_count,
                                      self.inflight_high)
            self.admission.add_signal(
                "walLag", self._wal_lag,
                env_int("THEIA_WAL_LAG_HIGH", 50_000))
            if self._fused is not None:
                # Fused-pipeline backlog: a slow/wedged device step
                # fills the bounded queue; crossing the watermark
                # walks the brownout ladder (sampled scoring → shed
                # detector → reject) instead of stacking requests
                # behind an invisible device stall.
                self.admission.add_signal(
                    "fusedQueue", self._fused.queue_depth,
                    env_int("THEIA_FUSED_QUEUE_HIGH", 0)
                    or self._fused.queue_capacity)
            if self._tiers:
                # Spill-tier occupancy as overload pressure: a spilled
                # series costs DRAM + a promote on re-arrival, so an
                # unbounded working set walks the brownout ladder
                # before it walks the host into swap.
                self.admission.add_signal(
                    "stateSpill",
                    lambda: sum(t.spilled_count for t in self._tiers),
                    env_int("THEIA_STATE_SPILL_HIGH", 1_000_000))
        # -- cluster tier hooks (theia_tpu/cluster wires these) ------
        # Router: split decoded batches by owner node, forward remote
        # slices (role `peer` routing mesh).
        self.router = None
        # Durability gate: called after the local insert leg, before
        # the acknowledgement — the replication leader blocks here
        # until the configured follower ack quorum holds the batch
        # (raises ReplicationLagError → HTTP 503).
        self.durability_gate: Optional[Callable[[], None]] = None
        # Exactly-once retried ingest: (stream, seq)-stamped batches
        # dedup against this window; recovery re-seeds it from the
        # tags the WAL replay surfaced, so the idempotency contract
        # survives kill -9.
        self.dedup = DedupWindow()
        # (stream, seq) batches currently IN FLIGHT: a retry racing
        # its still-processing original (client timeout shorter than a
        # stalled insert — the overload case) must not decode+insert a
        # second copy, and must not re-apply the block's dictionary
        # delta; it is answered 429 and finds duplicate:true once the
        # original acks.
        self._pending_lock = named_lock("ingest.pending")
        self._pending: set = set()
        # Decoded-but-unacknowledged batches parked by a post-decode
        # failure (replication-quorum timeout, forwarded-slice
        # failure, insert error): the DECODE already advanced the
        # stream's dictionary-delta chain, so the producer's mandated
        # same-bytes retry must NOT decode again (the delta base no
        # longer matches — "dictionary desync") — it replays the
        # parked decoded batch instead. One entry per stream (a
        # producer retries its failed block before sending the next),
        # bounded, cleared on success.
        self._parked_lock = named_lock("ingest.parked")
        self._parked: "collections.OrderedDict[str, Tuple[int, ColumnarBatch]]" = (
            collections.OrderedDict())
        recovered = getattr(db, "recovered_acks", None)
        if callable(recovered):
            n_seeded = 0
            for ack_stream, ack_seq, ack_rows, ack_total \
                    in recovered():
                if ack_total is not None and ack_rows < ack_total:
                    # A sharded batch's slices fsync independently
                    # under interval sync: part of this acked batch
                    # was not durable at the crash. Seeding anyway is
                    # the lesser evil — NOT seeding would make the
                    # producer's retry duplicate every recovered row —
                    # but the shortfall must be loud, and it is
                    # bounded by the WAL sync policy's documented loss
                    # window (THEIA_WAL_SYNC=always closes it).
                    logger.error(
                        "recovered ack (stream=%r seq=%d) is PARTIAL:"
                        " %d of %d rows were durable at the crash; "
                        "the missing rows are within the WAL sync-"
                        "policy loss bound and a retry will be "
                        "answered duplicate:true", ack_stream,
                        ack_seq, ack_rows, ack_total)
                self.dedup.record(ack_stream, ack_seq, ack_rows)
                n_seeded += 1
            if n_seeded:
                logger.info(
                    "dedup window seeded with %d acknowledged "
                    "batches recovered from the WAL", n_seeded)

    def _submit_insert(self, fn, *args):
        fut = self._insert_pool.submit(fn, *args)
        with self._inflight_lock:
            self._inflight.add(fut)
        fut.add_done_callback(self._discard_inflight)
        return fut

    def _discard_inflight(self, fut) -> None:
        with self._inflight_lock:
            self._inflight.discard(fut)

    def inflight_count(self) -> int:
        """Store-insert legs submitted but not finished — the insert
        backlog the admission plane watches against `inflight_high`."""
        with self._inflight_lock:
            return len(self._inflight)

    def _wal_lag(self) -> int:
        fn = getattr(self.db, "wal_lag", None)
        try:
            return int(fn()) if callable(fn) else 0
        except Exception:
            return 0

    def close(self, drain: bool = True,
              drain_timeout: float = 60.0) -> None:
        """Release the pipelining pool's threads (idempotent). By
        default DRAINS queued/in-flight store-insert legs first —
        those rows belong to acknowledged (or about-to-be-
        acknowledged) requests, and the old shutdown(wait=False)
        dropped them on SIGTERM, exactly the loss the durability
        contract forbids — but with a bound: a wedged insert (hung
        store, fault drill) must not stall shutdown past the WAL
        fsync and final checkpoint. `drain=False` is for tests
        tearing down a deliberately wedged pool."""
        if self._fused is not None:
            # the fused scorer drains its queued steps and exits; done
            # before the insert drain so in-flight requests' scoring
            # legs resolve while their insert legs settle
            self._fused.close()
        if drain:
            import concurrent.futures as _cf
            with self._inflight_lock:
                pending = list(self._inflight)
            if pending:
                done, not_done = _cf.wait(pending,
                                          timeout=drain_timeout)
                if not_done:
                    logger.error(
                        "%d store-insert legs still running after "
                        "%.0fs drain; abandoning them (their "
                        "requests were never acknowledged)",
                        len(not_done), drain_timeout)
        self._insert_pool.shutdown(wait=False)

    def _stream(self, stream_id: str) -> _Stream:
        with self._registry_lock:
            st = self._streams.get(stream_id)
            if st is None:
                if len(self._streams) >= MAX_STREAMS:
                    # Only genuinely idle streams are evictable —
                    # evicting an active producer would break its delta
                    # chain on every block (reset thrash).
                    now = time.monotonic()
                    idle = [s for s, v in self._streams.items()
                            if now - v.last_used > self.IDLE_EVICT_SECONDS]
                    if not idle:
                        raise StreamCapacityError(
                            f"too many active ingest streams "
                            f"(max {MAX_STREAMS})")
                    victim = min(idle,
                                 key=lambda s: self._streams[s].last_used)
                    del self._streams[victim]
                    logger.v(1).info("evicted idle ingest stream %r",
                                     victim)
                st = self._streams[stream_id] = _Stream()
                logger.v(1).info("new ingest stream %r", stream_id)
            st.last_used = time.monotonic()
            return st

    def _drop_stream(self, stream_id: str, st: _Stream) -> None:
        with self._registry_lock:
            if self._streams.get(stream_id) is st:
                del self._streams[stream_id]

    def ingest(self, payload: bytes, stream: str = "default",
               seq: Optional[int] = None,
               traceparent: Optional[str] = None
               ) -> Dict[str, object]:
        """Decode one wire payload, insert ∥ score. Raises ValueError on
        malformed payloads (mapped to HTTP 400 by the API layer); the
        failing stream is reset and must restart its encoder.

        This is a trace INGRESS: a fresh trace context is minted (or
        adopted from `traceparent` — a router forward carries its
        origin's), every nested operation joins it, and the sampled
        trace id rides back in the ack as `traceId` so `theia trace
        <id>` can pull the stitched cross-node tree. An unsampled (or
        THEIA_TRACE_SAMPLE=0) request records nothing and adds no
        wire bytes.

        `seq` is the producer's monotone batch sequence number within
        its stream: a retry of an already-acknowledged (stream, seq) —
        after a timeout, a 429, or a crash+recovery — is answered
        `{"duplicate": true}` with the original row count, without
        touching decoder, store, or detector state. The duplicate
        check runs BEFORE admission: answering a retry is how the
        producer learns its batch landed, so it must work even while
        new work is being rejected. Raises AdmissionRejected (HTTP 429
        + Retry-After) when the overload-control plane refuses the
        batch; under the brownout ladder's degraded rungs the
        detector/scoring leg is sampled or shed while rows stay
        durable (WAL + store) and acknowledged."""
        # THEIA_TRACE_SAMPLE_INGEST dials THIS ingress independently:
        # ingest runs orders of magnitude hotter than queries or
        # replication, and an un-dialed 1.0 rate would churn the
        # bounded span ring in seconds at production batch rates
        with _trace.ingress_span("ingest.request",
                                 traceparent=traceparent,
                                 sample_env="THEIA_TRACE_SAMPLE_INGEST",
                                 stream=stream) as sp:
            out = self._ingest_span_body(payload, stream, seq)
            sp.attrs["rows"] = out.get("rows", 0)
            if out.get("alerts"):
                sp.attrs["alerts"] = out["alerts"]
            if out.get("duplicate"):
                sp.attrs["duplicate"] = True
            ctx = _trace.current_context()
            if ctx is not None:
                out["traceId"] = ctx.trace_id
            return out

    def _ingest_span_body(self, payload: bytes, stream: str,
                          seq: Optional[int]) -> Dict[str, object]:
        t_req = time.perf_counter()
        if seq is not None:
            seq = int(seq)
            dup_rows = self.dedup.lookup(stream, seq)
            if dup_rows is None:
                with self._pending_lock:
                    if (stream, seq) in self._pending:
                        # the original attempt is still running: a
                        # second decode would double-insert AND
                        # corrupt the stream's dictionary-delta chain
                        # — tell the producer to come back for its
                        # duplicate ack
                        if self.admission is not None:
                            # keep /healthz admission.rejected in
                            # lockstep with the metric
                            self.admission.note_rejected()
                        _admission._M_REJECTED.labels(
                            reason="in_flight").inc()
                        raise _admission.AdmissionRejected(
                            "in_flight", 0.25,
                            f"(stream={stream!r}, seq={seq}) is "
                            f"still being processed")
                    # Re-check under the lock: the original may have
                    # COMPLETED between the lock-free lookup above and
                    # here (it records its ack strictly before it
                    # drops its reservation, so a second miss now is
                    # authoritative — no completed-and-acked original
                    # exists).
                    dup_rows = self.dedup.lookup(stream, seq)
                    if dup_rows is None:
                        self._pending.add((stream, seq))
            if dup_rows is not None:
                _admission._M_DEDUP_HITS.inc()
                _admission._M_DUP_ROWS.inc(dup_rows)
                logger.v(1).info(
                    "duplicate batch (stream=%r seq=%d, %d rows) "
                    "acked idempotently", stream, seq, dup_rows)
                return {"rows": dup_rows, "alerts": 0,
                        "duplicate": True}
        try:
            return self._ingest_admitted(payload, stream, seq, t_req)
        finally:
            if seq is not None:
                with self._pending_lock:
                    self._pending.discard((stream, seq))

    def _ingest_admitted(self, payload: bytes, stream: str,
                         seq: Optional[int],
                         t_req: float) -> Dict[str, object]:
        magic = payload[:4]
        is_record = magic == RECORD_MAGIC
        is_block = magic == _wire.BLOCK_MAGIC
        rows_hint: Optional[int] = None
        if is_block:
            # TBLK: the block header names the exact row count, and
            # `peek_counts` validates it against the payload size — so
            # admission charges BOTH bytes and rows up front, without
            # decoding a single column. A malformed header rejects
            # here (→ 400) before it can touch any bucket.
            try:
                rows_hint, _ = _wire.peek_counts(payload, 4)
            except _wire.WireCorruption:
                _M_ERRORS.labels(stage="decode").inc()
                raise
        level = LEVEL_OK
        if self.admission is not None:
            # raises AdmissionRejected → 429 + Retry-After (payload
            # bytes are charged here; rows after decode — except TBLK,
            # whose header already charged them via rows_hint). The
            # kwarg is passed only when a hint exists, so admit()
            # stubs/wrappers with the pre-TBLK two-arg signature keep
            # working for non-TBLK payloads.
            if rows_hint is None:
                level = self.admission.admit(stream, len(payload))
            else:
                level = self.admission.admit(stream, len(payload),
                                             rows_hint=rows_hint)
        parked = None
        if seq is not None and not is_record and not is_block:
            with self._parked_lock:
                pk = self._parked.get(stream)
                if pk is not None and pk[0] == seq:
                    parked = pk[1]
        wire_mv: Optional[memoryview] = None
        pre_routed: Optional[List[Tuple[str, bytes, int]]] = None
        if parked is not None:
            # this block already decoded once (its failed attempt
            # advanced the stream's delta chain and charged the row
            # bucket) — replay the decoded form, don't decode again
            batch = parked
        elif is_record:
            # Self-contained WAL-record payload (a router forward or a
            # demoted leader's tail re-ingest): decodes statelessly —
            # no stream slot, no dictionary-delta chain, and NEVER
            # re-routed (its origin already placed it).
            t_dec = time.perf_counter()
            try:
                from ..store.wal import (decode_record_body,
                                         split_dedup_tag)
                table, batch = decode_record_body(payload[4:])
                # a tail re-ingest ships the original (tagged) record
                # verbatim; identity comes from the query params, the
                # embedded tag is informational
                table, _tag = split_dedup_tag(table)
                if table != "flows":
                    raise ValueError(
                        f"TREC payload targets table {table!r}")
            except ValueError:
                _M_ERRORS.labels(stage="decode").inc()
                raise
            except Exception as e:
                _M_ERRORS.labels(stage="decode").inc()
                raise ValueError(f"undecodable TREC payload: {e}")
            _M_STAGE_DECODE.observe(time.perf_counter() - t_dec)
        elif is_block:
            # Self-contained TBLK block (the TFB3 producer format):
            # stateless decode — no stream slot, no dictionary-delta
            # chain, and no parked-batch bookkeeping (a retry simply
            # decodes the identical bytes again). The received column
            # section (`wire_mv`) rides on to the WAL so the journal
            # writes the producer's bytes VERBATIM instead of
            # re-encoding the adopted batch.
            t_dec = time.perf_counter()
            try:
                wire_mv = memoryview(payload)[4:]
                fwd = (self.router.split_wire(wire_mv)
                       if self.router is not None else None)
                if fwd is not None:
                    # cross-node split on the ENCODED bytes: only
                    # destinationIP was decoded to compute owners,
                    # remote slices left as column-gathered TREC
                    # payloads, and only the LOCAL slice is decoded
                    # in full here
                    local_wire, pre_routed = fwd
                    wire_mv = memoryview(local_wire)
                    batch, _end = _wire.decode_columns(wire_mv)
                else:
                    batch = _wire.decode_block(payload)
            except ValueError:
                _M_ERRORS.labels(stage="decode").inc()
                raise
            _M_STAGE_DECODE.observe(time.perf_counter() - t_dec)
        else:
            st = self._stream(stream)
            # The stream lock guards only the DECODE (the dictionary-
            # delta chain is per-stream state); the store insert runs
            # outside it, so one producer's slow insert (TTL scan, MV
            # fan-out) never blocks its next block's decode on another
            # thread, and different streams insert fully concurrently.
            # Store-visible order across racing blocks of one stream
            # is not defined — the store orders by timeInserted, not
            # arrival, exactly like concurrent INSERTs on one
            # ClickHouse connection pool. The same holds for the
            # DETECTOR leg: streaming state (CMS counts, EWMA
            # recurrences) is order-sensitive, so a producer that
            # pipelines blocks of one stream concurrently gets
            # nondeterministic alert output for the racing blocks; a
            # producer that needs reproducible alerting must await
            # each response before sending the next block.
            with st.lock:
                t_dec = time.perf_counter()
                try:
                    if payload[:4] in (BLOCK_MAGIC, BLOCK_MAGIC_V1):
                        batch = st.decoder.decode_block(payload)
                    else:
                        batch = st.decoder.decode(payload)
                except Exception:
                    # A failed decode may have partially advanced the
                    # dictionaries (TSV minting is not transactional)
                    # — discard the stream rather than serve a
                    # desynced one.
                    self._drop_stream(stream, st)
                    _M_ERRORS.labels(stage="decode").inc()
                    raise
                _M_STAGE_DECODE.observe(time.perf_counter() - t_dec)
        if parked is None and not is_block \
                and self.admission is not None:
            # post-decode row accounting: the row bucket may go into
            # debt, which rejects FUTURE requests until it refills
            # (TBLK already charged its exact count from the header)
            self.admission.charge_rows(stream, len(batch))
        try:
            out = self._apply_decoded(batch, stream, seq, level,
                                      t_req, is_record, wire=wire_mv,
                                      pre_routed=pre_routed)
        except Exception:
            if seq is not None and not is_record and not is_block:
                # the stream's delta chain is already advanced past
                # this block: hold its decoded form for the retry
                self._park(stream, seq, batch)
            raise
        if seq is not None and not is_record and not is_block:
            self._unpark(stream, seq)
        return out

    #: parked decoded batches are capped (failure-path state only;
    #: entries clear the moment a retry succeeds)
    MAX_PARKED = 4 * MAX_STREAMS

    def _park(self, stream: str, seq: int, batch: ColumnarBatch) -> None:
        with self._parked_lock:
            self._parked[stream] = (int(seq), batch)
            self._parked.move_to_end(stream)
            while len(self._parked) > self.MAX_PARKED:
                self._parked.popitem(last=False)

    def _unpark(self, stream: str, seq: int) -> None:
        with self._parked_lock:
            pk = self._parked.get(stream)
            if pk is not None and pk[0] == int(seq):
                del self._parked[stream]

    def _apply_decoded(self, batch: ColumnarBatch, stream: str,
                       seq: Optional[int], level: int, t_req: float,
                       is_record: bool,
                       wire: Optional[memoryview] = None,
                       pre_routed: Optional[List] = None
                       ) -> Dict[str, object]:
        """Everything after a successful decode: routing, the
        pipelined insert ∥ score legs, the replication durability
        gate, dedup acks, and the response. Split out so a failure
        anywhere in here can park the decoded batch for the retry.

        `wire` is the received TBLK column section covering exactly
        `batch`'s rows (already gathered down to the local slice when
        routed) — threaded to the store so the WAL journals it
        verbatim. `pre_routed` carries `split_wire`'s already-gathered
        remote slices; the TFB2/TSV path routes here instead, on the
        decoded batch."""
        # -- cluster routing: keep owned rows, forward the rest --------
        # (before the pipelined legs: forwards overlap the local
        # insert/score work; owners admit/score/dedup their slices
        # themselves). A retry re-splits identically — the hash is a
        # pure function of the rows — so owners answer duplicate:true
        # and the local slice dedups under its origin sub-stream.
        routed = None
        eff_stream = stream
        local_dup: Optional[int] = None
        if pre_routed is not None:
            routed = self.router.forward_all_wire(pre_routed, stream,
                                                  seq)
            if seq is not None:
                eff_stream = self.router.sub_stream(stream)
                local_dup = self.dedup.lookup(eff_stream, seq)
        elif self.router is not None and not is_record \
                and wire is None:
            local_batch, remote = self.router.split(batch)
            if remote:
                routed = self.router.forward_all(remote, stream, seq)
                batch = local_batch
                if seq is not None:
                    eff_stream = self.router.sub_stream(stream)
                    local_dup = self.dedup.lookup(eff_stream, seq)
        # Pipelined legs: the store insert (MV fan-out, TTL) and the
        # detector scoring are independent consumers of the decoded
        # batch (both read-only), so they run overlapped and the
        # request completes in max(legs), not their sum. Consequence
        # for a FAILED insert: scoring has already advanced detector
        # sketch state (that can't be rolled back), so a producer
        # retrying the 5xx'd payload counts those rows twice in the
        # detectors — at-least-once detector semantics, where the
        # pre-pipelined path skipped scoring on insert failure (a
        # seq-stamped producer avoids the double count entirely: the
        # retry of an acked batch never reaches the detectors). The
        # batch's alerts are still withheld (published only after the
        # insert leg succeeds, below), and the store itself stays
        # exactly-once.
        # the tag carries the LOGICAL batch size so a sharded store's
        # per-slice WAL records can reconstruct (and sanity-check) the
        # whole ack at recovery; a routed batch tags its LOCAL slice
        # under the origin sub-stream (the owners tag their own)
        dedup_tag = ((eff_stream, seq, len(batch))
                     if seq is not None else None)
        skip_local = local_dup is not None or len(batch) == 0
        fut = None
        if not skip_local:
            fut = self._submit_insert(self._timed_insert, batch,
                                      dedup_tag, wire)
        # Brownout: under pressure the scoring leg degrades first —
        # sampled at a declining fraction, then fully shed — while the
        # durable leg (WAL + store) keeps acknowledging rows.
        scored = (level == LEVEL_OK
                  or (self.admission is not None
                      and self.admission.should_score(level)))
        if skip_local:
            # local slice already landed (a routed retry) or every row
            # belongs to a remote owner — nothing to insert or score
            alerts, conn_alerts, n_conn = [], [], 0
        elif scored:
            try:
                t_det = time.perf_counter()
                alerts, conn_alerts, n_conn = self.score_batch(batch)
                _M_STAGE_DET.observe(time.perf_counter() - t_det)
            except Exception:
                _M_ERRORS.labels(stage="detector").inc()
                # await the insert leg even when scoring raised: an
                # unawaited future would hide the store's exception
                # and break acked-rows conservation. If the insert
                # SUCCEEDED, the rows (and their WAL tag) are durable
                # even though this request will 500 — record the ack
                # NOW so the producer's retry is answered
                # duplicate:true instead of double-inserting (and
                # desyncing its delta chain), exactly as a
                # crash+replay of the same record would behave.
                if fut.exception() is None and seq is not None:
                    self.dedup.record(eff_stream, seq, fut.result())
                raise
        else:
            alerts, conn_alerts, n_conn = [], [], 0
            _M_SHED_ROWS.labels(mode=LEVEL_NAMES[level]).inc(
                len(batch))
        if fut is not None:
            insert_exc = fut.exception()
            if insert_exc is not None:
                _M_ERRORS.labels(stage="store_insert").inc()
                raise insert_exc
            n = fut.result()
        else:
            n = local_dup or 0
        if seq is not None and routed is not None and fut is not None:
            # the local slice is durable: a retry of this batch must
            # not re-insert it even though the whole-batch ack below
            # is still pending on the forwards
            self.dedup.record(eff_stream, seq, n)
        remote_rows = 0
        if routed is not None:
            # owners ack (or answer duplicate:true for) their slices;
            # a slice that exhausts its retry budget raises
            # RouterForwardError → HTTP 503 → the producer retries the
            # whole batch idempotently
            remote_rows, _dups = self.router.await_all(routed)
        if self.durability_gate is not None and not skip_local:
            # replication quorum: block the acknowledgement until the
            # configured follower quorum holds the local WAL append
            # (raises ReplicationLagError → HTTP 503, retry-safe)
            self.durability_gate()
        total = n + remote_rows
        if seq is not None:
            # the ack is now durable to the WAL's policy bound (and
            # the quorum's, when configured); a retry of this
            # (stream, seq) is idempotent from here on
            self.dedup.record(stream, seq, total)
        now = time.time()
        n_alerts = len(alerts) + n_conn
        with self._alerts_lock:
            for a in alerts:
                self._alerts.appendleft(
                    {**dataclasses.asdict(a), "time": now})
            for d in conn_alerts:
                self._alerts.appendleft({**d, "time": now})
            self.rows_ingested += n
        _M_BATCHES.inc()
        _M_ROWS.inc(n)
        if alerts:
            _M_ALERTS.labels(kind="heavy_hitter").inc(len(alerts))
        if n_conn:
            _M_ALERTS.labels(kind="connection_anomaly").inc(n_conn)
        dt_req = time.perf_counter() - t_req
        _M_REQUEST.observe(dt_req)
        # the enclosing ingress span (ingest()) is the flight record
        # now — sampled requests publish with trace context attached;
        # tune THEIA_TRACE_SAMPLE down instead of a slow-only filter
        if n_alerts:
            logger.v(1).info("ingested %d rows, %d alerts", n, n_alerts)
        out: Dict[str, object] = {"rows": total, "alerts": n_alerts}
        if remote_rows:
            # rows this node forwarded to their owner-shard peers
            # (scored and alert-ringed THERE, not here)
            out["forwardedRows"] = remote_rows
        if not scored:
            # the producer sees its rows were stored but not scored —
            # alert absence under brownout is degradation, not quiet
            out["degraded"] = LEVEL_NAMES[level]
        return out

    def _timed_insert(self, batch: ColumnarBatch,
                      dedup: Optional[Tuple[str, int]] = None,
                      wire: Optional[memoryview] = None) -> int:
        t0 = time.perf_counter()
        try:
            # kwargs are passed only when set, so minimal insert_flows
            # signatures (test doubles, pre-wire stores) keep working
            kwargs: Dict[str, object] = {}
            if dedup is not None:
                kwargs["dedup"] = dedup
            if wire is not None:
                kwargs["wire"] = wire
            return self.db.insert_flows(batch, **kwargs)
        finally:
            _M_STAGE_STORE.observe(time.perf_counter() - t0)

    # -- detector leg ----------------------------------------------------

    def score_batch(self, batch: ColumnarBatch
                    ) -> Tuple[List, List[Dict[str, object]], int]:
        """Advance every shard whose keys appear in `batch`; returns
        (heavy-hitter alerts, described connection alerts, raw
        connection-alert count). Only the touched shard's lock is held
        while its slice scores, and free shards are taken first (see
        below), so requests whose keys land on different shards never
        wait on each other."""
        if len(batch) == 0:
            return [], [], 0
        scored, shard_ids = self._remap_global(batch)
        if self._fused is not None:
            # Fused engine: the remapped batch rides the coalescing
            # device pipeline (ingest/device_path.py) — no shard
            # locks, no per-shard slicing; per-shard order is the
            # pipeline's enqueue order.
            return self._fused.score(scored, shard_ids)
        hh_alerts: List = []
        raw_alerts: List[Tuple[DetectorShard, ColumnarBatch, Dict]] = []
        n_conn = 0
        # Opportunistic acquisition: score whichever touched shard is
        # free NOW, blocking only when every remaining shard is busy.
        # A fixed index-order visit would convoy concurrent requests
        # at shard 0 (every batch's keys usually span all shards);
        # visit order across shards is free to vary because slices of
        # one batch hold disjoint key sets — per-connection order is
        # enforced by the shard lock alone.
        pending: Deque = collections.deque(
            self._partition(scored, shard_ids))
        while pending:
            progressed = False
            for _ in range(len(pending)):
                shard, part = pending.popleft()
                if shard.lock.acquire(blocking=False):
                    try:
                        n_conn += self._score_shard(
                            shard, part, hh_alerts, raw_alerts)
                    finally:
                        shard.lock.release()
                    progressed = True
                else:
                    _M_LOCK_MISS.inc()
                    pending.append((shard, part))
            if not progressed and pending:
                # every remaining shard is busy — the convoy case the
                # opportunistic pass exists to avoid
                _M_LOCK_WAIT.inc()
                shard, part = pending.popleft()
                with shard.lock:
                    n_conn += self._score_shard(
                        shard, part, hh_alerts, raw_alerts)
        # The ring keeps MAX_ALERTS; in an alert storm only the newest
        # survive, so only those are worth decoding — capped over the
        # WHOLE batch, not per shard slice, and decoded outside any
        # shard lock (describe_alert only reads the slice + dicts).
        conn_alerts: List[Dict[str, object]] = []
        for shard, part, a in raw_alerts[-MAX_ALERTS:]:
            described = shard.streaming.describe_alert(part, a)
            # "row" is batch-local; meaningless once published
            described.pop("row", None)
            described["kind"] = "connection_anomaly"
            conn_alerts.append(described)
        return hh_alerts, conn_alerts, n_conn

    def _score_shard(self, shard: DetectorShard, part: ColumnarBatch,
                     hh_alerts: List,
                     raw_alerts: List[Tuple["DetectorShard",
                                            ColumnarBatch, Dict]]) -> int:
        """Advance ONE shard with its slice (caller holds shard.lock);
        appends heavy-hitter alerts and undecoded connection alerts
        (decoding is the caller's, outside the lock), returns the raw
        connection-alert count. The key columns already carry
        ingest-global codes: detector state (CMS counts,
        per-connection slots) persists across batches, so keys must
        mean the same endpoint whichever stream (or stream generation)
        produced the batch."""
        # Striped, lock-free increment: this thread holds shard.lock,
        # so it is the only writer of the shard's counter stripe.
        _M_SCORED.inc(len(part), stripe=shard.index)
        extra = float(self._shard_totals.sum()
                      - self._shard_totals[shard.index])
        hh_alerts.extend(shard.heavy.update(part, extra_total=extra))
        self._shard_totals[shard.index] = shard.heavy.total_volume
        raw_conn = shard.streaming.ingest(part)
        raw_alerts.extend((shard, part, a) for a in raw_conn)
        return len(raw_conn)

    def _remap_global(self, batch: ColumnarBatch
                      ) -> Tuple[ColumnarBatch, Optional[np.ndarray]]:
        """Stream-local → ingest-global codes for the key columns, and
        the per-row shard assignment. Only the dictionary lock is held
        — shard scoring proceeds concurrently."""
        with self._dict_lock:
            gcols = {c: self._mappers[c].remap(batch[c],
                                               batch.dicts[c])
                     for c in self.GLOBAL_COLUMNS}
            dst_shard = (self._dst_shard_table()
                         if self.n_shards > 1 else None)
        scored = ColumnarBatch(
            {**batch.columns, **gcols},
            {**batch.dicts,
             **{c: self._global_dicts[c]
                for c in self.GLOBAL_COLUMNS}})
        shard_ids = (dst_shard[gcols["destinationIP"]]
                     if dst_shard is not None else None)
        return scored, shard_ids

    def _dst_shard_table(self) -> np.ndarray:
        """code → shard for every destination code minted so far
        (caller holds the dictionary lock). Each NEW destination
        string is hashed once at mint time; rows then partition by a
        pure integer gather. The hash is over the string bytes, not
        the code, so the assignment is stable across restarts and
        ingestion orders."""
        d = self._global_dicts["destinationIP"]
        have = len(self._dst_shard)
        if have < len(d):
            fresh = np.fromiter(
                (self.shard_of_destination(s)
                 for s in d.entries_since(have)),
                dtype=np.int64)
            self._dst_shard = np.concatenate([self._dst_shard, fresh])
        return self._dst_shard

    def _resolve_keys(self, keys: np.ndarray) -> List[Tuple]:
        """String-resolve [K, 6] ingest-global connection-key rows for
        the state tier's restart-stable identity (keyHash + detstate
        rows). Called under the shard lock / fused scorer thread with
        K = keys being spilled or cold-probed, never per row. Takes
        the dictionary lock only (same shard→dict edge as _remap's
        callers; no reverse edge exists)."""
        with self._dict_lock:
            src_d = self._global_dicts["sourceIP"]
            dst_d = self._global_dicts["destinationIP"]
            return [(src_d.decode_one(int(k[0])), int(k[1]),
                     dst_d.decode_one(int(k[2])), int(k[3]),
                     int(k[4]), int(k[5])) for k in keys]

    def shard_of_destination(self, destination: str) -> int:
        """Stable shard assignment for a destination string (crc32 of
        the UTF-8 bytes mod n_shards — identical across processes,
        restarts, and ingestion orders)."""
        return zlib.crc32(
            destination.encode("utf-8", "surrogatepass")) % self.n_shards

    def _partition(self, scored: ColumnarBatch,
                   shard_ids: Optional[np.ndarray]):
        """Yield (shard, slice) for each shard with rows in `scored`,
        in shard-index order. Row order within a slice is batch order,
        so each connection's points reach its shard's recurrence in
        arrival order."""
        if shard_ids is None:
            yield self.shards[0], scored
            return
        for s in range(self.n_shards):
            idx = np.flatnonzero(shard_ids == s)
            if idx.size == 0:
                continue
            if idx.size == len(scored):
                yield self.shards[s], scored
                return
            yield self.shards[s], scored.take(idx)

    def detector_stats(self) -> Dict[str, object]:
        """Operator view of the sharded detector ensemble."""
        out = {
            "shards": self.n_shards,
            "series": [s.streaming.n_series for s in self.shards],
            "droppedSeries": [s.streaming.dropped_series
                              for s in self.shards],
            "totalVolume": float(self._shard_totals.sum()),
        }
        if self._tiers:
            out["stateTier"] = [t.stats() for t in self._tiers]
        return out

    def shard_liveness(self) -> Dict[str, object]:
        """Health-surface view of the detector shards: per-shard series
        occupancy plus a non-blocking lock probe (`busy` — True means a
        request held the shard's lock at sample time; a shard that is
        busy on EVERY probe is wedged)."""
        per_shard = []
        for s in self.shards:
            acquired = s.lock.acquire(blocking=False)
            if acquired:
                s.lock.release()
            row = {
                "shard": s.index,
                "busy": not acquired,
                "series": int(s.streaming.n_series),
                "capacity": int(s.streaming.capacity),
                "droppedSeries": int(s.streaming.dropped_series),
            }
            if s.streaming.tier is not None:
                row["stateTier"] = s.streaming.tier.stats()
            per_shard.append(row)
        engine: Dict[str, object] = {"name": self.engine_name}
        if self.engine_requested != self.engine_name:
            # only informative when auto resolved the name
            engine["requested"] = self.engine_requested

        if self._fused is not None:
            engine.update(self._fused.stats())
        return {
            "shards": self.n_shards,
            "streams": len(self._streams),
            "rowsIngested": self.rows_ingested,
            "engine": engine,
            "perShard": per_shard,
        }

    def push_alert(self, alert: Dict[str, object]) -> None:
        """Publish an externally produced alert (e.g. a completed
        spatial job's noise flows) onto the ring."""
        with self._alerts_lock:
            self._alerts.appendleft({**alert, "time": time.time()})

    def recent_alerts(self, limit: int = 100) -> List[Dict[str, object]]:
        with self._alerts_lock:
            return list(self._alerts)[:max(limit, 0)]
