"""Network ingest into a running manager + live alerting.

Plays the role of the reference's flow ingestion contract (the Flow
Aggregator inserts into ClickHouse over its native TCP protocol,
pkg/util/clickhouse/clickhouse.go:125; schema create_table.sh:31-84):
producers POST flow batches to the manager —

    POST /ingest
        body: a TFB2 binary columnar block (application/octet-stream)
              or TabSeparated rows (text/tab-separated-values)
        response: {"rows": N, "alerts": K}

Every ingested batch fans out to the store (materialized views, TTL)
AND advances the streaming detectors — the heavy-hitter / DDoS sketch
AND the per-connection EWMA anomaly engine — whose alerts are served
from a bounded ring:

    GET /alerts?limit=N      most recent alerts, newest first

Alert kinds: "heavy_hitter" / "ddos_shape" (volume + traffic-shape,
analytics/heavy_hitters.py) and "connection_anomaly" (per-connection
throughput spike with decoded connection identity and the arrival→alert
latency_s, analytics/streaming.py). The reference has no streaming
alert surface at all — its analytics are batch jobs
(plugins/anomaly-detection/anomaly_detection.py); this is the
sub-second path the BASELINE north star asks for, made reachable over
the wire.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Deque, Dict, List, Optional

import numpy as np

from ..analytics.heavy_hitters import HeavyHitterDetector
from ..analytics.streaming import StreamingDetector
from ..ingest.native import BLOCK_MAGIC, BLOCK_MAGIC_V1, TsvDecoder
from ..schema import ColumnarBatch, DictionaryMapper, StringDictionary
from ..utils import get_logger

logger = get_logger("ingest")

MAX_ALERTS = 1000


MAX_STREAMS = 64


class StreamCapacityError(Exception):
    """All stream slots are held by active producers (→ HTTP 503:
    retryable capacity condition, not a payload error)."""


class _Stream:
    def __init__(self) -> None:
        self.decoder = TsvDecoder()
        self.lock = threading.Lock()
        self.last_used = time.monotonic()


class IngestManager:
    """Serialized ingest path: wire bytes → store + streaming detector.

    Each producer is a *stream* (`?stream=<id>`, default "default")
    with its own decoder, because a TFB2 block sequence carries
    dictionary DELTAS relative to that producer's own stream — the
    same discipline as one ClickHouse native-protocol connection. Any
    payload type advances its stream's dictionaries, so keep block and
    TSV producers on separate streams.

    Failure/lifetime semantics (again mirroring a native-protocol
    connection): a payload that fails to decode RESETS the stream (the
    decoder is discarded — a partially-applied decode would otherwise
    desync the dictionary chain for good) and the producer restarts
    with a fresh encoder. When the stream table is full, only a stream
    idle for > IDLE_EVICT_SECONDS is evicted to admit the new one;
    with MAX_STREAMS active producers a new stream is refused with
    StreamCapacityError (HTTP 503, retryable) rather than breaking an
    active producer's delta chain. Decoded batches re-encode into the
    store's dictionaries on insert (Table adoption), so streams never
    need to know store state."""

    #: streams idle longer than this may be evicted to admit new ones
    IDLE_EVICT_SECONDS = 300.0

    #: string key columns remapped to ingest-global codes before
    #: scoring (both detectors key on them; see _global_codes)
    GLOBAL_COLUMNS = ("sourceIP", "destinationIP")

    def __init__(self, db, detector: Optional[HeavyHitterDetector] = None,
                 streaming: Optional[StreamingDetector] = None) -> None:
        self.db = db
        self._streams: Dict[str, _Stream] = {}
        self._registry_lock = threading.Lock()
        self.detector = detector or HeavyHitterDetector()
        self.streaming = streaming or StreamingDetector()
        # Detector state (device compute) and the alert ring have
        # separate locks: GET /alerts only touches the cheap ring lock,
        # never waiting behind scoring or JIT compilation.
        self._detector_lock = threading.Lock()
        self._alerts_lock = threading.Lock()
        self._alerts: Deque[Dict[str, object]] = collections.deque(
            maxlen=MAX_ALERTS)
        self.rows_ingested = 0
        # Detector keys must be stable across streams and stream
        # resets; stream-local dictionary codes are neither, so the
        # key columns re-encode against these ingest-global
        # dictionaries before scoring (cached incremental mappings,
        # schema.DictionaryMapper — no string objects on the hot
        # path). Sized to survive reset churn across MAX_STREAMS
        # producers; serialized by the detector lock.
        self._global_dicts: Dict[str, StringDictionary] = {
            c: StringDictionary() for c in self.GLOBAL_COLUMNS}
        self._mappers: Dict[str, DictionaryMapper] = {
            c: DictionaryMapper(self._global_dicts[c],
                                max_entries=2 * MAX_STREAMS)
            for c in self.GLOBAL_COLUMNS}

    def _stream(self, stream_id: str) -> _Stream:
        with self._registry_lock:
            st = self._streams.get(stream_id)
            if st is None:
                if len(self._streams) >= MAX_STREAMS:
                    # Only genuinely idle streams are evictable —
                    # evicting an active producer would break its delta
                    # chain on every block (reset thrash).
                    now = time.monotonic()
                    idle = [s for s, v in self._streams.items()
                            if now - v.last_used > self.IDLE_EVICT_SECONDS]
                    if not idle:
                        raise StreamCapacityError(
                            f"too many active ingest streams "
                            f"(max {MAX_STREAMS})")
                    victim = min(idle,
                                 key=lambda s: self._streams[s].last_used)
                    del self._streams[victim]
                    logger.v(1).info("evicted idle ingest stream %r",
                                     victim)
                st = self._streams[stream_id] = _Stream()
                logger.v(1).info("new ingest stream %r", stream_id)
            st.last_used = time.monotonic()
            return st

    def _drop_stream(self, stream_id: str, st: _Stream) -> None:
        with self._registry_lock:
            if self._streams.get(stream_id) is st:
                del self._streams[stream_id]

    def ingest(self, payload: bytes,
               stream: str = "default") -> Dict[str, object]:
        """Decode one wire payload, insert, score. Raises ValueError on
        malformed payloads (mapped to HTTP 400 by the API layer); the
        failing stream is reset and must restart its encoder."""
        st = self._stream(stream)
        # The stream lock guards only the DECODE (the dictionary-delta
        # chain is per-stream state); the store insert runs outside it,
        # so one producer's slow insert (TTL scan, MV fan-out) never
        # blocks its next block's decode on another thread, and
        # different streams insert fully concurrently. Store-visible
        # order across racing blocks of one stream is not defined — the
        # store orders by timeInserted, not arrival, exactly like
        # concurrent INSERTs on one ClickHouse connection pool. The
        # same holds for the DETECTOR leg: streaming state (CMS counts,
        # EWMA recurrences) is order-sensitive, so a producer that
        # pipelines blocks of one stream concurrently gets
        # nondeterministic alert output for the racing blocks; a
        # producer that needs reproducible alerting must await each
        # response before sending the next block.
        with st.lock:
            try:
                if payload[:4] in (BLOCK_MAGIC, BLOCK_MAGIC_V1):
                    batch = st.decoder.decode_block(payload)
                else:
                    batch = st.decoder.decode(payload)
            except Exception:
                # A failed decode may have partially advanced the
                # dictionaries (TSV minting is not transactional) —
                # discard the stream rather than serve a desynced one.
                self._drop_stream(stream, st)
                raise
        n = self.db.insert_flows(batch)
        with self._detector_lock:
            # Re-encode the string key columns against the
            # ingest-global dictionaries: detector state (CMS counts,
            # per-connection slots) persists across batches, so keys
            # must mean the same endpoint whichever stream (or stream
            # generation) produced the batch.
            scored = ColumnarBatch(
                {**batch.columns,
                 **{c: self._global_codes(c, batch)
                    for c in self.GLOBAL_COLUMNS}},
                {**batch.dicts,
                 **{c: self._global_dicts[c]
                    for c in self.GLOBAL_COLUMNS}})
            alerts = self.detector.update(scored)
            raw_conn = self.streaming.ingest(scored)
            # The ring keeps MAX_ALERTS; in an alert storm only the
            # newest survive, so only those are worth decoding.
            n_conn = len(raw_conn)
            conn_alerts = []
            for a in raw_conn[-MAX_ALERTS:]:
                described = self.streaming.describe_alert(scored, a)
                # "row" is batch-local; meaningless once published
                described.pop("row", None)
                described["kind"] = "connection_anomaly"
                conn_alerts.append(described)
        now = time.time()
        n_alerts = len(alerts) + n_conn
        with self._alerts_lock:
            for a in alerts:
                self._alerts.appendleft(
                    {**dataclasses.asdict(a), "time": now})
            for d in conn_alerts:
                self._alerts.appendleft({**d, "time": now})
            self.rows_ingested += n
        if n_alerts:
            logger.v(1).info("ingested %d rows, %d alerts", n, n_alerts)
        return {"rows": n, "alerts": n_alerts}

    def _global_codes(self, column: str,
                      batch: ColumnarBatch) -> np.ndarray:
        """Stream-local → ingest-global codes for `column` (caller
        holds the detector lock)."""
        return self._mappers[column].remap(batch[column],
                                           batch.dicts[column])

    def push_alert(self, alert: Dict[str, object]) -> None:
        """Publish an externally produced alert (e.g. a completed
        spatial job's noise flows) onto the ring."""
        with self._alerts_lock:
            self._alerts.appendleft({**alert, "time": time.time()})

    def recent_alerts(self, limit: int = 100) -> List[Dict[str, object]]:
        with self._alerts_lock:
            return list(self._alerts)[:max(limit, 0)]
