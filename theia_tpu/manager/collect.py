"""Shared async single-flight collector for downloadable artifacts
(support bundles, profiler traces).

One state machine — none → collecting → collected | failed (with
errorMsg) — so every artifact endpoint speaks the same status
vocabulary and the CLI's poll-then-download client behaves identically
against all of them.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..utils import get_logger
from ..analysis.lockdep import named_lock

logger = get_logger("collect")


class AsyncCollector:
    """Subclasses implement `_collect(*args) -> bytes` (the artifact)
    and set `kind`; `create()` runs it on a daemon thread, single
    flight."""

    kind = "Artifact"
    api_version = "system.theia.antrea.io/v1alpha1"
    name = "theia-manager"

    def __init__(self) -> None:
        self.status = "none"
        self._data: Optional[bytes] = None
        self._error = ""
        self._lock = named_lock("manager.collect")

    def _collect(self, *args) -> bytes:
        raise NotImplementedError

    def create(self, *args) -> Dict[str, object]:
        with self._lock:
            already = self.status == "collecting"
            if not already:
                self.status = "collecting"
                self._error = ""
                self._data = None   # never serve a stale artifact as
                                    # if it were this collection
        if not already:
            threading.Thread(target=self._run, args=args,
                             daemon=True).start()
        return self.to_api()

    def _run(self, *args) -> None:
        try:
            data = self._collect(*args)
            with self._lock:
                self._data = data
                self.status = "collected"
        except Exception as e:
            with self._lock:
                self.status = "failed"
                self._error = f"{type(e).__name__}: {e}"
            logger.error("%s collection failed: %s", self.kind,
                         self._error)

    def _extra_status(self) -> Dict[str, object]:
        """Subclass hook for additional to_api fields (caller holds no
        lock; read only immutable/atomic attributes)."""
        return {}

    def to_api(self) -> Dict[str, object]:
        with self._lock:
            doc = {
                "kind": self.kind,
                "apiVersion": self.api_version,
                "metadata": {"name": self.name},
                "status": self.status,
                "size": len(self._data) if self._data else 0,
                "errorMsg": self._error,
            }
        doc.update(self._extra_status())
        return doc

    def data(self) -> Optional[bytes]:
        with self._lock:
            return self._data
