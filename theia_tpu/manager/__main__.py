"""Run the theia-manager: REST API + job controllers over a FlowDatabase.

Usage:
  python -m theia_tpu.manager [--db flows.npz] [--port 11347]
      [--address 0.0.0.0] [--capacity-bytes N] [--ttl-seconds N]
      [--synth N_SERIES] [--tls-cert-dir DIR [--tls-cert F --tls-key F
      [--tls-ca F]]] [--auth-token-file F | --auth-token T]

--synth seeds the store with synthetic flows (demo/e2e); --db loads a
persisted FlowDatabase (and persists results back on shutdown). With
--db, a background checkpointer also snapshots the store atomically
every --checkpoint-interval seconds (default 60; 0 disables), bounding
kill -9 data loss to one interval — the durability role the
reference's ReplicatedMergeTree+ZooKeeper plays. --wal-dir (or
THEIA_WAL_DIR) additionally journals every acknowledged insert to a
write-ahead log BEFORE it is acknowledged, tightening the loss bound
from the checkpoint interval to the WAL sync policy (THEIA_WAL_SYNC,
default interval:1 — see store/wal.py); on startup the snapshot is
loaded and the log replayed above its stamp. TTL can also come
from the THEIA_TTL_SECONDS env var (the deployment manifest sets it;
flag wins).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def _persist_on_shutdown(db, db_path, checkpointer, log) -> bool:
    """Graceful-shutdown drain tail, in the only safe order: the WAL
    is fsynced FIRST (acknowledged rows are durable even if the final
    save fails), then the checkpointer is stopped, then the final
    snapshot is written and the now-covered WAL segments collected.
    A checkpointer whose writer thread failed to stop (wedged write)
    makes the final save unsafe — a racing late os.replace could
    clobber the newer file with the older one; both writes are atomic
    so nothing tears, but we skip the final save and say so (the
    synced WAL carries the tail). Returns True when a final snapshot
    was written."""
    sync = getattr(db, "wal_sync", None)
    if callable(sync):
        try:
            sync()
        except Exception as e:
            log.error("final WAL fsync failed: %s", e)
    stopped = checkpointer.stop() if checkpointer else True
    wrote = False
    try:
        if db_path:
            if not stopped:
                log.error(
                    "checkpoint thread wedged; SKIPPING the final "
                    "save (it could race the in-flight write) — the "
                    "synced WAL covers rows since the last completed "
                    "checkpoint")
            else:
                db.save(db_path)
                wrote = True
                # GC only up to the PREVIOUS snapshot's stamp (now in
                # <path>.prev): collecting up to the final stamp would
                # orphan the fallback snapshot if the file we just
                # wrote is later found corrupt.
                prev_stamp = getattr(checkpointer, "_gc_stamp", None)
                gc = getattr(db, "wal_gc", None)
                if prev_stamp is not None and callable(gc):
                    gc(prev_stamp)
    finally:
        # the WAL must close (final fsync) even if the save failed —
        # it is then the only durable copy of the tail
        close = getattr(db, "close_wal", None)
        if callable(close):
            close()
    return wrote


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="theia_tpu.manager")
    p.add_argument("--config", default=None,
                   help="YAML config file (reference "
                        "cmd/theia-manager/options.go): apiServer."
                        "{apiPort,selfSignedCert,tlsCertDir}; flags win")
    p.add_argument("-v", "--verbosity", type=int, default=0,
                   help="log verbosity (klog-style)")
    p.add_argument("--db", default=None, help="FlowDatabase .npz path")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--address", default="127.0.0.1",
                   help="bind address (0.0.0.0 inside a pod)")
    p.add_argument("--capacity-bytes", type=int, default=8 << 30)
    p.add_argument("--ttl-seconds", type=int, default=None,
                   help="flow TTL; default THEIA_TTL_SECONDS env or off")
    p.add_argument("--checkpoint-interval", type=float, default=60.0,
                   help="seconds between background snapshots of --db "
                        "(0 = only save on clean shutdown)")
    p.add_argument("--wal-dir", default=None,
                   help="write-ahead log directory (env THEIA_WAL_DIR; "
                        "unset = snapshot-only durability): inserts "
                        "are journaled before acknowledgement, so "
                        "kill -9 loss is bounded by THEIA_WAL_SYNC "
                        "instead of the checkpoint interval")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--dispatch", default="thread",
                   choices=["thread", "subprocess"],
                   help="job execution: in-process worker threads, or "
                        "one `python -m theia_tpu.runner` child per "
                        "job (process isolation — a crashing kernel "
                        "fails the JOB, not the manager; the "
                        "reference's Spark driver/executor boundary)")
    p.add_argument("--synth", type=int, default=0,
                   help="seed the store with N synthetic series")
    p.add_argument("--shards", type=int, default=1,
                   help="flow store shards (the reference's ClickHouse "
                        "`shards` Helm value; >1 uses the Distributed-"
                        "table equivalent)")
    p.add_argument("--ingest-shards", type=int, default=None,
                   help="detector shards on the ingest path (default: "
                        "THEIA_INGEST_SHARDS env, else min(8, cores)); "
                        "concurrent producer streams score "
                        "concurrently, one lock per shard")
    p.add_argument("--replicas", type=int, default=1,
                   help="live copies of the logical store (the "
                        "reference's `replicas` Helm value / "
                        "ReplicatedMergeTree role): writes fan to all, "
                        "reads fail over; composes with --shards")
    p.add_argument("--tls-cert-dir", default=None,
                   help="enable TLS; certs generated/loaded here")
    p.add_argument("--tls-cert", default=None)
    p.add_argument("--tls-key", default=None)
    p.add_argument("--tls-ca", default=None,
                   help="issuing CA bundle to publish for provided certs")
    p.add_argument("--auth-token", default=None,
                   help="require this API bearer token on mutating/"
                        "ingest/bundle endpoints (env THEIA_AUTH_TOKEN)")
    p.add_argument("--auth-token-file", default=None,
                   help="require the bearer token stored here; a fresh "
                        "random token is generated into the file if "
                        "absent (mode 0600)")
    p.add_argument("--peers", default=None,
                   help="cluster peer list (env THEIA_CLUSTER_PEERS): "
                        "'id=http://host:port,...' identical on every "
                        "node; enables the multi-node tier "
                        "(docs/cluster.md)")
    p.add_argument("--node-id", default=None,
                   help="this node's id in --peers (env "
                        "THEIA_CLUSTER_SELF; default: the first peer)")
    p.add_argument("--role", default=None,
                   choices=["leader", "follower", "peer"],
                   help="cluster role (env THEIA_CLUSTER_ROLE, default "
                        "peer): leader ships its WAL to the others "
                        "(quorum acks via THEIA_REPL_ACKS); follower "
                        "applies it and redirects ingest; peer joins "
                        "the ingest-routing mesh")
    p.add_argument("--repl-acks", default=None,
                   choices=["leader", "quorum", "all"],
                   help="replication ack policy (env THEIA_REPL_ACKS, "
                        "default quorum): how many copies must hold a "
                        "batch before it is acknowledged")
    p.add_argument("--reconcile-dir", default=None,
                   help="reconcile CR YAML documents in this directory "
                        "into jobs (the CRD control-plane seam; status "
                        "written back as <name>.status.yaml)")
    args = p.parse_args(argv)

    # Honor an explicit JAX_PLATFORMS before any backend initializes:
    # deployment sitecustomize hooks may pin the platform
    # programmatically, which silently overrides the env var — an
    # operator pinning the manager to cpu would otherwise claim (and
    # on kill, wedge) the accelerator tunnel. Same dance as bench.py.
    plats = os.environ.get("JAX_PLATFORMS", "").strip()
    if plats:
        import jax
        jax.config.update("jax_platforms", plats)

    from ..store import FlowDatabase, ShardedFlowDatabase
    from ..utils import get_logger, set_verbosity
    from .api import API_PORT, TheiaManagerServer

    set_verbosity(args.verbosity)
    log = get_logger("theia-manager")

    if args.config:
        import yaml
        with open(args.config) as f:
            conf = yaml.safe_load(f) or {}
        api_conf = conf.get("apiServer") or {}
        if args.port is None and "apiPort" in api_conf:
            args.port = int(api_conf["apiPort"])
        # TLS is on whenever the config carries TLS settings;
        # selfSignedCert=false means "use operator-provided certs from
        # the cert dir", not "plaintext" (reference options.go) — so
        # key presence, not truthiness, decides.
        if args.tls_cert_dir is None and (
                "selfSignedCert" in api_conf or "tlsCertDir" in api_conf):
            args.tls_cert_dir = str(
                api_conf.get("tlsCertDir", "/var/run/theia/tls"))
        if args.auth_token_file is None and "authTokenFile" in api_conf:
            args.auth_token_file = str(api_conf["authTokenFile"])
        log.v(1).info("loaded config from %s", args.config)

    if args.auth_token is None:
        args.auth_token = os.environ.get("THEIA_AUTH_TOKEN") or None

    from ..utils import env_int
    ttl = args.ttl_seconds
    if ttl is None:
        ttl = env_int("THEIA_TTL_SECONDS", 0) or None

    # Storage engine (THEIA_STORE_ENGINE=parts|flat, default flat):
    # the parts engine seals ingest into compressed column parts and
    # needs a directory for its cold tier + manifest — default
    # `<db path>.parts` beside the snapshot, THEIA_STORE_COLD_DIR
    # overrides, in-memory-only (pruning/compression, no tiering or
    # manifest recovery) when neither exists.
    from ..store import default_store_engine
    store_engine = default_store_engine()
    parts_dir = None
    if store_engine == "parts":
        parts_dir = (os.environ.get("THEIA_STORE_COLD_DIR")
                     or (args.db + ".parts" if args.db else None))
        print(f"store engine: parts"
              + (f" (part dir {parts_dir})" if parts_dir else
                 " (in-memory, no part directory)"),
              file=sys.stderr)

    if args.replicas > 1:
        import itertools

        from ..store import ReplicatedFlowDatabase
        _replica_seq = itertools.count()

        def _factory():
            idx = next(_replica_seq)
            rdir = (os.path.join(parts_dir, f"replica-{idx:03d}")
                    if parts_dir else None)
            if args.shards > 1:
                return ShardedFlowDatabase(n_shards=args.shards,
                                           ttl_seconds=ttl,
                                           parts_dir=rdir)
            return FlowDatabase(ttl_seconds=ttl, parts_dir=rdir)

        # Loads go through the loader even when the primary file is
        # missing: read_snapshot falls back to <path>.prev (the crash
        # window between prev-rotation and publish), and raises
        # FileNotFoundError only when NEITHER exists — an
        # os.path.exists() pre-check would silently start empty in
        # that window.
        if args.db:
            try:
                db = ReplicatedFlowDatabase.load(
                    args.db, replicas=args.replicas, factory=_factory)
            except FileNotFoundError:
                # the failed load consumed replica indices — restart
                # numbering so part dirs stay replica-000..N across
                # runs (a drifting numbering would strand old files)
                _replica_seq = itertools.count()
                db = ReplicatedFlowDatabase(replicas=args.replicas,
                                            factory=_factory)
        else:
            db = ReplicatedFlowDatabase(replicas=args.replicas,
                                        factory=_factory)
    elif args.shards > 1:
        if args.db:
            try:
                db = ShardedFlowDatabase.load(args.db,
                                              n_shards=args.shards,
                                              ttl_seconds=ttl,
                                              parts_dir=parts_dir)
            except FileNotFoundError:
                db = ShardedFlowDatabase(n_shards=args.shards,
                                         ttl_seconds=ttl,
                                         parts_dir=parts_dir)
        else:
            db = ShardedFlowDatabase(n_shards=args.shards,
                                     ttl_seconds=ttl,
                                     parts_dir=parts_dir)
    elif args.db:
        try:
            db = FlowDatabase.load(args.db, ttl_seconds=ttl,
                                   parts_dir=parts_dir)
        except FileNotFoundError:
            db = FlowDatabase(ttl_seconds=ttl, parts_dir=parts_dir)
    else:
        db = FlowDatabase(ttl_seconds=ttl, parts_dir=parts_dir)
    wal_dir = args.wal_dir or os.environ.get("THEIA_WAL_DIR") or None
    if wal_dir:
        # Attach BEFORE synth seeding / serving: recovery replays the
        # log above the snapshot stamp, then every insert is journaled
        # pre-acknowledgement.
        wal_stats = db.attach_wal(wal_dir)
        print(f"WAL at {wal_dir}: recovered "
              f"{wal_stats['recoveredRows']} rows in "
              f"{wal_stats['recoveredRecords']} records "
              f"({wal_stats['droppedRecords']} dropped)",
              file=sys.stderr)

    if args.synth:
        import contextlib

        from ..data.synth import SynthConfig, generate_flows
        # Demo seed rows are NOT journaled: a journaled seed would be
        # replayed at the next startup and then seeded again — one
        # extra seed per restart. (They still reach snapshots; demo
        # data does not need kill -9 durability.)
        suspended = getattr(db, "wal_suspended", None)
        with (suspended() if callable(suspended)
              else contextlib.nullcontext()):
            db.insert_flows(generate_flows(SynthConfig(
                n_series=args.synth, points_per_series=30,
                anomaly_fraction=0.1)))

    server = TheiaManagerServer(
        db, port=args.port if args.port is not None else API_PORT,
        workers=args.workers, capacity_bytes=args.capacity_bytes,
        address=args.address, dispatch=args.dispatch,
        tls_cert_dir=args.tls_cert_dir, tls_cert=args.tls_cert,
        tls_key=args.tls_key, tls_ca=args.tls_ca,
        auth_token=args.auth_token,
        auth_token_file=args.auth_token_file,
        ingest_shards=args.ingest_shards,
        cluster_peers=args.peers, cluster_self=args.node_id,
        cluster_role=args.role, cluster_acks=args.repl_acks)
    if server.cluster is not None:
        print(f"cluster node {server.cluster.cmap.self_id} "
              f"role={server.cluster.role} "
              f"peers={','.join(server.cluster.cmap.order)}",
              file=sys.stderr)
    if server.auth_token:
        print("API authentication enabled (bearer token)",
              file=sys.stderr)
    if server.ca_cert_path:
        print(f"CA certificate published at {server.ca_cert_path}",
              file=sys.stderr)
    print(f"theia-manager listening on {args.address}:{server.port}",
          file=sys.stderr)

    def stop(*_):
        # Only unblock serve_forever here; shutdown() would deadlock on
        # this thread (it IS the serve_forever thread) and the ordered
        # teardown below must finish before the db is persisted.
        threading.Thread(target=server.httpd.shutdown,
                         daemon=True).start()

    checkpointer = None
    if args.db and args.checkpoint_interval > 0:
        from ..store import Checkpointer
        # The store matches the on-disk file iff it was just loaded
        # from it and not re-seeded — then the first tick can skip.
        pristine = os.path.exists(args.db) and not args.synth
        checkpointer = Checkpointer(db, args.db,
                                    interval=args.checkpoint_interval,
                                    assume_current=pristine)
        checkpointer.start()
        print(f"checkpointing {args.db} every "
              f"{args.checkpoint_interval:g}s", file=sys.stderr)

    reconciler = None
    if args.reconcile_dir:
        from .reconciler import DeclarativeReconciler
        reconciler = DeclarativeReconciler(server.controller,
                                           args.reconcile_dir)
        reconciler.start()
        print(f"reconciling CRs in {args.reconcile_dir}",
              file=sys.stderr)

    signal.signal(signal.SIGINT, stop)
    signal.signal(signal.SIGTERM, stop)
    server.serve_forever()
    # Ordered drain: the HTTP server is already closed (no NEW ingest
    # or job submissions), so: finish reconciliation, drain in-flight
    # jobs, then shut the server stack down — which now WAITS for the
    # ingest insert pool (queued store-insert legs were acknowledged
    # work; dropping them on SIGTERM violated the durability
    # contract) — and only then fsync the WAL and take the final
    # checkpoint.
    if reconciler:
        reconciler.stop()
    server.controller.wait_all(timeout=60)
    server.shutdown()
    _persist_on_shutdown(db, args.db, checkpointer, log)


if __name__ == "__main__":
    main()
