"""Run the theia-manager: REST API + job controllers over a FlowDatabase.

Usage:
  python -m theia_tpu.manager [--db flows.npz] [--port 11347]
      [--capacity-bytes N] [--synth N_SERIES]

--synth seeds the store with synthetic flows (demo/e2e); --db loads a
persisted FlowDatabase (and persists results back on shutdown).
"""

from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="theia_tpu.manager")
    p.add_argument("--db", default=None, help="FlowDatabase .npz path")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--capacity-bytes", type=int, default=8 << 30)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--synth", type=int, default=0,
                   help="seed the store with N synthetic series")
    args = p.parse_args(argv)

    from ..store import FlowDatabase
    from .api import API_PORT, TheiaManagerServer

    if args.db:
        try:
            db = FlowDatabase.load(args.db)
        except FileNotFoundError:
            db = FlowDatabase()
    else:
        db = FlowDatabase()
    if args.synth:
        from ..data.synth import SynthConfig, generate_flows
        db.insert_flows(generate_flows(SynthConfig(
            n_series=args.synth, points_per_series=30,
            anomaly_fraction=0.1)))

    server = TheiaManagerServer(
        db, port=args.port if args.port is not None else API_PORT,
        workers=args.workers, capacity_bytes=args.capacity_bytes)
    print(f"theia-manager listening on :{server.port}", file=sys.stderr)

    def stop(*_):
        # shutdown() must not run on the thread executing
        # serve_forever() (BaseServer.shutdown would deadlock); hand it
        # to a helper thread and let serve_forever return below.
        import threading
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGINT, stop)
    signal.signal(signal.SIGTERM, stop)
    server.serve_forever()
    if args.db:
        db.save(args.db)


if __name__ == "__main__":
    main()
