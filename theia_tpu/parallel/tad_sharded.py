"""Sharded TAD scoring: shard_map over the (series × time) mesh.

This is the multi-chip version of theia_tpu.ops scoring (SURVEY §2.7:
Spark's executor data-parallelism → shard_map over the series axis; the
per-task whole-series processing → a sequence-parallel associative scan
over the time axis). One jitted step computes, fully sharded:

  * EWMA via local `associative_scan` + cross-shard composition of the
    per-shard affine summaries (all_gather over the "time" axis — the
    classic parallel-scan block decomposition),
  * masked sample stddev via psum over the "time" axis,
  * the anomaly mask, and a global anomaly count via psum over both axes
    (the collective the reference's driver-side `count()` implies).

The outputs come back with the same [S, T] sharding as the inputs, so a
caller can keep them device-resident for the result-row gather.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.ewma import DEFAULT_ALPHA
from .mesh import SERIES_AXIS, TIME_AXIS, Mesh, shard_map


def _local_scan(a, b):
    def combine(lhs, rhs):
        a1, b1 = lhs
        a2, b2 = rhs
        return a1 * a2, a2 * b1 + b2
    return jax.lax.associative_scan(combine, (a, b), axis=-1)


def _ewma_timeshard(x: jnp.ndarray, alpha: float,
                    n_time_shards: int) -> jnp.ndarray:
    """EWMA along a time-sharded axis: local scan + shard composition.

    Each time shard holds a contiguous [S_loc, T_loc] block. The affine
    summary (A_tot, B_tot) of every earlier shard is composed (in shard
    order) into an incoming state, then applied to the local cumulative
    scan: e = A_cum · e_in + B_cum.
    """
    a = jnp.full_like(x, 1.0 - alpha)
    b = alpha * x
    a_cum, b_cum = _local_scan(a, b)

    if n_time_shards == 1:
        return b_cum  # e_in = 0

    a_tot = a_cum[:, -1]
    b_tot = b_cum[:, -1]
    a_all = jax.lax.all_gather(a_tot, TIME_AXIS)  # [n_shards, S_loc]
    b_all = jax.lax.all_gather(b_tot, TIME_AXIS)
    my = jax.lax.axis_index(TIME_AXIS)

    e_in = jnp.zeros_like(a_tot)
    for j in range(n_time_shards):  # static, tiny (mesh axis size)
        take = j < my
        e_in = jnp.where(take, a_all[j] * e_in + b_all[j], e_in)
    return a_cum * e_in[:, None] + b_cum


def _sharded_step(x, mask, alpha: float, n_time_shards: int):
    xz = jnp.where(mask, x, 0.0)
    e = _ewma_timeshard(xz, alpha, n_time_shards)

    # Masked stddev_samp with cross-time-shard reductions.
    cnt = jax.lax.psum(jnp.sum(mask.astype(x.dtype), axis=-1), TIME_AXIS)
    total = jax.lax.psum(jnp.sum(xz, axis=-1), TIME_AXIS)
    mean = total / jnp.maximum(cnt, 1.0)
    ss = jax.lax.psum(
        jnp.sum(jnp.where(mask, (x - mean[:, None]) ** 2, 0.0), axis=-1),
        TIME_AXIS)
    var = ss / jnp.maximum(cnt - 1.0, 1.0)
    std = jnp.where(cnt >= 2, jnp.sqrt(var), jnp.nan)

    anomaly = (jnp.abs(xz - e) > std[:, None]) & mask
    count = jax.lax.psum(jnp.sum(anomaly.astype(jnp.int32)),
                         (SERIES_AXIS, TIME_AXIS))
    return e, std, anomaly, count


def make_sharded_ewma(mesh: Mesh, alpha: float = DEFAULT_ALPHA):
    """Build the jitted sharded scoring step for a mesh.

    Returns fn(x [S,T], mask [S,T]) → (ewma, stddev [S], anomaly, count)
    with S divisible by the series-axis size and T by the time-axis size.
    """
    n_time = mesh.shape[TIME_AXIS]
    step = functools.partial(_sharded_step, alpha=alpha,
                             n_time_shards=n_time)
    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS, TIME_AXIS)),
        out_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS),
                   P(SERIES_AXIS, TIME_AXIS), P()),
        check_vma=False)
    return jax.jit(mapped)


def shard_arrays(mesh: Mesh, x, mask) -> Tuple[jax.Array, jax.Array]:
    """device_put host arrays with the step's input sharding."""
    spec = NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))
    return jax.device_put(x, spec), jax.device_put(mask, spec)


def make_series_sharded(mesh: Mesh, kernel):
    """Data parallelism over the series axis for any scoring kernel
    with the (x [S,T], mask [S,T]) → (calc, std [S], anomaly) shape.

    Per-series work is independent (SURVEY §2.7 row 2: Spark's
    per-series task parallelism → series sharding), so the sharded
    step is the single-device kernel applied to each chip's series
    slab — no collectives, and per-series outputs are BIT-IDENTICAL
    to the single-device kernel (same computation graph per series).
    The time axis of the mesh (if >1) replicates.
    """
    mapped = shard_map(
        kernel, mesh=mesh,
        in_specs=(P(SERIES_AXIS, None), P(SERIES_AXIS, None)),
        out_specs=(P(SERIES_AXIS, None), P(SERIES_AXIS),
                   P(SERIES_AXIS, None)),
        check_vma=False)
    return jax.jit(mapped)


def make_sharded_arima(mesh: Mesh, refit_every: int = 1):
    """Sharded ARIMA scoring (series data parallelism; every
    (series, prefix) fit is independent — the walk-forward scan stays
    local to each shard)."""
    from ..ops.arima import arima_scores

    def step(x, mask):
        return arima_scores(x, mask, refit_every=refit_every)

    return make_series_sharded(mesh, step)


def make_sharded_dbscan(mesh: Mesh, eps: float, min_samples: int):
    """Sharded per-series DBSCAN noise scoring over the series axis.

    Each series' [T, T] distance test is independent, so series shards
    run the single-device formulation locally (the Pallas kernel on
    real TPU shards, the fused XLA formulation elsewhere — same
    auto-selection as `ops.dbscan.dbscan_scores`).
    """
    from ..ops.dbscan import dbscan_scores

    def step(x, mask):
        return dbscan_scores(x, mask, eps=eps, min_samples=min_samples)

    return make_series_sharded(mesh, step)


def make_sharded_points_dbscan(mesh: Mesh, eps: float,
                               min_samples: int = 4):
    """Sharded spatial DBSCAN over [N, F] point embeddings.

    The tiled two-pass of `ops.dbscan.dbscan_points_noise` shards over
    tile rows (mesh axis `rows`): each chip evaluates its row block
    against the full point set (one all_gather of the points), derives
    complete neighbor counts → local core flags, then a second
    all_gather shares the core flags for the reachability pass — the
    collective structure SURVEY §2.7 maps DBSCAN's region query onto.

    Returns fn(points [N, F] f32, valid [N] bool) → noise [N] bool,
    N divisible by the rows-axis size.
    """
    from .mesh import ROWS_AXIS

    eps2 = eps * eps

    def step(pts_loc, valid_loc):
        pts_all = jax.lax.all_gather(pts_loc, ROWS_AXIS)
        pts_all = pts_all.reshape(-1, pts_loc.shape[1])
        valid_all = jax.lax.all_gather(valid_loc, ROWS_AXIS).reshape(-1)
        t2 = (pts_loc * pts_loc).sum(-1)
        x2 = (pts_all * pts_all).sum(-1)
        d2 = t2[:, None] + x2[None, :] - 2.0 * jnp.matmul(
            pts_loc, pts_all.T, precision=jax.lax.Precision.HIGHEST)
        within = (d2 <= eps2) & valid_all[None, :] & valid_loc[:, None]
        counts = within.sum(-1)
        core_loc = (counts >= min_samples) & valid_loc
        core_all = jax.lax.all_gather(core_loc, ROWS_AXIS).reshape(-1)
        reach = (within & core_all[None, :]).any(-1)
        return valid_loc & ~core_loc & ~reach

    from jax.sharding import PartitionSpec as P2
    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(P2(ROWS_AXIS, None), P2(ROWS_AXIS)),
        out_specs=P2(ROWS_AXIS),
        check_vma=False)
    return jax.jit(mapped)
