"""Sharded TAD scoring: shard_map over the (series × time) mesh.

This is the multi-chip version of theia_tpu.ops scoring (SURVEY §2.7:
Spark's executor data-parallelism → shard_map over the series axis; the
per-task whole-series processing → a sequence-parallel associative scan
over the time axis). One jitted step computes, fully sharded:

  * EWMA via local `associative_scan` + cross-shard composition of the
    per-shard affine summaries (all_gather over the "time" axis — the
    classic parallel-scan block decomposition),
  * masked sample stddev via psum over the "time" axis,
  * the anomaly mask, and a global anomaly count via psum over both axes
    (the collective the reference's driver-side `count()` implies).

The outputs come back with the same [S, T] sharding as the inputs, so a
caller can keep them device-resident for the result-row gather.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.ewma import DEFAULT_ALPHA
from .mesh import SERIES_AXIS, TIME_AXIS, Mesh


def _local_scan(a, b):
    def combine(lhs, rhs):
        a1, b1 = lhs
        a2, b2 = rhs
        return a1 * a2, a2 * b1 + b2
    return jax.lax.associative_scan(combine, (a, b), axis=-1)


def _ewma_timeshard(x: jnp.ndarray, alpha: float,
                    n_time_shards: int) -> jnp.ndarray:
    """EWMA along a time-sharded axis: local scan + shard composition.

    Each time shard holds a contiguous [S_loc, T_loc] block. The affine
    summary (A_tot, B_tot) of every earlier shard is composed (in shard
    order) into an incoming state, then applied to the local cumulative
    scan: e = A_cum · e_in + B_cum.
    """
    a = jnp.full_like(x, 1.0 - alpha)
    b = alpha * x
    a_cum, b_cum = _local_scan(a, b)

    if n_time_shards == 1:
        return b_cum  # e_in = 0

    a_tot = a_cum[:, -1]
    b_tot = b_cum[:, -1]
    a_all = jax.lax.all_gather(a_tot, TIME_AXIS)  # [n_shards, S_loc]
    b_all = jax.lax.all_gather(b_tot, TIME_AXIS)
    my = jax.lax.axis_index(TIME_AXIS)

    e_in = jnp.zeros_like(a_tot)
    for j in range(n_time_shards):  # static, tiny (mesh axis size)
        take = j < my
        e_in = jnp.where(take, a_all[j] * e_in + b_all[j], e_in)
    return a_cum * e_in[:, None] + b_cum


def _sharded_step(x, mask, alpha: float, n_time_shards: int):
    xz = jnp.where(mask, x, 0.0)
    e = _ewma_timeshard(xz, alpha, n_time_shards)

    # Masked stddev_samp with cross-time-shard reductions.
    cnt = jax.lax.psum(jnp.sum(mask.astype(x.dtype), axis=-1), TIME_AXIS)
    total = jax.lax.psum(jnp.sum(xz, axis=-1), TIME_AXIS)
    mean = total / jnp.maximum(cnt, 1.0)
    ss = jax.lax.psum(
        jnp.sum(jnp.where(mask, (x - mean[:, None]) ** 2, 0.0), axis=-1),
        TIME_AXIS)
    var = ss / jnp.maximum(cnt - 1.0, 1.0)
    std = jnp.where(cnt >= 2, jnp.sqrt(var), jnp.nan)

    anomaly = (jnp.abs(xz - e) > std[:, None]) & mask
    count = jax.lax.psum(jnp.sum(anomaly.astype(jnp.int32)),
                         (SERIES_AXIS, TIME_AXIS))
    return e, std, anomaly, count


def make_sharded_ewma(mesh: Mesh, alpha: float = DEFAULT_ALPHA):
    """Build the jitted sharded scoring step for a mesh.

    Returns fn(x [S,T], mask [S,T]) → (ewma, stddev [S], anomaly, count)
    with S divisible by the series-axis size and T by the time-axis size.
    """
    n_time = mesh.shape[TIME_AXIS]
    step = functools.partial(_sharded_step, alpha=alpha,
                             n_time_shards=n_time)
    mapped = jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS, TIME_AXIS)),
        out_specs=(P(SERIES_AXIS, TIME_AXIS), P(SERIES_AXIS),
                   P(SERIES_AXIS, TIME_AXIS), P()),
        check_vma=False)
    return jax.jit(mapped)


def shard_arrays(mesh: Mesh, x, mask) -> Tuple[jax.Array, jax.Array]:
    """device_put host arrays with the step's input sharding."""
    spec = NamedSharding(mesh, P(SERIES_AXIS, TIME_AXIS))
    return jax.device_put(x, spec), jax.device_put(mask, spec)
