"""Device meshes for the analytics jobs.

The reference scales by adding Spark executor pods (SURVEY §2.7;
pkg/controller/networkpolicyrecommendation/controller.go:573-675 copies
executorInstances into the SparkApplication spec). The TPU-native
equivalent is a `jax.sharding.Mesh` over the chips of a slice:

  * axis "series" — data parallelism over connections (the Spark
    executor axis): each chip scores an independent slab of series.
  * axis "time"   — sequence parallelism over long series (no reference
    equivalent; the reference materializes unbounded collect_list rows
    per task, SURVEY §5 long-context note): the EWMA recurrence is
    associative, so it scans locally per shard and composes shard
    summaries across the ICI ring.

Collectives ride ICI within a host and DCN across hosts; XLA inserts
them from the shard_map specs in tad_sharded.py.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

SERIES_AXIS = "series"
TIME_AXIS = "time"
ROWS_AXIS = "rows"


def shard_map(f, *, mesh: Mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None):
    """`jax.shard_map` across jax versions.

    Newer jax exposes it at top level with a `check_vma` flag; older
    releases only have `jax.experimental.shard_map.shard_map`, where
    the same flag is spelled `check_rep`. Every shard_map call in the
    tree routes through here so kernels run on whichever jax the host
    ships."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return legacy(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, **kwargs)


def make_mesh(n_devices: Optional[int] = None,
              time_shards: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """A (series × time) mesh over `n_devices` (default: all visible).

    time_shards must divide the device count; time_shards=1 degenerates
    to pure series data parallelism.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if n % time_shards != 0:
        raise ValueError(
            f"time_shards {time_shards} must divide device count {n}")
    grid = np.asarray(devs).reshape(n // time_shards, time_shards)
    return Mesh(grid, (SERIES_AXIS, TIME_AXIS))


def make_rows_mesh(n_devices: Optional[int] = None,
                   devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D `rows` mesh — data parallelism over flow-record blocks
    (the NPR job's distinct/support-count shuffle axis)."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (ROWS_AXIS,))


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int,
                    fill=0) -> Tuple[np.ndarray, int]:
    """Pad `axis` up to a multiple; returns (padded, original size)."""
    size = arr.shape[axis]
    target = -(-size // multiple) * multiple if size else multiple
    if target == size:
        return arr, size
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - size)
    return np.pad(arr, pad, constant_values=fill), size
