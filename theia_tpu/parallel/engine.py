"""Job-level mesh selection: route production jobs onto every visible
chip.

The reference scales its production jobs by raising
`executorInstances` on the SparkApplication spec
(pkg/controller/networkpolicyrecommendation/controller.go:573-675);
nothing in the job itself changes. The TPU-native equivalent is this
module: `job_mesh()` inspects the visible devices once and hands the
analytics jobs a `jax.sharding.Mesh` to score over — `run_tad` /
`run_npr` call it by default, so the same manager-API job that runs
single-device on one chip runs sharded on a slice with no spec change.

Env switches:
  THEIA_MESH=off    — force single-device even on a multi-chip host
  THEIA_MESH=auto   — (default) all visible devices when >1
  THEIA_MESH=<N>    — first N visible devices
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Tuple

import jax

from .mesh import Mesh, make_mesh
from ..analysis.lockdep import named_lock

_lock = named_lock("parallel.engine")
_cache: Dict[str, Optional[Mesh]] = {}
# Jitted shard_map builders are cached per (mesh, kernel, params): the
# builders close over the mesh and re-running them would re-trace.
_fn_cache: Dict[Tuple, Callable] = {}


def job_mesh() -> Optional[Mesh]:
    """The mesh production jobs should score over, or None for the
    plain single-device path. Resolved once per THEIA_MESH value."""
    setting = os.environ.get("THEIA_MESH", "auto").strip().lower()
    with _lock:
        if setting in _cache:
            return _cache[setting]
    if setting in ("off", "0", "none", "false"):
        mesh = None
    else:
        n = len(jax.devices())
        if setting not in ("auto", ""):
            try:
                n = min(n, max(1, int(setting)))
            except ValueError:
                raise ValueError(
                    f"invalid THEIA_MESH={setting!r}: expected 'off', "
                    f"'auto', or a device count N") from None
        mesh = make_mesh(n) if n > 1 else None
    with _lock:
        _cache[setting] = mesh
    return mesh


def cached_kernel(key: Tuple, build: Callable[[], Callable]) -> Callable:
    """Memoize a jitted shard_map kernel under a hashable key."""
    with _lock:
        fn = _fn_cache.get(key)
    if fn is None:
        fn = build()
        with _lock:
            _fn_cache[key] = fn
    return fn


def reset_cache() -> None:
    """Test hook: drop memoized meshes/kernels (e.g. after changing
    THEIA_MESH or the visible device set)."""
    with _lock:
        _cache.clear()
        _fn_cache.clear()
