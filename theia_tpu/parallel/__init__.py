"""Device meshes and sharded scoring."""

from .engine import cached_kernel, job_mesh, reset_cache
from .mesh import (
    ROWS_AXIS,
    SERIES_AXIS,
    TIME_AXIS,
    make_mesh,
    make_rows_mesh,
    pad_to_multiple,
)
from .tad_sharded import (
    make_sharded_arima,
    make_sharded_dbscan,
    make_sharded_ewma,
    make_sharded_points_dbscan,
    shard_arrays,
)

__all__ = [
    "ROWS_AXIS", "SERIES_AXIS", "TIME_AXIS", "make_mesh",
    "make_rows_mesh", "pad_to_multiple", "cached_kernel", "job_mesh",
    "reset_cache", "make_sharded_arima", "make_sharded_dbscan",
    "make_sharded_ewma", "make_sharded_points_dbscan", "shard_arrays",
]
