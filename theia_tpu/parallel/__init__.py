"""Device meshes and sharded scoring."""

from .mesh import (
    ROWS_AXIS,
    SERIES_AXIS,
    TIME_AXIS,
    make_mesh,
    make_rows_mesh,
    pad_to_multiple,
)
from .tad_sharded import make_sharded_ewma, shard_arrays

__all__ = [
    "ROWS_AXIS", "SERIES_AXIS", "TIME_AXIS", "make_mesh",
    "make_rows_mesh", "pad_to_multiple",
    "make_sharded_ewma", "shard_arrays",
]
