"""`theia` — the command line interface.

Re-provides the reference's cobra CLI (pkg/theia/commands/): the same
command tree, flag names and output shapes, talking to the manager REST
API. Where the reference port-forwards into the cluster
(pkg/theia/portforwarder), this CLI takes --manager-addr (default
http://127.0.0.1:11347).

  theia policy-recommendation  run|status|retrieve|list|delete   (alias pr)
  theia throughput-anomaly-detection ...                        (alias tad)
  theia clickhouse status [--diskInfo --tableInfo --insertRate
                           --stackTraces]
  theia supportbundle
  theia version

`run --wait` polls job status every 5 s like the reference
(pkg/theia/commands/config/config.go StatusCheckPollInterval; loop at
policy_recommendation_run.go:223-259).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import ssl
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from typing import Dict, Optional

from ..utils import (
    AGG_FLOWS,
    POLICY_TYPES,
    TAD_ALGOS,
    get_manager_addr,
    validate_k8s_quantity,
)
from ..utils.backoff import capped_backoff

DEFAULT_ADDR = "http://127.0.0.1:11347"
GROUP = "/apis/intelligence.theia.antrea.io/v1alpha1"
POLL_INTERVAL = 5.0
POLL_TIMEOUT = 3600.0

NPR_RESOURCE = "networkpolicyrecommendations"
TAD_RESOURCE = "throughputanomalydetectors"
DD_RESOURCE = "trafficdropdetections"
FPM_RESOURCE = "flowpatternminings"
SAD_RESOURCE = "spatialanomalydetections"

TIME_FORMAT = "%Y-%m-%d %H:%M:%S"


class APIError(SystemExit):
    pass


class APIConnectionError(APIError):
    """Transient transport-level failure (connection refused/reset,
    timeout, HTTP 503): worth retrying inside a poll loop, fatal
    everywhere a human is waiting on one answer."""


class APIRetryAfterError(APIConnectionError):
    """HTTP 429: the manager is over CAPACITY (not down) and said when
    to come back. `retry_after` carries the server's hint; poll loops
    treat it like any transient failure, the ingest client honors the
    hint precisely."""

    retry_after = 1.0


_CA_CERT = ""
_TOKEN = ""


def _url_context():
    if not _CA_CERT:
        return None
    return ssl.create_default_context(cafile=_CA_CERT)


def _auth_headers() -> Dict[str, str]:
    """Bearer token for an authenticated manager (the reference CLI
    reads a ServiceAccount token Secret and sends it the same way,
    pkg/theia/commands/utils.go:122-144)."""
    return {"Authorization": f"Bearer {_TOKEN}"} if _TOKEN else {}


def _urlopen(addr: str, req: urllib.request.Request,
             timeout: float = 30) -> bytes:
    """Open a manager request, classifying failures into
    APIError/APIConnectionError (the one place the taxonomy lives)."""
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=_url_context()) as resp:
            return resp.read()
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        detail = body
        try:
            detail = json.loads(body).get("message", body)
        except Exception:
            pass
        if e.code == 429:
            from ..ingest.client import parse_retry_after
            err = APIRetryAfterError(
                f"error: manager over capacity (429): {detail}")
            err.retry_after = parse_retry_after(e.headers, body)
            raise err
        cls = APIConnectionError if e.code == 503 else APIError
        raise cls(f"error: {e.code} from manager: {detail}")
    except urllib.error.URLError as e:
        # covers socket.timeout too (URLError wraps it) — but a TLS
        # failure (bad CA, hostname mismatch) is permanent: retrying
        # it for the whole poll window would bury the real reason
        cls = (APIError if isinstance(e.reason, ssl.SSLError)
               else APIConnectionError)
        raise cls(
            f"error: cannot reach theia-manager at {addr}: {e.reason}")


def _request(addr: str, method: str, path: str,
             body: Optional[Dict] = None) -> Dict:
    req = urllib.request.Request(
        addr + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json",
                 **_auth_headers()})
    raw = _urlopen(addr, req)
    return json.loads(raw) if raw else {}


def _poll_request(addr: str, path: str, deadline: float) -> Dict:
    """GET with transient retry: a poll loop that has been waiting on
    a job for minutes must not die to a single connection blip or a
    503 (manager restarting, replicas resyncing). Capped exponential
    backoff, bounded by the caller's overall poll deadline."""
    attempt = 0
    while True:
        try:
            return _request(addr, "GET", path)
        except APIConnectionError as e:
            attempt += 1
            backoff = capped_backoff(1.0, 30.0, attempt)
            if time.time() + backoff > deadline:
                raise
            print(f"warning: {e}; retrying in {backoff:.0f}s",
                  file=sys.stderr)
            time.sleep(backoff)


def _parse_time_arg(value: str, flag: str) -> Optional[int]:
    if not value:
        return None
    try:
        dt = datetime.datetime.strptime(value, TIME_FORMAT)
    except ValueError:
        raise SystemExit(
            f"error: {flag} should be in '{TIME_FORMAT}' format")
    return int(dt.replace(tzinfo=datetime.timezone.utc).timestamp())


def _wait_for_job(addr: str, resource: str, name: str) -> Dict:
    deadline = time.time() + POLL_TIMEOUT
    while time.time() < deadline:
        doc = _poll_request(addr, f"{GROUP}/{resource}/{name}",
                            deadline)
        state = (doc.get("status") or {}).get("state", "")
        if state in ("COMPLETED", "FAILED"):
            return doc
        time.sleep(POLL_INTERVAL)
    raise APIError(f"error: timed out waiting for job {name}")


def _print_job_table(items) -> None:
    fmt = "{:<44} {:<10} {:<10} {}"
    print(fmt.format("NAME", "STATE", "PROGRESS", "ERROR"))
    for doc in items:
        st = doc.get("status") or {}
        progress = f"{st.get('completedStages', 0)}/" \
                   f"{st.get('totalStages', 0)}"
        print(fmt.format(doc["metadata"]["name"], st.get("state", ""),
                         progress, st.get("errorMsg", "")))


def _sizing_body(args) -> Dict[str, object]:
    """Resource-sizing spec fields (reference CRD spec,
    pkg/apis/crd/v1alpha1/types.go)."""
    return {
        "executorInstances": args.executor_instances,
        "driverCoreRequest": args.driver_core_request,
        "driverMemory": args.driver_memory,
        "executorCoreRequest": args.executor_core_request,
        "executorMemory": args.executor_memory,
    }


# -- policy-recommendation ----------------------------------------------

def npr_run(args) -> None:
    name = "pr-" + str(uuid.uuid4())
    body = {
        "metadata": {"name": name},
        "jobType": args.type,
        "limit": args.limit,
        "policyType": args.policy_type,
        "startInterval": _parse_time_arg(args.start_time, "start-time"),
        "endInterval": _parse_time_arg(args.end_time, "end-time"),
        "nsAllowList": json.loads(args.ns_allow_list)
        if args.ns_allow_list else None,
        "excludeLabels": args.exclude_labels,
        "toServices": args.to_services,
        **_sizing_body(args),
    }
    body = {k: v for k, v in body.items() if v is not None}
    _request(args.manager_addr, "POST", f"{GROUP}/{NPR_RESOURCE}", body)
    print(f"Successfully created policy recommendation job with name "
          f"{name}")
    if args.wait:
        doc = _wait_for_job(args.manager_addr, NPR_RESOURCE, name)
        st = doc.get("status") or {}
        if st.get("state") == "FAILED":
            raise APIError(
                f"error: job failed: {st.get('errorMsg', '')}")
        outcome = st.get("recommendationOutcome", "")
        if args.file:
            with open(args.file, "w") as f:
                f.write(outcome)
            print(f"Recommendation written to {args.file}")
        else:
            print(outcome)


def npr_status(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{NPR_RESOURCE}/{args.name}")
    st = doc.get("status") or {}
    print(f"Status of this policy recommendation job is "
          f"{st.get('state', '')}")
    if st.get("state") == "RUNNING":
        print(f"Completed stages: {st.get('completedStages', 0)}/"
              f"{st.get('totalStages', 0)}")


def npr_retrieve(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{NPR_RESOURCE}/{args.name}")
    outcome = (doc.get("status") or {}).get("recommendationOutcome", "")
    if args.file:
        with open(args.file, "w") as f:
            f.write(outcome)
        print(f"Recommendation written to {args.file}")
    else:
        print(outcome)


def npr_list(args) -> None:
    doc = _request(args.manager_addr, "GET", f"{GROUP}/{NPR_RESOURCE}")
    _print_job_table(doc.get("items", []))


def npr_delete(args) -> None:
    _request(args.manager_addr, "DELETE",
             f"{GROUP}/{NPR_RESOURCE}/{args.name}")
    print(f"Successfully deleted policy recommendation job with name "
          f"{args.name}")


# -- throughput-anomaly-detection ---------------------------------------

def tad_run(args) -> None:
    name = "tad-" + str(uuid.uuid4())
    body = {
        "metadata": {"name": name},
        "jobType": args.algo,
        "startInterval": _parse_time_arg(args.start_time, "start-time"),
        "endInterval": _parse_time_arg(args.end_time, "end-time"),
        "nsIgnoreList": json.loads(args.ns_ignore_list)
        if args.ns_ignore_list else None,
        "aggFlow": args.agg_flow or None,
        "podLabel": args.pod_label or None,
        "podName": args.pod_name or None,
        "podNameSpace": args.pod_namespace or None,
        "externalIp": args.external_ip or None,
        "servicePortName": args.svc_port_name or None,
        "clusterUUID": args.cluster_uuid or None,
        # refitEvery=1 is the server default; 0 (auto) must survive the
        # None-filter below, so only drop the default.
        "refitEvery": args.refit_every
        if args.refit_every != 1 else None,
        **_sizing_body(args),
    }
    body = {k: v for k, v in body.items() if v is not None}
    _request(args.manager_addr, "POST", f"{GROUP}/{TAD_RESOURCE}", body)
    print(f"Successfully started Throughput Anomaly Detection job with "
          f"name: {name}")
    if args.wait:
        doc = _wait_for_job(args.manager_addr, TAD_RESOURCE, name)
        st = doc.get("status") or {}
        if st.get("state") == "FAILED":
            raise APIError(
                f"error: job failed: {st.get('errorMsg', '')}")
        _print_tad_stats(doc.get("stats", []))


def _print_table(rows, cols) -> None:
    """Column-aligned table; cells are newline-stripped and truncated."""
    def cell(r, c):
        return str(r.get(c, "")).replace("\n", " ")[:80]

    widths = {c: max(len(c), *(len(cell(r, c)) for r in rows))
              for c in cols}
    print("  ".join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(cell(r, c).ljust(widths[c]) for c in cols))


def _print_tad_stats(stats) -> None:
    if not stats:
        print("No anomalies found")
        return
    _print_table(stats, [
        "id", "sourceIP", "sourceTransportPort", "destinationIP",
        "destinationTransportPort", "flowEndSeconds", "throughput",
        "aggType", "algoType", "anomaly"])


def tad_status(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{TAD_RESOURCE}/{args.name}")
    st = doc.get("status") or {}
    print(f"Status of this anomaly detection job is "
          f"{st.get('state', '')}")
    if st.get("state") == "RUNNING":
        print(f"Completed stages: {st.get('completedStages', 0)}/"
              f"{st.get('totalStages', 0)}")


def tad_retrieve(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{TAD_RESOURCE}/{args.name}")
    stats = doc.get("stats", [])
    if args.file:
        with open(args.file, "w") as f:
            json.dump(stats, f, indent=2)
        print(f"Anomalies written to {args.file}")
    else:
        _print_tad_stats(stats)


def tad_list(args) -> None:
    doc = _request(args.manager_addr, "GET", f"{GROUP}/{TAD_RESOURCE}")
    _print_job_table(doc.get("items", []))


def tad_delete(args) -> None:
    _request(args.manager_addr, "DELETE",
             f"{GROUP}/{TAD_RESOURCE}/{args.name}")
    print(f"Successfully deleted Throughput Anomaly Detection job with "
          f"name: {args.name}")


# -- drop-detection (theia-sf drop-detection equivalent) ----------------

def _print_dd_stats(stats) -> None:
    if not stats:
        print("No abnormal traffic drops found")
        return
    _print_table(stats, [
        "id", "endpoint", "direction", "avgDrop", "stdevDrop",
        "anomalyDropDate", "anomalyDropNumber"])


def dd_run(args) -> None:
    name = "dd-" + str(uuid.uuid4())
    body = {
        "metadata": {"name": name},
        "jobType": args.type,
        "startInterval": _parse_time_arg(args.start_time, "start-time"),
        "endInterval": _parse_time_arg(args.end_time, "end-time"),
        "clusterUUID": args.cluster_uuid or None,
    }
    body = {k: v for k, v in body.items() if v is not None}
    _request(args.manager_addr, "POST", f"{GROUP}/{DD_RESOURCE}", body)
    print(f"Successfully started traffic drop detection job with "
          f"name: {name}")
    if args.wait:
        doc = _wait_for_job(args.manager_addr, DD_RESOURCE, name)
        st = doc.get("status") or {}
        if st.get("state") == "FAILED":
            raise APIError(
                f"error: job failed: {st.get('errorMsg', '')}")
        _print_dd_stats(doc.get("stats", []))


def dd_status(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{DD_RESOURCE}/{args.name}")
    st = doc.get("status") or {}
    print(f"Status of this traffic drop detection job is "
          f"{st.get('state', '')}")
    if st.get("state") == "RUNNING":
        print(f"Completed stages: {st.get('completedStages', 0)}/"
              f"{st.get('totalStages', 0)}")


def dd_retrieve(args) -> None:
    doc = _request(args.manager_addr, "GET",
                   f"{GROUP}/{DD_RESOURCE}/{args.name}")
    stats = doc.get("stats", [])
    if args.file:
        with open(args.file, "w") as f:
            json.dump(stats, f, indent=2)
        print(f"Drop anomalies written to {args.file}")
    else:
        _print_dd_stats(stats)


def dd_list(args) -> None:
    doc = _request(args.manager_addr, "GET", f"{GROUP}/{DD_RESOURCE}")
    _print_job_table(doc.get("items", []))


def dd_delete(args) -> None:
    _request(args.manager_addr, "DELETE",
             f"{GROUP}/{DD_RESOURCE}/{args.name}")
    print(f"Successfully deleted traffic drop detection job with "
          f"name: {args.name}")


# -- pattern mining (north-star FP-Growth config; no reference CLI) -----

def _print_fpm_stats(stats) -> None:
    if not stats:
        print("No frequent patterns found")
        return
    _print_table(stats, ["id", "items", "itemsetLength", "support"])


def fpm_run(args) -> None:
    name = "fpm-" + str(uuid.uuid4())
    body = {
        "metadata": {"name": name},
        "minSupport": args.min_support or None,
        "maxLen": args.max_len,
        "columns": [c.strip() for c in args.columns.split(",")
                    if c.strip()] or None,
        "startInterval": _parse_time_arg(args.start_time, "start-time"),
        "endInterval": _parse_time_arg(args.end_time, "end-time"),
    }
    body = {k: v for k, v in body.items() if v is not None}
    _request(args.manager_addr, "POST", f"{GROUP}/{FPM_RESOURCE}", body)
    print(f"Successfully started flow pattern mining job with "
          f"name: {name}")
    if args.wait:
        doc = _wait_for_job(args.manager_addr, FPM_RESOURCE, name)
        st = doc.get("status") or {}
        if st.get("state") == "FAILED":
            raise APIError(
                f"error: job failed: {st.get('errorMsg', '')}")
        _print_fpm_stats(doc.get("stats", []))


def _simple_actions(resource, label, print_stats):
    """status/retrieve/list/delete handlers for a job resource."""

    def status(args):
        doc = _request(args.manager_addr, "GET",
                       f"{GROUP}/{resource}/{args.name}")
        st = doc.get("status") or {}
        print(f"Status of this {label} job is {st.get('state', '')}")
        if st.get("state") == "RUNNING":
            print(f"Completed stages: {st.get('completedStages', 0)}/"
                  f"{st.get('totalStages', 0)}")

    def retrieve(args):
        doc = _request(args.manager_addr, "GET",
                       f"{GROUP}/{resource}/{args.name}")
        stats = doc.get("stats", [])
        if args.file:
            with open(args.file, "w") as f:
                json.dump(stats, f, indent=2)
            print(f"Results written to {args.file}")
        else:
            print_stats(stats)

    def list_(args):
        doc = _request(args.manager_addr, "GET", f"{GROUP}/{resource}")
        _print_job_table(doc.get("items", []))

    def delete(args):
        _request(args.manager_addr, "DELETE",
                 f"{GROUP}/{resource}/{args.name}")
        print(f"Successfully deleted {label} job with name: "
              f"{args.name}")

    return status, retrieve, list_, delete


fpm_status, fpm_retrieve, fpm_list, fpm_delete = _simple_actions(
    FPM_RESOURCE, "flow pattern mining", _print_fpm_stats)


# -- spatial anomaly detection (north-star spatial-DBSCAN config) -------

def _print_sad_stats(stats) -> None:
    if not stats:
        print("No spatial anomalies found")
        return
    _print_table(stats, ["id", "sourceIP", "destinationIP",
                         "destinationTransportPort", "octetDeltaCount"])


def sad_run(args) -> None:
    name = "sad-" + str(uuid.uuid4())
    body = {
        "metadata": {"name": name},
        "eps": args.eps,
        "minSamples": args.min_samples,
        "startInterval": _parse_time_arg(args.start_time, "start-time"),
        "endInterval": _parse_time_arg(args.end_time, "end-time"),
    }
    body = {k: v for k, v in body.items() if v is not None}
    _request(args.manager_addr, "POST", f"{GROUP}/{SAD_RESOURCE}", body)
    print(f"Successfully started spatial anomaly detection job with "
          f"name: {name}")
    if args.wait:
        doc = _wait_for_job(args.manager_addr, SAD_RESOURCE, name)
        st = doc.get("status") or {}
        if st.get("state") == "FAILED":
            raise APIError(
                f"error: job failed: {st.get('errorMsg', '')}")
        _print_sad_stats(doc.get("stats", []))


sad_status, sad_retrieve, sad_list, sad_delete = _simple_actions(
    SAD_RESOURCE, "spatial anomaly detection", _print_sad_stats)


# -- clickhouse / supportbundle / version -------------------------------

def clickhouse_status(args) -> None:
    components = [c for c, on in (
        ("diskInfo", args.diskInfo), ("tableInfo", args.tableInfo),
        ("insertRate", args.insertRate),
        ("stackTraces", args.stackTraces),
        ("deviceInfo", args.deviceInfo)) if on]
    if not components:
        components = ["diskInfo", "tableInfo", "insertRate"]
    for comp in components:
        doc = _request(args.manager_addr, "GET",
                       "/apis/stats.theia.antrea.io/v1alpha1/"
                       f"clickhouse/{comp}")
        key = {"diskInfo": "diskInfos", "tableInfo": "tableInfos",
               "insertRate": "insertRates",
               "stackTraces": "stackTraces",
               "deviceInfo": "deviceInfos"}[comp]
        rows = doc.get(key, [])
        print(f"== {comp} ==")
        if rows:
            _print_table(rows, list(rows[0].keys()))


def _poll_and_download(addr: str, path: str, wait_s: float,
                       out_path: str, label: str) -> int:
    """Shared async-collect client: poll status until collected (or
    failed), then stream .../theia-manager/download to `out_path`.
    Returns the byte count."""
    deadline = time.time() + wait_s
    while time.time() < deadline:
        doc = _poll_request(addr, path, deadline)
        status = doc.get("status")
        if status == "collected":
            break
        if status == "failed":
            raise APIError(
                f"error: {label} failed: {doc.get('errorMsg', '')}")
        time.sleep(0.5)
    else:
        raise APIError(f"error: {label} collection timed out")
    req = urllib.request.Request(
        addr + path + "/theia-manager/download",
        headers=_auth_headers())
    with urllib.request.urlopen(req, timeout=60,
                                context=_url_context()) as resp:
        data = resp.read()
    with open(out_path, "wb") as f:
        f.write(data)
    return len(data)


def supportbundle(args) -> None:
    path = "/apis/system.theia.antrea.io/v1alpha1/supportbundles"
    _request(args.manager_addr, "POST", path)
    out = args.file or "theia-supportbundle.tar.gz"
    n = _poll_and_download(args.manager_addr, path, 60, out,
                           "support bundle")
    print(f"Support bundle written to {out} ({n} bytes)")


def profile(args) -> None:
    """Capture an XLA profiler trace from the manager (no reference
    equivalent — its closest surface is the ClickHouse stack-trace
    dump)."""
    path = "/apis/system.theia.antrea.io/v1alpha1/profiles"
    _request(args.manager_addr, "POST", path,
             {"durationSeconds": args.duration})
    out = args.file or "theia-profile.tar.gz"
    n = _poll_and_download(args.manager_addr, path,
                           args.duration + 120, out, "profile")
    print(f"XLA profile written to {out} ({n} bytes); "
          f"view with TensorBoard/xprof")


# -- ingest (exactly-once producer; the Flow-Aggregator-over-the-wire
# -- role, driven from a shell) -----------------------------------------

def ingest_cmd(args) -> None:
    """Produce synthetic flow batches to POST /ingest through the
    exactly-once client (stream+seq stamping, Retry-After honored
    with jittered capped backoff) — the operator's load/drill tool
    and the smallest correct producer to crib from."""
    from ..data.synth import SynthConfig, generate_flows
    from ..ingest import make_block_encoder
    from ..ingest.client import IngestClient, IngestError

    # TBLK by default; THEIA_INGEST_FORMAT=tfb2 keeps the legacy
    # dictionary-delta stream for drills against old managers
    enc = make_block_encoder()
    batch = generate_flows(SynthConfig(
        n_series=args.series, points_per_series=args.points,
        anomaly_fraction=args.anomaly_fraction, seed=args.seed),
        dicts=enc.dicts)
    client = IngestClient(args.manager_addr,
                          stream=args.stream or None,
                          token=_TOKEN, ca_cert=_CA_CERT or None)
    alerts = 0
    t0 = time.time()
    try:
        for i in range(args.batches):
            out = client.send(enc.encode(batch))
            alerts += int(out.get("alerts", 0))
            if args.interval > 0 and i + 1 < args.batches:
                time.sleep(args.interval)
    except IngestError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(1)
    dt = max(time.time() - t0, 1e-9)
    s = client.summary()
    print(f"stream {s['stream']}: acked {s['rowsAcked']} rows in "
          f"{s['batchesAcked']} batches ({s['rowsAcked'] / dt:,.0f} "
          f"rows/s), {alerts} alerts, {s['duplicates']} duplicate "
          f"acks, {s['rejected429']} over-capacity retries, "
          f"{s['transientRetries']} transient retries")


# -- query (filtered aggregations over the store — the vectorized
# -- read path of the parts engine) -------------------------------------

_WHERE_OPS = (">=", "<=", "!=", ">", "<", "=")


def _parse_where(clause: str) -> dict:
    """One --where clause → filter doc: `col>=443`, `sourceIP=10.0.0.9`,
    `destinationIP in 10.0.0.1,10.0.0.2`."""
    if " in " in clause:
        column, _, raw = clause.partition(" in ")
        return {"column": column.strip(), "op": "in",
                "value": [v for v in raw.strip().split(",") if v]}
    for op in _WHERE_OPS:
        if op in clause:
            column, _, value = clause.partition(op)
            return {"column": column.strip(), "op": op,
                    "value": value.strip()}
    raise SystemExit(
        f"error: --where {clause!r} has no operator "
        f"(expected one of {_WHERE_OPS} or ' in ')")


def query_cmd(args) -> None:
    """Run one filtered aggregation through POST /query and print the
    result rows (the CLI face of the vectorized query engine).

    Cluster-aware: --manager-addr takes a comma-separated endpoint
    list, and the request rides the IngestClient failover/redirect
    machinery — connection refusal / 5xx rotate endpoints, 307/308
    re-target at the node named in Location — so the command works
    against ANY node of a cluster, not just the one it was pointed
    at."""
    doc: dict = {}
    if getattr(args, "table", ""):
        doc["table"] = args.table
    if args.group_by:
        doc["groupBy"] = args.group_by
    if args.agg:
        doc["aggregates"] = args.agg
    if args.where:
        doc["filters"] = [_parse_where(w) for w in args.where]
    for name in ("start", "end", "k"):
        v = getattr(args, name)
        if v is not None:
            doc[name] = v
    if args.time_column:
        doc["timeColumn"] = args.time_column
    if args.order_by:
        doc["orderBy"] = args.order_by
    if args.explain:
        doc["explain"] = True
    from ..ingest.client import IngestClient, IngestError
    addrs = [a.strip() for a in args.manager_addr.split(",")
             if a.strip()]
    try:
        client = IngestClient(addrs, stream="cli-query",
                              token=_TOKEN, ca_cert=_CA_CERT or None,
                              max_attempts=4, backoff_base=0.2,
                              backoff_cap=2.0)
        out = client.request_json("POST", "/query", doc)
    except IngestError as e:
        raise APIError(f"error: {e}")
    if args.json:
        print(json.dumps(out, indent=2))
        return
    rows = out.get("rows", [])
    if rows:
        _print_table(rows, list(rows[0].keys()))
    else:
        print("no groups matched")
    footer = (f"-- {out.get('groupCount', 0)} groups, "
              f"{out.get('rowsScanned', 0):,} rows scanned, "
              f"{out.get('partsScanned', 0)} parts scanned / "
              f"{out.get('partsPruned', 0)} pruned, "
              f"{out.get('engine')} engine, cache {out.get('cache')}, "
              f"{out.get('tookMs', 0)} ms")
    peers = out.get("peers")
    if peers:
        footer += (f"; cluster {peers.get('queried', 0)} peers "
                   f"queried / {peers.get('pruned', 0)} pruned, "
                   f"{out.get('bytesShipped', 0):,} partial bytes")
    if out.get("traceId"):
        footer += f"; trace {out['traceId']}"
    print(footer)
    if args.explain and out.get("profile"):
        _print_explain(out["profile"])
    if out.get("partial"):
        print(f"!! PARTIAL result — peers unavailable: "
              f"{', '.join(out.get('missingPeers', []))} "
              f"(answer covers the reachable nodes only)",
              file=sys.stderr)


def _print_explain(prof: Dict) -> None:
    """Render the EXPLAIN profile: header facts, phase timings, then
    per-peer (coordinator) and per-part (local engine) tables."""
    head = [f"engine {prof.get('engine')}"]
    if prof.get("kernel"):
        head.append(f"kernel {prof['kernel']}")
    head.append(f"cache {prof.get('cache', '?')}")
    if prof.get("fingerprint"):
        head.append(f"fingerprint {prof['fingerprint']}")
    if prof.get("rowsMatched") is not None:
        head.append(f"{prof.get('rowsScanned', 0):,} rows scanned / "
                    f"{prof['rowsMatched']:,} matched")
    elif prof.get("rowsMatchedLocal") is not None:
        head.append(f"{prof.get('rowsScanned', 0):,} rows scanned "
                    f"cluster-wide / {prof['rowsMatchedLocal']:,} "
                    f"matched locally")
    print("EXPLAIN: " + ", ".join(head))
    phases = prof.get("phases") or {}
    if phases:
        print("  phases: " + ", ".join(
            f"{k} {v} ms" for k, v in phases.items()))
    peers = prof.get("peers") or []
    if peers:
        print("  peers:")
        _print_table(peers, ["peer", "status", "tookMs", "execMs",
                             "bytes", "rowsScanned", "partsScanned",
                             "partsPruned", "reason"])
    parts = prof.get("parts") or []
    if parts:
        print(f"  parts ({len(parts)}"
              + (f" shown, {prof['partsListTruncated']} more"
                 if prof.get("partsListTruncated") else "")
              + "):")
        shown = [{**p, "fate": (p.get("pruned") or "scanned")}
                 for p in parts]
        _print_table(shown, ["part", "tier", "rows", "fate"])
    if prof.get("memtableRows"):
        print(f"  memtable: {prof['memtableRows']:,} rows scanned")


# -- top (live rates from GET /metrics; no reference equivalent — the
# -- closest is watching the provisioned Grafana dashboards) ------------

def _request_text(addr: str, path: str) -> str:
    """GET returning raw text (the Prometheus exposition body)."""
    req = urllib.request.Request(addr + path, headers=_auth_headers())
    return _urlopen(addr, req).decode()


def _top_rows(sample, prev, dt):
    """One render pass: (metric, labels, rate string, value string)
    rows — counters (`*_total`) and histogram `*_count` series get a
    per-second rate against the previous sample; gauges print their
    value; `*_bucket` / `*_sum` series are elided (bucket grids don't
    read as a table)."""
    rows = []
    for (name, labels), value in sorted(sample.items()):
        if name.endswith(("_bucket", "_sum")):
            continue
        is_rate = name.endswith(("_total", "_count"))
        rate = ""
        if is_rate and prev is not None and dt > 0:
            delta = value - prev.get((name, labels), 0.0)
            rate = f"{max(delta, 0.0) / dt:,.1f}"
        label_s = ",".join(f"{k}={v}" for k, v in labels)
        value_s = (f"{value:,.0f}" if float(value).is_integer()
                   else f"{value:,.2f}")
        rows.append({"METRIC": name, "LABELS": label_s,
                     "RATE/s": rate, "VALUE": value_s})
    return rows


def trace_cmd(args) -> None:
    """Fetch one distributed trace by id (from ANY cluster node — the
    queried node fans the lookup out to its live peers and stitches
    the spans) and render the cross-node tree."""
    doc = _request(
        args.manager_addr, "GET",
        "/debug/traces?trace="
        + urllib.parse.quote(args.trace_id, safe=""))
    spans = doc.get("spans") or []
    if not spans:
        print(f"trace {args.trace_id}: no spans retained "
              f"(expired from the ring, unsampled, or "
              f"THEIA_TRACE_RING=0)")
        return
    nodes = doc.get("nodes") or []
    print(f"trace {doc.get('trace')} — {len(spans)} spans across "
          f"{len(nodes)} node(s): {', '.join(nodes)}")
    if doc.get("peersMissing"):
        print(f"!! peers unreachable (trace may be incomplete): "
              f"{', '.join(doc['peersMissing'])}", file=sys.stderr)
    if doc.get("clockNote"):
        print(f"   note: {doc['clockNote']}")
    by_id = {s.get("spanId"): s for s in spans if s.get("spanId")}
    children: Dict[str, list] = {}
    roots = []
    for s in spans:
        parent = s.get("parentSpanId")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    t0 = min(float(s.get("startTime") or 0) for s in spans)
    meta_keys = ("op", "startTime", "durationMs", "parent", "thread",
                 "traceId", "spanId", "parentSpanId", "node", "error")

    def render(s, depth):
        offset = (float(s.get("startTime") or 0) - t0) * 1000
        attrs = " ".join(f"{k}={v}" for k, v in s.items()
                         if k not in meta_keys)
        line = (f"{'  ' * depth}{'└ ' if depth else ''}{s['op']} "
                f"[{s.get('node') or 'local'}] "
                f"{s.get('durationMs', 0)} ms @+{offset:,.1f} ms")
        if s.get("error"):
            line += f" ERROR={s['error']}"
        if attrs:
            line += f"  {attrs}"
        print(line)
        kids = sorted(children.get(s.get("spanId"), []),
                      key=lambda c: float(c.get("startTime") or 0))
        for c in kids:
            render(c, depth + 1)

    for root in sorted(roots,
                       key=lambda s: float(s.get("startTime") or 0)):
        render(root, 0)


# -- cluster-wide top ----------------------------------------------------

def _cluster_top_sample(clients):
    """One scrape pass: addr → parsed exposition (None when the node
    is unreachable after the client's retry budget). Scrapes run
    CONCURRENTLY — one hung node costs one timeout, not its place in
    a serial chain, exactly when a degraded cluster is what the
    operator is trying to see."""
    from concurrent.futures import ThreadPoolExecutor

    from ..obs import prom as _prom

    def scrape(client):
        try:
            return _prom.parse(client.request_text("GET", "/metrics"))
        except Exception:   # IngestError, parse failure: node is down
            return None

    with ThreadPoolExecutor(max_workers=max(2, len(clients))) as pool:
        futs = [(addr, pool.submit(scrape, client))
                for addr, client in clients]
        return {addr: fut.result() for addr, fut in futs}


def _node_label(addr) -> str:
    """host:port — unambiguous even when peer ids are unknown (a node
    scrapes fine before its cluster tier is configured)."""
    return addr.split("://", 1)[-1]


#: rung names mirror manager/admission.py LEVEL_NAMES (kept literal
#: here so `theia top` stays import-light)
_ADMISSION_NAMES = ("ok", "sampled", "shed_detector", "reject")


def _cluster_top_rows(samples, prev, dt):
    """Per-node columns + a cluster-total row. Counters render as
    rates against the previous scrape of the SAME node."""
    def rate(sample, prior, name):
        if sample is None or prior is None or dt <= 0:
            return 0.0
        cur = sum(v for (n, _), v in sample.items() if n == name)
        old = sum(v for (n, _), v in prior.items() if n == name)
        return max(cur - old, 0.0) / dt

    def gauge(sample, name, default=0.0):
        if sample is None:
            return default
        return sum(v for (n, _), v in sample.items() if n == name)

    def skip_pct(scanned: float, skipped: float) -> str:
        total = scanned + skipped
        return f"{100.0 * skipped / total:,.0f}%" if total > 0 else "-"

    rows = []
    totals = {"rows": 0.0, "parts": 0.0, "q": 0.0,
              "gscan": 0.0, "gskip": 0.0}
    for addr, sample in samples.items():
        prior = (prev or {}).get(addr)
        if sample is None:
            rows.append({"NODE": _node_label(addr),
                         "STATUS": "DOWN", "ROWS/s": "", "REPL LAG": "",
                         "ADMISSION": "", "PARTS": "", "QUERY/s": "",
                         "GRAN SKIP": ""})
            continue
        rows_s = rate(sample, prior, "theia_ingest_rows_total")
        q_s = (rate(sample, prior, "theia_query_cache_hits_total")
               + rate(sample, prior, "theia_query_seconds_count")
               + rate(sample, prior, "theia_query_fanout_seconds_count"))
        lags = [v for (n, _), v in sample.items()
                if n == "theia_repl_lag_records"]
        lvl = int(gauge(sample, "theia_admission_level"))
        parts = gauge(sample, "theia_store_parts")
        # index effectiveness at a glance: lifetime share of index
        # granules the skip indexes pruned inside scanned parts
        # (theia_query_granules_*_total, PR 12)
        gscan = gauge(sample, "theia_query_granules_scanned_total")
        gskip = gauge(sample, "theia_query_granules_skipped_total")
        totals["rows"] += rows_s
        totals["parts"] += parts
        totals["q"] += q_s
        totals["gscan"] += gscan
        totals["gskip"] += gskip
        rows.append({
            "NODE": _node_label(addr),
            "STATUS": "up",
            "ROWS/s": f"{rows_s:,.0f}",
            "REPL LAG": f"{max(lags):,.0f}" if lags else "-",
            "ADMISSION": _ADMISSION_NAMES[
                min(max(lvl, 0), len(_ADMISSION_NAMES) - 1)],
            "PARTS": f"{parts:,.0f}",
            "QUERY/s": f"{q_s:,.1f}",
            "GRAN SKIP": skip_pct(gscan, gskip),
        })
    rows.append({
        "NODE": "TOTAL", "STATUS": "",
        "ROWS/s": f"{totals['rows']:,.0f}", "REPL LAG": "",
        "ADMISSION": "", "PARTS": f"{totals['parts']:,.0f}",
        "QUERY/s": f"{totals['q']:,.1f}",
        "GRAN SKIP": skip_pct(totals["gscan"], totals["gskip"]),
    })
    return rows


def top_cluster(args) -> None:
    """`theia top --cluster`: scrape every endpoint in the (comma-
    separated) --manager-addr list and render per-node columns plus a
    cluster-total row. Each endpoint rides its own IngestClient, so a
    flapping node retries/backs off exactly like a producer would."""
    from ..ingest.client import IngestClient
    addrs = [a.strip() for a in args.manager_addr.split(",")
             if a.strip()]
    clients = [(a, IngestClient(a, stream="cli-top", token=_TOKEN,
                                ca_cert=_CA_CERT or None,
                                timeout=5.0,
                                max_attempts=2, backoff_base=0.1,
                                backoff_cap=0.5))
               for a in addrs]
    prev = None
    prev_t = 0.0
    i = 0
    try:
        while True:
            samples = _cluster_top_sample(clients)
            now = time.time()
            dt = now - prev_t if prev is not None else 0.0
            if not args.no_clear and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            stamp = datetime.datetime.fromtimestamp(now).strftime(
                TIME_FORMAT)
            n_up = sum(1 for s in samples.values() if s is not None)
            print(f"theia top --cluster — {n_up}/{len(addrs)} nodes "
                  f"up  {stamp}")
            # per-peer heartbeat RTT averages from any live node's
            # histogram (scrape-cumulative: sum/count)
            rtts = []
            for sample in samples.values():
                if sample is None:
                    continue
                for (name, labels), v in sample.items():
                    if name == "theia_cluster_heartbeat_rtt_seconds_sum" \
                            and labels:
                        peer = dict(labels).get("peer")
                        cnt = sample.get(
                            ("theia_cluster_heartbeat_rtt_seconds_count",
                             labels), 0.0)
                        if cnt:
                            rtts.append((peer, v / cnt * 1e3))
                break   # one node's view is the cluster's link set
            if rtts:
                print("heartbeat rtt: " + ", ".join(
                    f"{p} {ms:.1f}ms" for p, ms in sorted(rtts)))
            _print_table(_cluster_top_rows(samples, prev, dt),
                         ["NODE", "STATUS", "ROWS/s", "REPL LAG",
                          "ADMISSION", "PARTS", "QUERY/s",
                          "GRAN SKIP"])
            prev, prev_t = samples, now
            i += 1
            if args.iterations and i >= args.iterations:
                return
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


# -- stored-history sparklines (theia top --history) ---------------------

_WINDOW_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400}

_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _parse_window(raw: str) -> int:
    """'6h' / '30m' / '900' → seconds."""
    s = raw.strip().lower()
    try:
        if s and s[-1] in _WINDOW_UNITS:
            return int(float(s[:-1]) * _WINDOW_UNITS[s[-1]])
        return int(s)
    except ValueError:
        raise APIError(f"error: bad --history window {raw!r} "
                       f"(expected e.g. 6h, 30m, 900)")


def _sparkline(values) -> str:
    """One row of block characters; None (empty bucket) renders as a
    space; a flat series renders at the floor."""
    finite = [v for v in values if v is not None]
    if not finite:
        return ""
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_SPARK_CHARS[0])
        else:
            out.append(_SPARK_CHARS[
                min(len(_SPARK_CHARS) - 1,
                    int((v - lo) / span * len(_SPARK_CHARS)))])
    return "".join(out)


def _history_series(rows, start: int, bucket: int, n_buckets: int):
    """Fold /query rows (metric, kind, labels, node, timeInserted +
    the four exact aggregate columns) into per-(metric, kind) bucket
    arrays of NATURAL-unit values: gauges → mean sample per bucket
    (pooled across children); cumulative kinds (counters, histogram
    sum/count) → rate/s computed PER SERIES — each labels × node
    child is its own monotone counter, whose bucket-to-bucket level
    increase is exact — then summed across the metric's series
    (differencing a max folded over unrelated children would track
    only the highest-level series and hide the rest)."""
    scale = 1e6   # METRICS_VALUE_SCALE (kept literal: import-light)
    gauge_acc: Dict[tuple, dict] = {}
    cum_acc: Dict[tuple, dict] = {}
    for r in rows:
        b = (int(r["timeInserted"]) - start) // bucket
        if b < 0:
            continue
        # the query window runs through `now` inclusive, which lands
        # past the last bucket boundary whenever window % bucket != 0
        # (and always for t == now); fold that remainder into the
        # final bucket instead of silently dropping the newest
        # samples — LAST must show the most recent stored value
        b = min(b, n_buckets - 1)
        if r["kind"] == "gauge":
            s = gauge_acc.setdefault((r["metric"], r["kind"]), {})
            cur = s.get(b)
            if cur is None:
                s[b] = {"sum": r["sum(valueSum)"],
                        "count": r["sum(valueCount)"]}
            else:
                cur["sum"] += r["sum(valueSum)"]
                cur["count"] += r["sum(valueCount)"]
        else:
            key = (r["metric"], r["kind"],
                   r.get("labels", ""), r.get("node", ""))
            s = cum_acc.setdefault(key, {})
            cur = s.get(b)
            if cur is None:
                s[b] = {"max": r["max(valueMax)"]}
            else:
                cur["max"] = max(cur["max"], r["max(valueMax)"])
    series: Dict[tuple, list] = {}
    for (metric, kind), buckets in sorted(gauge_acc.items()):
        vals: list = [None] * n_buckets
        for b, v in buckets.items():
            if v["count"]:
                vals[b] = v["sum"] / scale / v["count"]
        series[(metric, kind)] = vals
    for (metric, kind, _lab, _node), buckets in sorted(
            cum_acc.items()):
        # this one series' rate between consecutive non-empty buckets
        vals = [None] * n_buckets
        prev_level = None
        prev_b = None
        for b in sorted(buckets):
            level = buckets[b]["max"] / scale
            if prev_level is not None and b > prev_b:
                vals[b] = max(level - prev_level, 0.0) \
                    / ((b - prev_b) * bucket)
            prev_level, prev_b = level, b
        out = series.get((metric, kind))
        if out is None:
            series[(metric, kind)] = vals
        else:
            for i, v in enumerate(vals):
                if v is not None:
                    out[i] = v + (out[i] or 0.0)
    # derived mean series: where a histogram's _sum and _count rates
    # both exist, their ratio is the mean observation per bucket —
    # the "latency sparkline"
    for (metric, kind) in list(series):
        if kind != "sum" or not metric.endswith("_sum"):
            continue
        base = metric[:-4]
        cnt = series.get((base + "_count", "count"))
        if cnt is None:
            continue
        s_vals = series[(metric, kind)]
        mean = [
            (s_vals[i] / cnt[i])
            if (s_vals[i] is not None and cnt[i]) else None
            for i in range(n_buckets)]
        series[(base + " (mean)", "derived")] = mean
    return series


def top_history(args) -> None:
    """`theia top --history <window>`: render sparklines from the
    STORED `__metrics__` series instead of diffing two live scrapes —
    history survives restarts, and on a cluster the query plane
    answers for every node from any node. Windows past the rollup
    horizon read from downsampled parts transparently."""
    window = _parse_window(args.history)
    now = int(time.time())
    start = now - window
    # bucket floor = the default scrape cadence: narrower buckets
    # would alias raw 15s samples into an on/off checkerboard
    bucket = max(window // 48, 15)
    n_buckets = max(window // bucket, 1)
    filters = [{"column": "kind", "op": "ne", "value": "bucket"}]
    if getattr(args, "node", ""):
        filters.append({"column": "node", "op": "eq",
                        "value": args.node})
    doc = {"table": "__metrics__",
           "groupBy": "metric,kind,labels,node,timeInserted",
           "aggregates": ["min:valueMin", "max:valueMax",
                          "sum:valueSum", "sum:valueCount"],
           "filters": filters,
           "start": start, "end": now + 1, "k": 0,
           "cache": "0"}
    from ..ingest.client import IngestClient, IngestError
    addrs = [a.strip() for a in args.manager_addr.split(",")
             if a.strip()]
    try:
        client = IngestClient(addrs, stream="cli-top",
                              token=_TOKEN, ca_cert=_CA_CERT or None,
                              max_attempts=4, backoff_base=0.2,
                              backoff_cap=2.0)
        out = client.request_json("POST", "/query", doc)
    except IngestError as e:
        raise APIError(f"error: {e}")
    rows = out.get("rows", [])
    needle = (getattr(args, "metric", "") or "").strip()
    if needle:
        rows = [r for r in rows if needle in r["metric"]]
    series = _history_series(rows, start, bucket, n_buckets)
    stamp = datetime.datetime.fromtimestamp(now).strftime(TIME_FORMAT)
    print(f"theia top --history {args.history} — "
          f"{len(series)} series, {bucket}s buckets, "
          f"stored history through {stamp}")
    if not series:
        print("no stored series in the window (is the metrics "
              "history loop on? THEIA_METRICS_SCRAPE_INTERVAL)")
        return
    table = []
    for (metric, kind), vals in sorted(series.items()):
        finite = [v for v in vals if v is not None]
        last = finite[-1] if finite else None
        unit = "/s" if kind not in ("gauge", "derived") else ""
        table.append({
            "METRIC": metric,
            "KIND": kind,
            "SPARK": _sparkline(vals),
            "LAST": (f"{last:,.4g}{unit}"
                     if last is not None else "-"),
        })
    _print_table(table, ["METRIC", "KIND", "SPARK", "LAST"])
    if out.get("partial"):
        print(f"!! PARTIAL history — peers unavailable: "
              f"{', '.join(out.get('missingPeers', []))}",
              file=sys.stderr)


def alerts_cmd(args) -> None:
    """`theia alerts [--rules]`: the recent alert ring (detector +
    rule firings), and with --rules the declarative rule set with its
    per-(rule, node) hysteresis states."""
    doc = _request(args.manager_addr, "GET",
                   f"/alerts?limit={args.limit}")
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    if args.rules:
        rules = doc.get("rules")
        if not rules:
            print("no alert rules engine on this manager (set "
                  "THEIA_ALERT_RULES and keep "
                  "THEIA_METRICS_SCRAPE_INTERVAL > 0)")
            return
        print(f"alert rules — {len(rules.get('rules', []))} loaded "
              f"from {rules.get('path') or '(unset)'}, "
              f"{rules.get('evaluations', 0)} evaluations, "
              f"{rules.get('transitions', 0)} transitions")
        if rules.get("loadError"):
            print(f"!! load error (previous rule set still active): "
                  f"{rules['loadError']}", file=sys.stderr)
        spec_rows = [{
            "RULE": r["name"], "TYPE": r["type"],
            "METRIC": r["metric"],
            "EXPR": f"{r['agg']} {r['op']} {r['threshold']:g}",
            "WINDOWS": ",".join(str(w) for w in r["windows"]),
            "FOR": f"{r['forTicks']}/{r['clearTicks']}",
        } for r in rules.get("rules", [])]
        if spec_rows:
            _print_table(spec_rows, ["RULE", "TYPE", "METRIC",
                                     "EXPR", "WINDOWS", "FOR"])
        state_rows = [{
            "RULE": s["rule"],
            "NODE": s.get("node", ""),
            "STATE": ("FIRING" if s["state"] == "firing"
                      else s["state"]),
            "VALUE": (f"{s['value']:,.4g}"
                      if s.get("value") is not None else "-"),
            "STREAK": s.get("breachStreak", 0),
        } for s in rules.get("states", [])]
        if state_rows:
            _print_table(state_rows, ["RULE", "NODE", "STATE",
                                      "VALUE", "STREAK"])
        else:
            print("(no rule states yet — waiting for the first "
                  "evaluation ticks)")
        return
    alerts = doc.get("alerts") or []
    if not alerts:
        print("no recent alerts")
        return
    rows = [{
        "TIME": (datetime.datetime.fromtimestamp(
            a["time"]).strftime(TIME_FORMAT)
            if a.get("time") else ""),
        "KIND": a.get("kind", a.get("algo", "")),
        "DETAIL": (f"rule {a.get('rule')} {a.get('state')} "
                   f"value={a.get('value'):,.4g} vs "
                   f"{a.get('op', '>=')} {a.get('threshold')}"
                   if a.get("kind") == "rule"
                   and a.get("value") is not None
                   else str({k: v for k, v in a.items()
                             if k not in ("time", "kind")})[:100]),
        "NODE": a.get("node", ""),
    } for a in alerts]
    _print_table(rows, ["TIME", "KIND", "NODE", "DETAIL"])


def top(args) -> None:
    """Poll GET /metrics and render a live rates table (rates are
    deltas between successive scrapes)."""
    if getattr(args, "history", ""):
        top_history(args)
        return
    if getattr(args, "cluster", False):
        top_cluster(args)
        return
    from ..obs import prom as _prom
    prev = None
    prev_t = 0.0
    i = 0
    failures = 0
    try:
        while True:
            try:
                text = _request_text(args.manager_addr, "/metrics")
            except APIConnectionError as e:
                # a monitoring loop must outlive the blip it exists to
                # observe (manager restarting, replicas resyncing) —
                # same discipline as the job-poll retry
                failures += 1
                backoff = capped_backoff(
                    max(args.interval, 0.1), 30.0, failures)
                print(f"warning: {e}; retrying in {backoff:.0f}s",
                      file=sys.stderr)
                time.sleep(backoff)
                continue
            failures = 0
            now = time.time()
            sample = _prom.parse(text)
            rows = _top_rows(sample, prev,
                             now - prev_t if prev is not None else 0.0)
            if not args.no_clear and sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            stamp = datetime.datetime.fromtimestamp(now).strftime(
                TIME_FORMAT)
            print(f"theia top — {args.manager_addr}  {stamp}  "
                  f"({len(rows)} series)")
            lvl = sample.get(("theia_admission_level", ()))
            if lvl is not None:
                names = _ADMISSION_NAMES
                i_lvl = min(max(int(lvl), 0), len(names) - 1)
                pressure = sample.get(("theia_admission_pressure",
                                       ()), 0.0)
                print(f"admission: {names[i_lvl]} (rung {i_lvl}, "
                      f"pressure {pressure:.2f})")
            peer_rows = sorted(
                (labels[0][1], value)
                for (name, labels), value in sample.items()
                if name == "theia_cluster_peer_up" and labels)
            if peer_rows:
                # cluster header: per-peer liveness + replication lag
                # (the theia_repl_* gauges exist on the leader)
                def _peer_cell(peer, up):
                    lag = sample.get(
                        ("theia_repl_lag_records", (("peer", peer),)))
                    cell = f"{peer} {'up' if up else 'DOWN'}"
                    if lag is not None:
                        cell += f" lag {lag:,.0f}"
                    rtt_sum = sample.get(
                        ("theia_cluster_heartbeat_rtt_seconds_sum",
                         (("peer", peer),)))
                    rtt_n = sample.get(
                        ("theia_cluster_heartbeat_rtt_seconds_count",
                         (("peer", peer),)), 0.0)
                    if rtt_sum is not None and rtt_n:
                        cell += f" rtt {rtt_sum / rtt_n * 1e3:.1f}ms"
                    return cell
                n_up = sum(1 for _, up in peer_rows if up)
                print(f"cluster: {n_up}/{len(peer_rows)} peers up — "
                      + ", ".join(_peer_cell(p, up)
                                  for p, up in peer_rows))
            pc = sample.get(("theia_store_parts", ()))
            if pc is not None:
                # parts-engine header: part count, tier residency,
                # merge rate from scrape-to-scrape deltas
                hot = sample.get(
                    ("theia_store_part_bytes", (("tier", "hot"),)),
                    0.0)
                cold = sample.get(
                    ("theia_store_part_bytes", (("tier", "cold"),)),
                    0.0)
                dt_p = now - prev_t if prev is not None else 0.0
                dm = 0.0
                if prev is not None:
                    dm = max(sample.get(
                        ("theia_store_merges_total", ()), 0.0)
                        - prev.get(("theia_store_merges_total", ()),
                                   0.0), 0.0)
                print(f"parts engine: {pc:,.0f} parts, "
                      f"hot {hot / 1e6:,.1f} MB, "
                      f"cold {cold / 1e6:,.1f} MB, "
                      f"{dm / dt_p if dt_p > 0 else 0.0:,.2f} "
                      f"merges/s")
            rv = sample.get(("theia_rollup_views", ()))
            if rv:
                # rollup-maintenance header: active views, fold rate
                # of the insert path, cumulative tier folds — visible
                # whenever rollup maintenance is active
                dt_r = now - prev_t if prev is not None else 0.0
                dr = 0.0
                if prev is not None:
                    dr = max(sample.get(
                        ("theia_rollup_applied_rows_total", ()), 0.0)
                        - prev.get(
                            ("theia_rollup_applied_rows_total", ()),
                            0.0), 0.0)
                tier_folds = sum(
                    value for (name, _labels), value in sample.items()
                    if name == "theia_rollup_folds_total")
                print(f"rollup views: {rv:,.0f} active, "
                      f"{dr / dt_r if dt_r > 0 else 0.0:,.0f} "
                      f"rows/s applied, "
                      f"{tier_folds:,.0f} tier folds")
            qc = sample.get(("theia_query_seconds_count", ()))
            if qc is not None:
                # query-engine header: query rate, scan rate, cache
                # hit ratio — scrape-to-scrape deltas. q/s = cache
                # hits + executed queries (the seconds histogram):
                # the histogram alone misses cache hits, the cache
                # counters alone miss everything when the cache is
                # disabled — either half would read as an idle engine
                # under the other workload.
                def _qdelta(name):
                    if prev is None:
                        return 0.0
                    return max(sample.get((name, ()), 0.0)
                               - prev.get((name, ()), 0.0), 0.0)
                dt_q = now - prev_t if prev is not None else 0.0
                dscan = _qdelta("theia_query_rows_scanned_total")
                dh = _qdelta("theia_query_cache_hits_total")
                dm_q = _qdelta("theia_query_cache_misses_total")
                dq = dh + _qdelta("theia_query_seconds_count")
                hit_pct = (100.0 * dh / (dh + dm_q)
                           if (dh + dm_q) > 0 else 0.0)
                qline = (f"query engine: "
                         f"{dq / dt_q if dt_q > 0 else 0.0:,.1f} q/s, "
                         f"{dscan / dt_q if dt_q > 0 else 0.0:,.0f} "
                         f"rows/s scanned, "
                         f"cache hit {hit_pct:.0f}%")
                slow = sample.get(
                    ("theia_query_slow_queries_total", ()), 0.0)
                if slow:
                    # captured profiles live at /debug/slow_queries
                    qline += f", {slow:,.0f} slow captured"
                print(qline)
                # distributed fan-out header (routing-mesh nodes):
                # cumulative peers queried/pruned/failed — nonzero
                # only where the coordinator actually runs
                fanq = sample.get(
                    ("theia_query_peers_queried_total", ()), 0.0)
                fanp = sample.get(
                    ("theia_query_peers_pruned_total", ()), 0.0)
                fanf = sample.get(
                    ("theia_query_peers_failed_total", ()), 0.0)
                if fanq or fanp or fanf:
                    fb = sample.get(
                        ("theia_query_fanout_bytes_total", ()), 0.0)
                    print(f"query fanout: {fanq:,.0f} peers queried, "
                          f"{fanp:,.0f} pruned, {fanf:,.0f} failed, "
                          f"{fb / 1e3:,.1f} KB partials shipped")
            ld = sample.get(("theia_lockdep_locks", ()))
            if ld:
                # lockdep header: witness scope + the one number that
                # must stay zero, plus the currently worst lock by
                # cumulative wait (contention hot spot at a glance)
                inv_n = sample.get(
                    ("theia_lockdep_inversions", ()), 0.0)
                edges_n = sample.get(("theia_lockdep_edges", ()), 0.0)
                worst, worst_wait = "", 0.0
                for (name, labels), value in sample.items():
                    if name == "theia_lockdep_wait_seconds_total" \
                            and labels and value > worst_wait:
                        worst, worst_wait = labels[0][1], value
                line = (f"lockdep: {ld:,.0f} locks, "
                        f"{edges_n:,.0f} order edges, "
                        f"{inv_n:,.0f} inversions")
                if inv_n:
                    line += "  ** LATENT DEADLOCK — see theia locks"
                if worst:
                    line += (f"; top wait: {worst} "
                             f"({worst_wait:.2f}s total)")
                print(line)
            qd = sample.get(("theia_fused_queue_depth", ()))
            if qd is not None:
                # fused-engine header: pipeline backlog + step rate +
                # coalesced rows/step, from scrape-to-scrape deltas
                def _delta(name):
                    if prev is None:
                        return 0.0
                    return max(sample.get((name, ()), 0.0)
                               - prev.get((name, ()), 0.0), 0.0)
                steps = _delta("theia_fused_steps_total")
                step_rows = _delta("theia_fused_batch_rows_sum")
                dt_s = now - prev_t if prev is not None else 0.0
                print(f"fused engine: queue depth {qd:.0f}, "
                      f"{steps / dt_s if dt_s > 0 else 0.0:,.1f} "
                      f"steps/s, "
                      f"{step_rows / steps if steps > 0 else 0.0:,.0f}"
                      f" rows/step")
            hot = sample.get(("theia_state_hot_series", ()))
            if hot is not None:
                # working-set state tier header: occupancy split plus
                # promote/evict/drop rates from scrape-to-scrape
                # deltas (drops must stay 0 while the tier is on —
                # that is the tier's whole contract)
                def _sdelta(name):
                    if prev is None:
                        return 0.0
                    cur = sum(v for (n, _l), v in sample.items()
                              if n == name)
                    old = sum(v for (n, _l), v in prev.items()
                              if n == name)
                    return max(cur - old, 0.0)
                spilled = sample.get(
                    ("theia_state_spilled_series", ()), 0.0)
                dt_t = now - prev_t if prev is not None else 0.0
                ev = _sdelta("theia_state_evictions_total")
                pr = _sdelta("theia_state_promotions_total")
                drops = _sdelta("theia_detector_series_dropped_total")
                tline = (f"state tier: {hot:,.0f} hot, "
                         f"{spilled:,.0f} spilled, "
                         f"{pr / dt_t if dt_t > 0 else 0.0:,.1f} "
                         f"promotions/s, "
                         f"{ev / dt_t if dt_t > 0 else 0.0:,.1f} "
                         f"evictions/s, "
                         f"{drops / dt_t if dt_t > 0 else 0.0:,.1f} "
                         f"drops/s")
                if drops:
                    tline += "  ** SERIES DROPPED despite tier"
                print(tline)
            if rows:
                _print_table(rows, ["METRIC", "LABELS", "RATE/s",
                                    "VALUE"])
            prev, prev_t = sample, now
            i += 1
            if args.iterations and i >= args.iterations:
                return
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


def parts_cmd(args) -> None:
    """`theia parts` — the storage engine at inspection depth: the
    `theia top` parts header expanded to per-table sort-key / granule
    / index stats and a bounded per-part inventory (token-gated
    GET /debug/parts)."""
    doc = _request(args.manager_addr, "GET",
                   f"/debug/parts?limit={args.limit}")
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    if doc.get("engine") != "parts" or not doc.get("tables"):
        print("store engine: flat (no parts — set "
              "THEIA_STORE_ENGINE=parts)")
        return

    def kb(n) -> str:
        return f"{(n or 0) / 1e3:,.1f}K"

    for t in doc["tables"]:
        s = t.get("stats") or {}
        shard = f" [shard {t['shard']}]" if "shard" in t else ""
        print(f"table {t.get('table')}{shard}: "
              f"{s.get('count', 0):,} parts "
              f"({s.get('hot', 0):,} hot / {s.get('cold', 0):,} cold, "
              f"{s.get('sorted', 0):,} sorted v2), "
              f"{s.get('rows', 0):,} rows "
              f"+ {s.get('memtableRows', 0):,} memtable")
        key = ",".join(s.get("sortKey") or ()) or "(none — unsorted)"
        print(f"  sort key: {key}; granule {s.get('granuleRows', 0):,}"
              f" rows — {s.get('indexedParts', 0):,} indexed parts, "
              f"{s.get('granules', 0):,} granules, "
              f"index {kb(s.get('indexBytes'))}B resident")
        print(f"  lifetime: {s.get('sealed', 0):,} sealed, "
              f"{s.get('merges', 0):,} merges "
              f"({s.get('coldMerges', 0):,} cold), "
              f"{s.get('demoted', 0):,} demoted, "
              f"{s.get('upgraded', 0):,} upgraded v1→v2")
        entries = t.get("parts") or []
        if not entries:
            continue
        rows = [{
            "UID": e.get("uid", ""),
            "TIER": e.get("tier", ""),
            "FMT": f"v{e.get('fmt', 1)}",
            "ROWS": f"{e.get('rows', 0):,}",
            "RAM": kb(e.get("residentBytes")),
            "FILE": kb(e.get("fileBytes")),
            "GRANULES": e.get("granules", ""),
            "INDEX": (kb(e.get("indexBytes"))
                      if "indexBytes" in e else ""),
            "TIME-RANGE": "..".join(
                str(v) for v in (e.get("timeRange") or ())),
        } for e in entries]
        _print_table(rows, ["UID", "TIER", "FMT", "ROWS", "RAM",
                            "FILE", "GRANULES", "INDEX", "TIME-RANGE"])


def views_cmd(args) -> None:
    """`theia views` — the declared rollup views at inspection depth
    (token-gated GET /debug/views): definitions, tiers, per-store
    aggregate part/row counts, maintenance stats, loadError."""
    doc = _request(args.manager_addr, "GET", "/debug/views")
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    if not doc.get("enabled") or not doc.get("views"):
        print("no rollup views declared (set THEIA_ROLLUP_VIEWS "
              "and/or THEIA_ROLLUP_DEFAULTS=1)")
        if doc.get("loadError"):
            print(f"load error: {doc['loadError']}")
        return
    print(f"rollup views: {len(doc['views'])} declared across "
          f"{doc.get('stores', 1)} store(s)  — "
          f"{doc.get('rowsApplied', 0):,} rows applied, "
          f"{doc.get('aggregateRows', 0):,} aggregate rows, "
          f"{doc.get('folds', 0):,} tier folds, "
          f"{doc.get('rebuilds', 0):,} rebuilds")
    if doc.get("configPath"):
        print(f"config: {doc['configPath']}")
    if doc.get("loadError"):
        print(f"LOAD ERROR (previous set still active): "
              f"{doc['loadError']}")
    rows = []
    for v in doc["views"]:
        d = v.get("definition") or {}
        tiers = d.get("tiers") or []
        tier_s = "→".join(
            [f"{d.get('bucketSeconds', '?')}s"]
            + [f"{t['resolutionSeconds']}s" for t in tiers])
        aggs = d.get("aggregates") or []
        agg_s = ",".join(
            (a["op"] if not a.get("column")
             else f"{a['op']}({a['column']})") for a in aggs)
        rows.append({
            "VIEW": v.get("name", ""),
            "GROUP-BY": len(d.get("groupBy") or ()),
            "AGGREGATES": agg_s[:40],
            "TIERS": tier_s,
            "FILTERS": len(d.get("filters") or ()),
            "ROWS": f"{v.get('rows', 0):,}",
            "PARTS": v.get("parts", 0),
            "RES-SEEN": ",".join(
                str(r) for r in (v.get("partResolutions") or ())),
        })
    _print_table(rows, ["VIEW", "GROUP-BY", "AGGREGATES", "TIERS",
                        "FILTERS", "ROWS", "PARTS", "RES-SEEN"])


def locks_cmd(args) -> None:
    """`theia locks` — the runtime lockdep witness at inspection
    depth (token-gated GET /debug/locks): per-lock acquire/contention
    counts, wait and hold p95s, the observed acquisition-order edges,
    and any witnessed inversions."""
    doc = _request(args.manager_addr, "GET", "/debug/locks")
    if args.json:
        print(json.dumps(doc, indent=2))
        return
    if not doc.get("enabled"):
        print("lockdep witness: off (start the manager with "
              "THEIA_LOCKDEP=1 to arm it)")
        return
    stats = doc.get("stats") or {}
    edges = doc.get("orderEdges") or []
    inv = doc.get("inversions") or []
    print(f"lockdep witness: {len(doc.get('locks') or ())} lock "
          f"classes, {len(edges)} order edges, "
          f"{len(inv)} inversion(s)")
    if inv:
        for i in inv:
            print(f"  INVERSION: {' -> '.join(i.get('cycle', ()))} "
                  f"(new edge at {i.get('site', '?')}, thread "
                  f"{i.get('thread', '?')})")
    rows = []
    order = sorted(stats.items(),
                   key=lambda kv: -kv[1].get("waitTotalSeconds", 0.0))
    for name, s in order[:args.limit]:
        rows.append({
            "LOCK": name,
            "ACQUIRES": f"{s.get('acquires', 0):,}",
            "CONTENDED": f"{s.get('contended', 0):,}",
            "WAIT-P95": f"{s.get('waitP95Seconds', 0.0) * 1e3:.3f}ms",
            "WAIT-MAX": f"{s.get('waitMaxSeconds', 0.0) * 1e3:.2f}ms",
            "HOLD-P95": f"{s.get('holdP95Seconds', 0.0) * 1e3:.3f}ms",
            "HOLD-TOT": f"{s.get('holdTotalSeconds', 0.0):.2f}s",
        })
    if rows:
        _print_table(rows, ["LOCK", "ACQUIRES", "CONTENDED",
                            "WAIT-P95", "WAIT-MAX", "HOLD-P95",
                            "HOLD-TOT"])
    if args.edges and edges:
        erows = [{"HELD": e.get("held", ""),
                  "THEN-ACQUIRED": e.get("acquired", ""),
                  "FIRST-SEEN": e.get("site", "")}
                 for e in edges]
        _print_table(erows, ["HELD", "THEN-ACQUIRED", "FIRST-SEEN"])
    nesting = doc.get("selfNesting") or {}
    if nesting:
        print("same-class nesting (instance order unproven — see "
              "docs/analysis.md): "
              + ", ".join(f"{k} x{v}"
                          for k, v in sorted(nesting.items())))


def version(args) -> None:
    from .. import __version__
    print(f"theia version: {__version__}")
    try:
        doc = _request(args.manager_addr, "GET", "/version")
        print(f"theia-manager version: {doc.get('version', 'unknown')}")
    except SystemExit:
        print("theia-manager version: unavailable")


# -- parser --------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="theia", description="theia-tpu command line tool")
    p.add_argument("--manager-addr", default=get_manager_addr(DEFAULT_ADDR),
                   help="theia-manager API address (env "
                        "THEIA_MANAGER_ADDR overrides the default); "
                        "`theia query` accepts a comma-separated "
                        "endpoint list and fails over across it")
    p.add_argument("--ca-cert", default="",
                   help="CA certificate for a TLS manager (the "
                        "published theia-ca.crt)")
    p.add_argument("--token", default=os.environ.get("THEIA_TOKEN", ""),
                   help="API bearer token (env THEIA_TOKEN); required "
                        "for mutating calls on an authenticated "
                        "manager")
    p.add_argument("--token-file", default="",
                   help="read the API bearer token from this file "
                        "(e.g. the manager's --auth-token-file)")
    p.add_argument("--use-port-forward", action="store_true",
                   help="tunnel to the in-cluster manager Service via "
                        "`kubectl port-forward` (reference CLI "
                        "default; needs a kubeconfig)")
    p.add_argument("--namespace", default="flow-visibility",
                   help="manager namespace for --use-port-forward")
    p.add_argument("--service", default="theia-manager",
                   help="manager Service for --use-port-forward")
    p.add_argument("--kubectl", default="kubectl",
                   help="kubectl binary for --use-port-forward")
    p.add_argument("-v", "--verbosity", type=int, default=0,
                   help="log verbosity (klog-style)")
    sub = p.add_subparsers(dest="command", required=True)

    def quantity(flag):
        def parse(value):
            try:
                return validate_k8s_quantity(value, flag)
            except ValueError as e:
                raise argparse.ArgumentTypeError(str(e))
        return parse

    def sizing_flags(run):
        """Job resource sizing (reference CRD spec fields validated at
        pkg/controller/networkpolicyrecommendation/controller.go:586-608;
        defaults from pkg/theia/commands/policy_recommendation_run.go:
        324-352 — 1 executor, 200m CPU, 512M memory)."""
        run.add_argument("--executor-instances",
                         dest="executor_instances", type=int, default=1)
        run.add_argument("--driver-core-request",
                         dest="driver_core_request", default="200m",
                         type=quantity("driver-core-request"))
        run.add_argument("--driver-memory", dest="driver_memory",
                         default="512M", type=quantity("driver-memory"))
        run.add_argument("--executor-core-request",
                         dest="executor_core_request", default="200m",
                         type=quantity("executor-core-request"))
        run.add_argument("--executor-memory", dest="executor_memory",
                         default="512M",
                         type=quantity("executor-memory"))

    def add_job_commands(group, run_fn, status_fn, retrieve_fn, list_fn,
                         delete_fn, run_flags):
        gsub = group.add_subparsers(dest="action", required=True)
        run = gsub.add_parser("run")
        run_flags(run)
        run.add_argument("--wait", action="store_true")
        run.add_argument("-f", "--file", default="")
        run.set_defaults(fn=run_fn)
        for action, fn, needs_name in (
                ("status", status_fn, True), ("retrieve", retrieve_fn,
                                              True),
                ("list", list_fn, False), ("delete", delete_fn, True)):
            sp = gsub.add_parser(action)
            if needs_name:
                sp.add_argument("name")
            if action == "retrieve":
                sp.add_argument("-f", "--file", default="")
            sp.set_defaults(fn=fn)

    npr = sub.add_parser("policy-recommendation", aliases=["pr"])

    def npr_flags(run):
        run.add_argument("-t", "--type", default="initial",
                         choices=["initial", "subsequent"])
        run.add_argument("-l", "--limit", type=int, default=0)
        run.add_argument("-p", "--policy-type", dest="policy_type",
                         default="anp-deny-applied",
                         choices=list(POLICY_TYPES))
        run.add_argument("-s", "--start-time", dest="start_time",
                         default="")
        run.add_argument("-e", "--end-time", dest="end_time", default="")
        run.add_argument("-n", "--ns-allow-list", dest="ns_allow_list",
                         default="")
        run.add_argument("--exclude-labels", dest="exclude_labels",
                         type=lambda v: v != "false", default=True)
        run.add_argument("--to-services", dest="to_services",
                         type=lambda v: v != "false", default=True)
        sizing_flags(run)

    add_job_commands(npr, npr_run, npr_status, npr_retrieve, npr_list,
                     npr_delete, npr_flags)

    tad = sub.add_parser("throughput-anomaly-detection", aliases=["tad"])

    def tad_flags(run):
        run.add_argument("-a", "--algo", required=True,
                         choices=list(TAD_ALGOS))
        run.add_argument("-s", "--start-time", dest="start_time",
                         default="")
        run.add_argument("-e", "--end-time", dest="end_time", default="")
        run.add_argument("-n", "--ns-ignore-list", dest="ns_ignore_list",
                         default="")
        run.add_argument("--agg-flow", dest="agg_flow", default="",
                         choices=list(AGG_FLOWS))
        run.add_argument("--pod-label", dest="pod_label", default="")
        run.add_argument("--pod-name", dest="pod_name", default="")
        run.add_argument("--pod-namespace", dest="pod_namespace",
                         default="")
        run.add_argument("--external-ip", dest="external_ip", default="")
        run.add_argument("--svc-port-name", dest="svc_port_name",
                         default="")
        run.add_argument("--cluster-uuid", dest="cluster_uuid",
                         default="")
        run.add_argument("--refit-every", dest="refit_every", type=int,
                         default=1,
                         help="ARIMA refit cadence: 1 = exact "
                              "refit-per-step (default), k>1 = grouped "
                              "refits, 0 = auto for long series")
        sizing_flags(run)

    add_job_commands(tad, tad_run, tad_status, tad_retrieve, tad_list,
                     tad_delete, tad_flags)

    dd = sub.add_parser("drop-detection", aliases=["dd"],
                        help="abnormal traffic-drop detection")

    def dd_flags(run):
        run.add_argument("-t", "--type", default="initial",
                         choices=["initial"])
        run.add_argument("-s", "--start-time", dest="start_time",
                         default="")
        run.add_argument("-e", "--end-time", dest="end_time", default="")
        run.add_argument("--cluster-uuid", dest="cluster_uuid",
                         default="")

    add_job_commands(dd, dd_run, dd_status, dd_retrieve, dd_list,
                     dd_delete, dd_flags)

    fpm = sub.add_parser("pattern-mining", aliases=["fpm"],
                         help="frequent flow-pattern mining")

    def fpm_flags(run):
        run.add_argument("-m", "--min-support", dest="min_support",
                         type=int, default=0,
                         help="absolute support threshold (0 = auto: "
                              "1%% of rows, floor 2)")
        run.add_argument("-c", "--columns", default="",
                         help="comma-separated item columns")
        run.add_argument("--max-len", dest="max_len", type=int,
                         default=3, choices=[1, 2, 3])
        run.add_argument("-s", "--start-time", dest="start_time",
                         default="")
        run.add_argument("-e", "--end-time", dest="end_time",
                         default="")

    add_job_commands(fpm, fpm_run, fpm_status, fpm_retrieve, fpm_list,
                     fpm_delete, fpm_flags)

    sad = sub.add_parser("spatial-anomaly-detection", aliases=["sad"],
                         help="spatial DBSCAN over flow embeddings")

    def sad_flags(run):
        run.add_argument("--eps", type=float, default=None)
        run.add_argument("--min-samples", dest="min_samples", type=int,
                         default=None)
        run.add_argument("-s", "--start-time", dest="start_time",
                         default="")
        run.add_argument("-e", "--end-time", dest="end_time",
                         default="")

    add_job_commands(sad, sad_run, sad_status, sad_retrieve, sad_list,
                     sad_delete, sad_flags)

    ch = sub.add_parser("clickhouse")
    chsub = ch.add_subparsers(dest="action", required=True)
    status = chsub.add_parser("status")
    status.add_argument("--diskInfo", action="store_true")
    status.add_argument("--tableInfo", action="store_true")
    status.add_argument("--insertRate", action="store_true")
    status.add_argument("--stackTraces", action="store_true")
    status.add_argument("--deviceInfo", action="store_true",
                        help="accelerator inventory + HBM usage "
                             "(no reference equivalent)")
    status.set_defaults(fn=clickhouse_status)

    ing = sub.add_parser("ingest",
                         help="produce synthetic flow batches to "
                              "POST /ingest (exactly-once: stream+seq "
                              "stamped, 429 Retry-After honored)")
    ing.add_argument("--stream", default="",
                     help="producer stream id (default: random)")
    ing.add_argument("--batches", type=int, default=10)
    ing.add_argument("--series", type=int, default=64,
                     help="synthetic connection series per batch")
    ing.add_argument("--points", type=int, default=30,
                     help="points per series per batch")
    ing.add_argument("--anomaly-fraction", dest="anomaly_fraction",
                     type=float, default=0.1)
    ing.add_argument("--interval", type=float, default=0.0,
                     help="seconds between batches (0 = flat out)")
    ing.add_argument("--seed", type=int, default=0)
    ing.set_defaults(fn=ingest_cmd)

    q = sub.add_parser(
        "query",
        help="filtered aggregation over the flow store (the "
             "vectorized /query read path)")
    q.add_argument("--table", default="",
                   help="table to query: flows (default) or "
                        "__metrics__ (the stored metrics history)")
    q.add_argument("--group-by", default="",
                   help="comma-separated group-by columns "
                        "(e.g. sourceIP,destinationIP)")
    q.add_argument("--agg", action="append", default=[],
                   help="aggregate op:column (sum:octetDeltaCount, "
                        "mean:throughput) or `count`; repeatable")
    q.add_argument("--where", action="append", default=[],
                   help="filter clause: col>=443, sourceIP=10.0.0.9, "
                        "destinationIP in a,b; repeatable (ANDed)")
    q.add_argument("--start", type=int, default=None,
                   help="window start (unix seconds, inclusive)")
    q.add_argument("--end", type=int, default=None,
                   help="window end (unix seconds, exclusive)")
    q.add_argument("--time-column", default="",
                   help="window start column (default "
                        "flowStartSeconds)")
    q.add_argument("-k", type=int, default=None,
                   help="top-K groups by --order-by (0 = all)")
    q.add_argument("--order-by", default="",
                   help="aggregate label to order by (default: the "
                        "first aggregate)")
    q.add_argument("--json", action="store_true",
                   help="print the raw result document")
    q.add_argument("--explain", action="store_true",
                   help="attach the execution profile (per-part "
                        "scanned/pruned with reasons, kernel, cache, "
                        "per-peer fan-out timings) — the result rows "
                        "are identical either way")
    q.set_defaults(fn=query_cmd)

    sb = sub.add_parser("supportbundle")
    sb.add_argument("-f", "--file", default="")
    sb.set_defaults(fn=supportbundle)

    prof = sub.add_parser("profile",
                          help="capture an XLA profiler trace from "
                               "the manager")
    prof.add_argument("-d", "--duration", type=float, default=3.0)
    prof.add_argument("-f", "--file", default="")
    prof.set_defaults(fn=profile)

    tp = sub.add_parser("top",
                        help="live metric rates from the manager's "
                             "GET /metrics (Prometheus exposition)")
    tp.add_argument("-i", "--interval", type=float, default=2.0,
                    help="seconds between scrapes")
    tp.add_argument("-n", "--iterations", type=int, default=0,
                    help="render N tables then exit (0 = forever)")
    tp.add_argument("--no-clear", dest="no_clear", action="store_true",
                    help="append tables instead of clearing the screen")
    tp.add_argument("--cluster", action="store_true",
                    help="scrape EVERY endpoint in the (comma-"
                         "separated) --manager-addr list and render "
                         "per-node columns (rows/s, repl lag, "
                         "admission rung, parts, query/s, granule "
                         "skip ratio) plus a cluster-total row")
    tp.add_argument("--history", default="",
                    help="render sparklines from the STORED metrics "
                         "history (table __metrics__) over this "
                         "trailing window (e.g. 6h, 30m) instead of "
                         "diffing live scrapes")
    tp.add_argument("--metric", default="",
                    help="with --history: only series whose name "
                         "contains this substring")
    tp.add_argument("--node", default="",
                    help="with --history: only series recorded by "
                         "this node id")
    tp.set_defaults(fn=top)

    al = sub.add_parser("alerts",
                        help="recent alerts from the manager's ring "
                             "(detector + rule firings); --rules "
                             "shows the declarative rule set and "
                             "its hysteresis states")
    al.add_argument("--rules", action="store_true",
                    help="show the alert-rule set + per-(rule, node) "
                         "states instead of the alert ring")
    al.add_argument("--limit", type=int, default=100)
    al.add_argument("--json", action="store_true",
                    help="print the raw /alerts document")
    al.set_defaults(fn=alerts_cmd)

    tr = sub.add_parser("trace",
                        help="fetch one distributed trace by id from "
                             "any cluster node (the node stitches "
                             "every peer's spans) and render the "
                             "cross-node tree")
    tr.add_argument("trace_id", help="the traceId from an ingest ack, "
                                     "a /query result, or a span in "
                                     "/debug/traces")
    tr.set_defaults(fn=trace_cmd)

    pa = sub.add_parser("parts",
                        help="storage-engine part inventory from the "
                             "manager's GET /debug/parts: per-table "
                             "parts, tiers, formats, sort key, and "
                             "granule/index stats")
    pa.add_argument("--limit", type=int, default=64,
                    help="max per-part rows per table (the summary "
                         "header always covers everything)")
    pa.add_argument("--json", action="store_true",
                    help="print the raw /debug/parts document")
    pa.set_defaults(fn=parts_cmd)

    vw = sub.add_parser("views",
                        help="declared rollup views from the "
                             "manager's GET /debug/views: "
                             "definitions, tiers, aggregate part/row "
                             "counts, maintenance stats, loadError")
    vw.add_argument("--json", action="store_true",
                    help="print the raw /debug/views document")
    vw.set_defaults(fn=views_cmd)

    lk = sub.add_parser(
        "locks",
        help="lockdep witness: per-lock contention/hold stats, "
             "observed order edges, inversions (GET /debug/locks)")
    lk.add_argument("--json", action="store_true",
                    help="raw JSON document")
    lk.add_argument("--edges", action="store_true",
                    help="also print the observed order-edge table")
    lk.add_argument("--limit", type=int, default=30,
                    help="stats rows shown (sorted by total wait)")
    lk.set_defaults(fn=locks_cmd)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=version)
    return p


def main(argv=None) -> None:
    global _CA_CERT, _TOKEN
    args = build_parser().parse_args(argv)
    _CA_CERT = getattr(args, "ca_cert", "") or ""
    _TOKEN = getattr(args, "token", "") or ""
    token_file = getattr(args, "token_file", "") or ""
    if not _TOKEN and token_file:
        try:
            with open(token_file) as f:
                _TOKEN = f.read().strip()
        except OSError as e:
            raise APIError(
                f"error: cannot read token file {token_file}: {e}")
    forwarder = None
    if getattr(args, "use_port_forward", False):
        from .portforward import PortForwarder
        forwarder = PortForwarder(args.namespace, args.service,
                                  kubectl=args.kubectl)
        local = forwarder.start()
        # a --ca-cert means the in-cluster manager serves TLS; the
        # tunnel carries the TLS bytes verbatim
        scheme = "https" if _CA_CERT else "http"
        args.manager_addr = f"{scheme}://127.0.0.1:{local}"
    from ..utils import set_verbosity
    set_verbosity(getattr(args, "verbosity", 0))
    try:
        args.fn(args)
    except BrokenPipeError:
        # stdout consumer (e.g. `| head`) closed the pipe — exit quietly
        try:
            sys.stdout.close()
        except Exception:
            pass
        raise SystemExit(0)
    finally:
        if forwarder is not None:
            forwarder.stop()


if __name__ == "__main__":
    main()
