"""The `theia` command line interface (python -m theia_tpu.cli)."""
