"""Port-forward into the cluster for the CLI.

The reference CLI tunnels to the manager Service with a client-go
SPDY port-forwarder (pkg/theia/portforwarder/portforwarder.go:48,74)
unless --use-cluster-ip is set. The equivalent here delegates to
`kubectl port-forward` — the operator's kubeconfig and auth are
exactly what kubectl already handles — and parses the bound local
port from its output. The CLI owns the child for the duration of the
command and tears it down on exit.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Optional

API_PORT = 11347
START_TIMEOUT_SECONDS = 20.0


class PortForwardError(SystemExit):
    pass


class PortForwarder:
    """One `kubectl port-forward svc/<service> :11347` child."""

    def __init__(self, namespace: str, service: str = "theia-manager",
                 kubectl: str = "kubectl") -> None:
        self.namespace = namespace
        self.service = service
        self.kubectl = kubectl
        self.local_port: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None

    def start(self) -> int:
        """Spawn the forwarder; returns the local port once kubectl
        reports `Forwarding from 127.0.0.1:<port> -> ...`."""
        cmd = [self.kubectl, "-n", self.namespace, "port-forward",
               f"svc/{self.service}", f":{API_PORT}"]
        try:
            self._proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
        except FileNotFoundError:
            raise PortForwardError(
                f"error: --use-port-forward needs {self.kubectl!r} on "
                f"PATH (or pass --kubectl); alternatively reach the "
                f"manager directly with --manager-addr")

        port: list = []
        output: list = []   # kubectl's own words for the error path

        def read():
            assert self._proc and self._proc.stdout
            for line in self._proc.stdout:
                if line.strip():
                    output.append(line.strip())
                if not port and "Forwarding from" in line:
                    try:
                        # "Forwarding from 127.0.0.1:40123 -> 11347"
                        addr = line.split("Forwarding from", 1)[1]
                        port.append(int(
                            addr.split("->")[0].strip()
                            .rsplit(":", 1)[1]))
                    except (IndexError, ValueError):
                        pass
                    done.set()
            done.set()   # EOF: kubectl exited

        done = threading.Event()
        t = threading.Thread(target=read, daemon=True)
        t.start()
        if not done.wait(START_TIMEOUT_SECONDS) or not port:
            rc = self._proc.poll()
            self.stop()
            tail = " | ".join(output[-3:])
            raise PortForwardError(
                "error: port-forward did not come up"
                + (f" (kubectl exited {rc})" if rc is not None else "")
                + (f": {tail}" if tail else ""))
        self.local_port = port[0]
        return self.local_port

    def stop(self) -> None:
        if self._proc and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        self._proc = None
