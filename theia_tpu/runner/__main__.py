"""tpu-job-runner: the analytics jobs behind the Spark-job CLI contract.

Replaces the reference's SparkApplication payloads with a standalone
process the controllers can spawn. Option names/forms mirror the
reference scripts so the control plane stays drop-in compatible:

  tad — plugins/anomaly-detection/anomaly_detection.py:744-778 and the
        controller arg-build pkg/controller/anomalydetector/
        controller.go:525-620 (--algo, --start_time, --end_time, --id,
        --ns-ignore-list, --agg-flow, --pod-label, --pod-name,
        --pod-namespace, --external-ip, --svc-port-name)
  npr — plugins/policy-recommendation/policy_recommendation_job.py:
        1034-1084 (--type, --limit, --option, --start_time, --end_time,
        --ns_allow_list, --id, --rm_labels, --to_services)

Instead of a JDBC URL the runner takes --db (FlowDatabase .npz path);
results are written back into the same database file, or — with --out —
into a small results-only .npz (the manager's subprocess dispatch uses
this so a job over a large snapshot doesn't rewrite the whole flows
table just to hand back a few result rows). --progress-file emits
Spark-UI-shaped progress (see progress.py).

Usage:
  python -m theia_tpu.runner tad --db flows.npz --algo EWMA
  python -m theia_tpu.runner npr --db flows.npz --type initial -o 1
"""

from __future__ import annotations

import argparse
import datetime
import json
from typing import Optional

from ..utils import AGG_FLOWS, TAD_ALGOS

TIME_FORMAT = "%Y-%m-%d %H:%M:%S"

#: exit status for an injected/transient I/O failure (EX_TEMPFAIL):
#: the controller classifies it retry-worthy, unlike a spec error's
#: generic non-zero exit
TRANSIENT_EXIT_CODE = 75


def parse_time(value: Optional[str]) -> Optional[int]:
    if not value:
        return None
    dt = datetime.datetime.strptime(value, TIME_FORMAT)
    return int(dt.replace(tzinfo=datetime.timezone.utc).timestamp())


def _save_results(db, args) -> None:
    """--out: results-only snapshot (uncompressed: short-lived handoff
    file); default: full database written back into --db."""
    if getattr(args, "out", None):
        # all result tables, straight from the store registry — a
        # hand-kept list here silently dropped newly added kinds
        db.save(args.out, tables=tuple(db.result_tables),
                compress=False)
    else:
        db.save(args.db)


def parse_json_list(value: Optional[str]) -> list:
    if not value:
        return []
    parsed = json.loads(value)
    if not isinstance(parsed, list):
        raise argparse.ArgumentTypeError(
            f"expected a JSON list, got {value!r}")
    return parsed


def _add_common_job_flags(sp) -> None:
    """The shared job contract every subcommand carries: database
    path, time window, job id, progress file, results-only output."""
    sp.add_argument("--db", required=True,
                    help="FlowDatabase .npz path")
    sp.add_argument("-s", "--start_time", default="",
                    help=f"'{TIME_FORMAT}' UTC")
    sp.add_argument("-e", "--end_time", default="")
    sp.add_argument("-i", "--id", default=None)
    sp.add_argument("--progress-file", default=None)
    sp.add_argument("--out", default=None,
                    help="write result tables only to this .npz "
                         "(skips saving the full db back to --db)")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="theia_tpu.runner",
        description="TPU-native analytics job runner")
    sub = p.add_subparsers(dest="job", required=True)

    tad = sub.add_parser("tad", help="throughput anomaly detection")
    _add_common_job_flags(tad)
    tad.add_argument("-a", "--algo", required=True,
                     choices=list(TAD_ALGOS))
    tad.add_argument("-n", "--ns-ignore-list", "--ns_ignore_list",
                     dest="ns_ignore_list", default="")
    tad.add_argument("-f", "--agg-flow", dest="agg_flow", default="",
                     choices=list(AGG_FLOWS))
    tad.add_argument("-l", "--pod-label", dest="pod_label", default="")
    tad.add_argument("-N", "--pod-name", dest="pod_name", default="")
    tad.add_argument("-P", "--pod-namespace", dest="pod_namespace",
                     default="")
    tad.add_argument("-x", "--external-ip", dest="external_ip",
                     default="")
    tad.add_argument("-p", "--svc-port-name", dest="svc_port_name",
                     default="")
    tad.add_argument("-c", "--cluster-uuid", dest="cluster_uuid",
                     default="",
                     help="scope to one cluster in a multicluster store")
    tad.add_argument("--refit-every", "--refit_every",
                     dest="refit_every", type=int, default=1,
                     help="ARIMA refit cadence (1=exact per-step, "
                          "0=auto for long series)")

    npr = sub.add_parser("npr", help="network policy recommendation")
    _add_common_job_flags(npr)
    npr.add_argument("-t", "--type", dest="rec_type", default="initial",
                     choices=["initial", "subsequent"])
    npr.add_argument("-l", "--limit", type=int, default=0)
    npr.add_argument("-o", "--option", type=int, default=1,
                     choices=[1, 2, 3])
    npr.add_argument("-n", "--ns_allow_list", default="")
    npr.add_argument("--rm_labels", default="true")
    npr.add_argument("--to_services", default="true")

    dd = sub.add_parser("dropdetection",
                        help="abnormal traffic-drop detection "
                             "(theia-sf drop-detection equivalent)")
    _add_common_job_flags(dd)
    dd.add_argument("-t", "--type", dest="job_type", default="initial",
                    choices=["initial"])
    dd.add_argument("-c", "--cluster-uuid", dest="cluster_uuid",
                    default="")

    fpm = sub.add_parser("patterns",
                         help="frequent flow-pattern mining "
                              "(FP-Growth-equivalent output)")
    _add_common_job_flags(fpm)
    fpm.add_argument("-m", "--min-support", dest="min_support",
                     type=int, default=0,
                     help="absolute support threshold "
                          "(0 = auto: 1%% of rows, floor 2)")
    fpm.add_argument("-c", "--columns", default="",
                     help="comma-separated item columns "
                          "(default: ns/port/protocol set)")
    fpm.add_argument("--max-len", dest="max_len", type=int, default=3,
                     choices=[1, 2, 3])

    sp = sub.add_parser("spatial",
                        help="spatial DBSCAN anomaly detection over "
                             "flow embeddings")
    _add_common_job_flags(sp)
    sp.add_argument("--eps", type=float, default=None)
    sp.add_argument("--min-samples", dest="min_samples", type=int,
                    default=None)
    return p


def run_tad_job(args) -> str:
    from ..analytics import TadQuerySpec, run_tad
    from ..store import FlowDatabase
    from .progress import TAD_STAGES, JobProgress

    spec = TadQuerySpec(
        start_time=parse_time(args.start_time),
        end_time=parse_time(args.end_time),
        ns_ignore_list=parse_json_list(args.ns_ignore_list),
        agg_flow=args.agg_flow,
        pod_label=args.pod_label,
        pod_name=args.pod_name,
        pod_namespace=args.pod_namespace,
        external_ip=args.external_ip,
        svc_port_name=args.svc_port_name,
        cluster_uuid=args.cluster_uuid,
        refit_every=args.refit_every,
    )
    if args.pod_namespace and not (args.pod_label or args.pod_name):
        raise SystemExit(
            "invalid request: 'pod-namespace' argument can not be used "
            "alone, should be specified along pod-label or pod-name")
    progress = JobProgress(args.id or "tad", TAD_STAGES,
                           path=args.progress_file)
    try:
        db = FlowDatabase.load(args.db)
        job_id = run_tad(db, args.algo, spec, tad_id=args.id,
                         progress=progress)
        _save_results(db, args)
    except BaseException as e:
        progress.fail(str(e))
        raise
    return job_id


def run_npr_job(args) -> str:
    from ..analytics import run_npr
    from ..store import FlowDatabase
    from .progress import NPR_STAGES, JobProgress

    progress = JobProgress(args.id or "npr", NPR_STAGES,
                           path=args.progress_file)
    try:
        db = FlowDatabase.load(args.db)
        job_id = run_npr(
            db,
            recommendation_type=args.rec_type,
            limit=args.limit,
            option=args.option,
            start_time=parse_time(args.start_time),
            end_time=parse_time(args.end_time),
            ns_allow_list=(parse_json_list(args.ns_allow_list) or None),
            rm_labels=args.rm_labels != "false",
            to_services=args.to_services != "false",
            recommendation_id=args.id,
            progress=progress,
        )
        _save_results(db, args)
    except BaseException as e:
        progress.fail(str(e))
        raise
    return job_id


def run_dd_job(args) -> str:
    from ..analytics import run_drop_detection
    from ..store import FlowDatabase
    from .progress import DD_STAGES, JobProgress

    progress = JobProgress(args.id or "dd", DD_STAGES,
                           path=args.progress_file)
    try:
        db = FlowDatabase.load(args.db)
        job_id = run_drop_detection(
            db,
            job_type=args.job_type,
            detection_id=args.id,
            start_time=parse_time(args.start_time),
            end_time=parse_time(args.end_time),
            cluster_uuid=args.cluster_uuid,
            progress=progress,
        )
        _save_results(db, args)
    except BaseException as e:
        progress.fail(str(e))
        raise
    return job_id


def run_patterns_job(args) -> str:
    from ..analytics import run_pattern_mining
    from ..analytics.itemsets import DEFAULT_COLUMNS
    from ..store import FlowDatabase
    from .progress import FPM_STAGES, JobProgress

    progress = JobProgress(args.id or "patterns", FPM_STAGES,
                           path=args.progress_file)
    try:
        db = FlowDatabase.load(args.db)
        columns = (tuple(c.strip() for c in args.columns.split(",")
                         if c.strip())
                   if args.columns else DEFAULT_COLUMNS)
        job_id = run_pattern_mining(
            db,
            min_support=args.min_support,
            columns=columns,
            max_len=args.max_len,
            start_time=parse_time(args.start_time),
            end_time=parse_time(args.end_time),
            mining_id=args.id,
            progress=progress,
        )
        _save_results(db, args)
    except BaseException as e:
        progress.fail(str(e))
        raise
    return job_id


def run_spatial_job(args) -> str:
    from ..analytics import run_spatial
    from ..analytics.spatial import DEFAULT_EPS, DEFAULT_MIN_SAMPLES
    from ..store import FlowDatabase
    from .progress import SPATIAL_STAGES, JobProgress

    progress = JobProgress(args.id or "spatial", SPATIAL_STAGES,
                           path=args.progress_file)
    try:
        db = FlowDatabase.load(args.db)
        job_id = run_spatial(
            db,
            eps=args.eps if args.eps is not None else DEFAULT_EPS,
            min_samples=(args.min_samples
                         if args.min_samples is not None
                         else DEFAULT_MIN_SAMPLES),
            start_time=parse_time(args.start_time),
            end_time=parse_time(args.end_time),
            spatial_id=args.id,
            progress=progress,
        )
        _save_results(db, args)
    except BaseException as e:
        progress.fail(str(e))
        raise
    return job_id


def main(argv=None) -> None:
    # Honor an explicit JAX_PLATFORMS before any backend initializes
    # (deployment sitecustomize hooks may pin the platform
    # programmatically, overriding the env var) — the manager spawns
    # runner children with the platform it wants them on.
    import os
    plats = os.environ.get("JAX_PLATFORMS", "").strip()
    if plats:
        import jax
        jax.config.update("jax_platforms", plats)
    args = build_parser().parse_args(argv)
    # Fault point shared with thread dispatch: THEIA_FAULTS reaches
    # this child through the env the controller spawned it with. An
    # injected error exits TRANSIENT_EXIT_CODE (the controller's
    # retry classification); an injected hang sits here until the
    # controller's deadline kill.
    import sys

    from ..utils import faults
    try:
        faults.fire("runner.exec", job=args.job)
    except faults.FaultError as e:
        print(str(e), file=sys.stderr)
        raise SystemExit(TRANSIENT_EXIT_CODE)
    runners = {"tad": run_tad_job, "npr": run_npr_job,
               "dropdetection": run_dd_job,
               "patterns": run_patterns_job,
               "spatial": run_spatial_job}
    # Trace the whole run and ship the timing summary on stderr: this
    # process dies with the job, so its obs state surfaces through the
    # stderr tail the controller keeps on the record (runner_log_tail,
    # the support bundle's runner-log source).
    from ..obs import trace
    with trace.span("runner.job", job=args.job, id=args.id or ""):
        job_id = runners[args.job](args)
    for op, rec in trace.slowest().items():
        print(f"timing {op}: {rec['durationMs']:.1f} ms",
              file=sys.stderr)
    print(json.dumps({"id": job_id, "state": "COMPLETED"}))


if __name__ == "__main__":
    main()
