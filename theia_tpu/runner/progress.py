"""Job progress reporting, shaped like the Spark UI REST the reference
controllers scrape (pkg/controller/util.go:129-159 reads
/api/v1/applications/<id>/stages and surfaces completedStages/
totalStages into CRD status).

The runner updates a JSON document after every stage; it is written
atomically to a file (for the file-based manager/controller seam) and
kept in memory for in-process callers.
"""

from __future__ import annotations

import json
import threading
import time
from typing import List, Optional

from ..utils import atomic_write
from ..analysis.lockdep import named_lock


class JobProgress:
    """Tracks named stages of one job run.

    States mirror the Spark application lifecycle the controllers map
    into CRD status (controller.go:458-500): RUNNING → COMPLETED/FAILED.
    """

    def __init__(self, job_id: str, stages: List[str],
                 path: Optional[str] = None) -> None:
        self.job_id = job_id
        self.stages = list(stages)
        self.path = path
        self._completed = 0
        self._state = "RUNNING"
        self._error = ""
        self._current = ""
        self._started = time.time()
        self._lock = named_lock("runner.progress")
        self._flush()

    def stage(self, name: str) -> None:
        with self._lock:
            if self._current:
                self._completed += 1
            self._current = name
        self._flush()

    def done(self) -> None:
        with self._lock:
            self._completed = len(self.stages)
            self._current = ""
            self._state = "COMPLETED"
        self._flush()

    def fail(self, error: str) -> None:
        with self._lock:
            self._state = "FAILED"
            self._error = error
        self._flush()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "id": self.job_id,
                "state": self._state,
                "currentStage": self._current,
                "completedStages": self._completed,
                "totalStages": len(self.stages),
                "errorMsg": self._error,
                "startedAt": self._started,
            }

    def _flush(self) -> None:
        if not self.path:
            return
        snap = self.snapshot()

        def write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(snap, f)

        atomic_write(self.path, write)


class FileProgress:
    """Read side of a runner's --progress-file: the manager's
    equivalent of the reference scraping the Spark UI REST into CRD
    status (pkg/controller/util.go:129-159). snapshot() re-reads the
    file and caches the last good document, so status stays correct
    after the job's scratch directory is cleaned up."""

    def __init__(self, job_id: str, stages: List[str],
                 path: str) -> None:
        self.job_id = job_id
        self.stages = list(stages)
        self.path = path
        self._last = {
            "id": job_id,
            "state": "RUNNING",
            "currentStage": "",
            "completedStages": 0,
            "totalStages": len(stages),
            "errorMsg": "",
            "startedAt": time.time(),
        }

    def snapshot(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and "completedStages" in doc:
                self._last = doc
        except (OSError, ValueError):
            pass   # mid-write/retired file: serve the cached snapshot
        return dict(self._last)

    def fail(self, error: str) -> None:
        """The runner process owns the file; just reflect the failure
        in the cached snapshot for status readers."""
        self._last = {**self._last, "state": "FAILED",
                      "errorMsg": error}


TAD_STAGES = ["read", "tensorize", "score", "write"]
NPR_STAGES = ["read", "recommend", "write"]
DD_STAGES = ["read", "tensorize", "score", "write"]
FPM_STAGES = ["read", "mine", "write"]
SPATIAL_STAGES = ["read", "embed", "score", "write"]
