"""tpu-job-runner: Spark-job CLI contract + progress reporting."""

from .progress import NPR_STAGES, TAD_STAGES, JobProgress

__all__ = ["JobProgress", "TAD_STAGES", "NPR_STAGES"]
