#!/usr/bin/env python
"""Benchmark: TAD scoring throughput on the local accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference's documented end-to-end capacity is ~4,000 flow
records/s (ClickHouse insert rate on the default deployment,
reference docs/network-flow-visibility.md:484-488; the Spark jobs then
re-scan those rows in minutes-long batches). Here the comparable number
is how many flow records per second the TPU engine scores through the
jitted EWMA anomaly step (scan + stddev + threshold over padded series).

Method: synthesize a small host batch once, tile it to a large
device-resident [S, T] batch (so the Python-bound generator is off the
measured path — VERDICT r1 note), then time steady-state jitted steps.
Each step scores S·T flow records. Secondary numbers (host tensorize
rate, device transfer) go to stderr.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np

BASELINE_RECORDS_PER_SEC = 4000.0


def _kill_strays() -> None:
    """Kill leftover theia manager/runner processes before touching the
    accelerator: a stray process still holding the chip is exactly what
    produced round 3's 'TPU backend setup/compile error' — the bench
    must own the device when the driver runs it."""
    me = os.getpid()
    try:
        pids = [int(p) for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return
    for pid in pids:
        if pid == me:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    errors="replace")
        except OSError:
            continue
        if "theia_tpu.manager" in cmd or "theia_tpu.runner" in cmd:
            print(f"killing stray process {pid}: {cmd[:120]}",
                  file=sys.stderr)
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass


def _run_child(env: dict, timeout_s: float):
    """Run the measurement in a child process (THEIA_BENCH_INNER=1) so
    a hung accelerator tunnel can be killed instead of hanging the
    whole bench. Returns (stdout, failure_reason): stdout is the JSON
    line (b'' on failure); failure_reason is None, "timeout", or
    "init failure (rc=N)" — the caller's retry decision hangs on the
    distinction (a lease-wedged tunnel may recover, a platform that
    failed to initialize will fail again immediately)."""
    t0 = time.monotonic()
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**env, "THEIA_BENCH_INNER": "1"},
            stdout=subprocess.PIPE, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench child timed out after {timeout_s:.0f}s",
              file=sys.stderr)
        return b"", "timeout"
    if child.returncode != 0:
        elapsed = time.monotonic() - t0
        print(f"bench child exited rc={child.returncode} after "
              f"{elapsed:.0f}s", file=sys.stderr)
        # A fast nonzero exit is platform init failing deterministically
        # (retrying hits the same wall); a child that ran a while and
        # THEN died (OOM kill, flaky tunnel) is a transient crash the
        # retry exists for.
        if elapsed < 60.0:
            return b"", f"init failure (rc={child.returncode})"
        return b"", f"crash (rc={child.returncode})"
    out = child.stdout.strip()
    # rc=0 with no JSON line still needs a non-None reason: the caller
    # branches on it (and an empty success should retry, not crash)
    return out, (None if out else "no output (rc=0)")


def _parse_args(argv=None):
    import argparse
    p = argparse.ArgumentParser(
        description="theia-tpu benchmark driver (one JSON result "
                    "line on stdout, whatever happens)")
    p.add_argument("--out", default="BENCH_latest.json",
                   help="write the result as a schema-versioned JSON "
                        "artifact (host metadata + per-leg values) to "
                        "this path — reproducible BENCH_*.json "
                        "instead of numbers living in changelog "
                        "prose. Default BENCH_latest.json, so every "
                        "run leaves a machine-readable trajectory "
                        "point; pass --out '' to skip the artifact")
    return p.parse_args(argv)


def _write_artifact(path: str, result: dict) -> None:
    """Schema-versioned bench artifact: the result dict plus enough
    host metadata to interpret (or distrust) the numbers later."""
    import datetime
    import platform
    import socket
    doc = {
        "schemaVersion": 1,
        "createdAt": datetime.datetime.now(
            datetime.timezone.utc).isoformat(),
        "host": {
            "hostname": socket.gethostname(),
            "cpus": os.cpu_count(),
            "platform": platform.platform(),
            "python": platform.python_version(),
        },
        # knobs only, never credentials: the artifact is meant to be
        # committed/shared (THEIA_TOKEN / THEIA_AUTH_TOKEN carry the
        # deployment's service secret)
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(("THEIA_", "JAX_"))
                and not any(s in k for s in
                            ("TOKEN", "SECRET", "KEY", "PASSWORD"))},
        "result": result,
    }
    try:
        import jax
        doc["host"]["jax"] = jax.__version__
    except Exception:
        pass
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    print(f"bench artifact written to {path}", file=sys.stderr)


def _leg_stats(times) -> dict:
    """best/median/spread for one timed leg's per-iteration seconds —
    recorded under result["leg_stats"] so the 2-core bench host's
    run-to-run noise (ROADMAP: 12-36k rows/s swings across identical
    runs) is visible IN the JSON artifact, not just changelog prose.
    spreadPct = (worst - best) / median."""
    ts = sorted(float(t) for t in times)
    med = ts[len(ts) // 2]
    return {
        "iterations": len(ts),
        "bestMs": round(ts[0] * 1e3, 3),
        "medianMs": round(med * 1e3, 3),
        "spreadPct": round((ts[-1] - ts[0]) / med * 100, 1)
        if med > 0 else 0.0,
    }


def main() -> None:
    """Always prints exactly one JSON result line on stdout, whatever
    fails or HANGS. The orchestrator (this function) owns no JAX state;
    it runs the measurement in a child on the default backend, and if
    the child dies or stalls (round 3: jax.devices() hung on a dead
    accelerator tunnel) retries once on the CPU backend, then emits a
    value-0 line as the last resort."""
    if os.environ.get("THEIA_BENCH_INNER") == "1":
        print(json.dumps(run_benchmarks()))
        return
    args = _parse_args()
    _kill_strays()
    # Device-attempt budget: THEIA_BENCH_DEVICE_TIMEOUT wins (BENCH_r05
    # burned 2x420s before degrading; a host that knows its accelerator
    # should cap the attempt tighter), legacy THEIA_BENCH_TIMEOUT next.
    timeout_s = float(os.environ.get("THEIA_BENCH_DEVICE_TIMEOUT")
                      or os.environ.get("THEIA_BENCH_TIMEOUT")
                      or "420")
    # More than one accelerator attempt: a stale pool claim (a killed
    # TPU process earlier in the round) wedges the tunnel until its
    # lease expires — a second try minutes later can land on a
    # recovered backend where the first hung, and a real TPU number
    # beats a fast degraded one.
    try:
        attempts = max(1, int(
            os.environ.get("THEIA_BENCH_TPU_ATTEMPTS", "2")))
    except ValueError:
        attempts = 2   # never let a bad env var break the JSON line
    retry_wait = 120.0
    out = b""
    degraded_reason = None
    for attempt in range(attempts):
        t_try = time.monotonic()
        out, why = _run_child(dict(os.environ), timeout_s)
        if out:
            break
        if why.startswith("init failure"):
            # Platform init itself failed (fast, deterministic): the
            # retry would hit the same wall — go straight to CPU.
            degraded_reason = f"accelerator {why}"
            print("platform init failed; skipping the retry",
                  file=sys.stderr)
            break
        degraded_reason = (f"accelerator attempt timed out after "
                           f"{timeout_s:.0f}s"
                           if why == "timeout"
                           else f"accelerator {why}")
        if attempt + 1 < attempts:
            # A fast failure re-hits the same unexpired lease; only
            # waiting gives the pool a chance to reclaim it.
            elapsed = time.monotonic() - t_try
            wait = max(0.0, retry_wait - elapsed)
            print(f"accelerator attempt {attempt + 1}/{attempts} "
                  f"failed; retrying in {wait:.0f}s (pool lease may "
                  f"expire)", file=sys.stderr)
            time.sleep(wait)
    if not out:
        print("retrying on the CPU backend (degraded)", file=sys.stderr)
        # The CPU fallback gets its own budget: THEIA_BENCH_DEVICE_
        # TIMEOUT caps accelerator attempts only — a tight device cap
        # must not kill the fallback that exists to survive it.
        cpu_timeout = float(os.environ.get("THEIA_BENCH_TIMEOUT")
                            or "420")
        out, _ = _run_child(
            {**os.environ, "JAX_PLATFORMS": "cpu",
             "THEIA_BENCH_FAST": "1"}, cpu_timeout)
        if out and degraded_reason:
            # stamp WHY the bench degraded, not just that it did
            try:
                doc = json.loads(out)
                doc["degraded_reason"] = degraded_reason
                out = json.dumps(doc).encode()
            except ValueError:
                pass
    if not out:
        out = json.dumps({
            "metric": "tad_ewma_scoring_records_per_sec", "value": 0,
            "unit": "records/s", "vs_baseline": 0.0,
            "error": "all backends failed or timed out; see stderr",
            "degraded_reason": degraded_reason
            or "all backends failed",
        }).encode()
    if args.out:
        try:
            _write_artifact(args.out, json.loads(out))
        except Exception as e:
            print(f"bench artifact write failed: {e}", file=sys.stderr)
    sys.stdout.buffer.write(out + b"\n")
    sys.stdout.flush()


def run_benchmarks() -> dict:
    import jax

    # The axon sitecustomize hook sets jax_platforms programmatically,
    # which overrides the env var — force the requested backend back
    # (same dance as tests/conftest.py) or the CPU-fallback child would
    # re-initialize the very accelerator tunnel it is falling back from.
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from theia_tpu.analytics import TadQuerySpec, build_series
    from theia_tpu.data.synth import SynthConfig, generate_flows
    from theia_tpu.ops.ewma import ewma_scores

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    # Host side: generate + tensorize a seed batch (measured separately).
    cfg = SynthConfig(n_series=256, points_per_series=128,
                      anomaly_fraction=0.1, seed=0)
    t0 = time.perf_counter()
    batch = generate_flows(cfg)
    t1 = time.perf_counter()
    series = build_series(batch, TadQuerySpec(), dtype=np.float32)
    tensorize_rate = 0.0
    for _ in range(3):   # warm best-of-3 (first call pays the .so load)
        t2 = time.perf_counter()
        series = build_series(batch, TadQuerySpec(), dtype=np.float32)
        tensorize_rate = max(tensorize_rate,
                             len(batch) / (time.perf_counter() - t2))
    print(f"host synth: {len(batch) / (t1 - t0):,.0f} rows/s; "
          f"tensorize: {tensorize_rate:,.0f} rows/s",
          file=sys.stderr)

    # Tile to a large device batch: 32768 series x 128 steps = 4.2M
    # records per step (~16 MiB fp32).
    reps = 32768 // series.values.shape[0]
    x = np.tile(series.values.astype(np.float32), (reps, 1))
    mask = np.tile(series.mask, (reps, 1))
    n_records = x.size

    t3 = time.perf_counter()
    xd = jax.device_put(x)
    md = jax.device_put(mask)
    jax.block_until_ready((xd, md))
    t4 = time.perf_counter()
    print(f"device transfer: {x.nbytes / (t4 - t3) / 1e9:.2f} GB/s",
          file=sys.stderr)

    # Warmup (compile) then steady-state timing.
    out = ewma_scores(xd, md)
    jax.block_until_ready(out)
    n_iters = 20
    t5 = time.perf_counter()
    for _ in range(n_iters):
        out = ewma_scores(xd, md)
    jax.block_until_ready(out)
    t6 = time.perf_counter()

    step_s = (t6 - t5) / n_iters
    records_per_sec = n_records / step_s
    print(f"step: {step_s * 1e3:.3f} ms for {n_records:,} records "
          f"({x.nbytes / step_s / 1e9:.1f} GB/s effective)",
          file=sys.stderr)

    # Secondary: ARIMA / DBSCAN steady-state device rates on a smaller
    # batch (ARIMA's walk-forward scan is far heavier than EWMA).
    # THEIA_BENCH_FAST (set on the CPU-fallback retry) skips them —
    # minutes of walk-forward ARIMA on a host core would starve the
    # stages that still say something useful about the pipeline.
    try:
        if os.environ.get("THEIA_BENCH_FAST") == "1":
            raise RuntimeError("THEIA_BENCH_FAST=1")
        from theia_tpu.ops import arima_scores, dbscan_scores
        xs, ms = xd[:4096], md[:4096]
        for name, fn in (("ARIMA", arima_scores),
                         ("DBSCAN", dbscan_scores)):
            jax.block_until_ready(fn(xs, ms))   # compile
            ta = time.perf_counter()
            for _ in range(5):
                out2 = fn(xs, ms)
            jax.block_until_ready(out2)
            rate = xs.size * 5 / (time.perf_counter() - ta)
            print(f"{name} scoring: {rate:,.0f} records/s "
                  f"({xs.shape[0]} series)", file=sys.stderr)
    except Exception as e:
        print(f"algo bench skipped: {e}", file=sys.stderr)

    # Secondary diagnostics (stderr): native ingest rate + streaming
    # alert latency on this chip.
    try:
        from theia_tpu.ingest import BlockEncoder, TsvDecoder, \
            encode_tsv, native_available
        if native_available():
            payload = encode_tsv(batch) * 8
            dec = TsvDecoder()
            dec.decode(payload)   # warm
            t7 = time.perf_counter()
            decoded = dec.decode(payload)
            t8 = time.perf_counter()
            print(f"native ingest (TSV): "
                  f"{len(decoded) / (t8 - t7):,.0f} rows/s",
                  file=sys.stderr)
            enc = BlockEncoder(dicts=batch.dicts)
            blocks = [enc.encode(batch) for _ in range(9)]
            bdec = TsvDecoder()
            bdec.decode_block(blocks[0])   # warm + dict delta
            t7 = time.perf_counter()
            n_blk = sum(len(bdec.decode_block(p)) for p in blocks[1:])
            t8 = time.perf_counter()
            print(f"native ingest (binary block): "
                  f"{n_blk / (t8 - t7):,.0f} rows/s", file=sys.stderr)
    except Exception as e:
        print(f"ingest bench skipped: {e}", file=sys.stderr)

    try:
        from theia_tpu.store import FlowDatabase
        host = generate_flows(SynthConfig(n_series=2000,
                                          points_per_series=30))
        FlowDatabase().insert_flows(host)   # warm native group-sum
        best = 0.0
        for _ in range(3):
            db = FlowDatabase()
            t9 = time.perf_counter()
            db.insert_flows(host)
            best = max(best, len(host) / (time.perf_counter() - t9))
        print(f"store insert (3 MV fan-out): {best:,.0f} rows/s",
              file=sys.stderr)
    except Exception as e:
        print(f"store bench skipped: {e}", file=sys.stderr)

    # Degraded-mode fan-out: replicated write throughput with one of
    # two replicas auto-quarantined by an injected per-replica write
    # fault — the number an operator sees between a replica failure
    # and its repair-loop re-admission.
    degraded_write = 0.0
    try:
        from theia_tpu.store import ReplicatedFlowDatabase
        from theia_tpu.utils import faults
        host2 = generate_flows(SynthConfig(n_series=2000,
                                           points_per_series=30))
        rdb = ReplicatedFlowDatabase(replicas=2)
        rdb.insert_flows(host2)   # warm both replicas
        faults.arm("replica.write:error@2")   # next fan-out, replica 1
        try:
            rdb.insert_flows(host2)
        finally:
            faults.disarm()
        if not rdb.membership()["quarantined"]:
            raise RuntimeError("injected fault did not quarantine")
        best = 0.0
        for _ in range(3):
            tq = time.perf_counter()
            rdb.insert_flows(host2)
            best = max(best,
                       len(host2) / (time.perf_counter() - tq))
        degraded_write = best
        print(f"degraded fan-out write (1 of 2 replicas "
              f"quarantined): {best:,.0f} rows/s", file=sys.stderr)
    except Exception as e:
        print(f"degraded-write bench skipped: {e}", file=sys.stderr)

    # End-to-end pipeline: wire bytes → stream decode → store insert
    # (3 MV fan-out, TTL check) → heavy-hitter + per-connection
    # streaming detectors → alert ring — the whole POST /ingest path
    # as one number (VERDICT r2 #2). The detector legs run on the HOST
    # cpu backend here: under axon the host↔device link is a remote
    # tunnel measured above at ~0.1 GB/s — a dev-environment artifact
    # ~2 orders of magnitude below a real v5e host's DMA link — and
    # letting streaming state ride it would time the tunnel, not the
    # pipeline.
    e2e_rate = 0.0
    e2e_stages: dict = {}
    e2e_scaling: dict = {}
    det_shard_scaling: dict = {}
    try:
        import contextlib

        from theia_tpu.ingest import BlockEncoder, TsvDecoder, \
            native_available
        from theia_tpu.manager.ingest import (IngestManager,
                                              default_ingest_shards)
        from theia_tpu.store import FlowDatabase

        if native_available():
            def cpu_ctx():
                # fresh context manager per `with`: jax.default_device
                # returns a single-use @contextmanager on current jax
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()
            big = generate_flows(SynthConfig(n_series=2000,
                                             points_per_series=30))
            enc = BlockEncoder(dicts=big.dicts)
            blocks = [enc.encode(big) for _ in range(9)]
            with cpu_ctx():
                # Headline: the real IngestManager path, one stream.
                # Best-of-2 passes: shared-host CPU steal makes single
                # passes noisy (observed 2-3x swings on idle RAM).
                im = IngestManager(FlowDatabase(ttl_seconds=12 * 3600))
                im.ingest(blocks[0])   # warm: dict deltas + jit
                dt = float("inf")
                for _ in range(2):
                    t9 = time.perf_counter()
                    n_e2e = sum(im.ingest(p)["rows"]
                                for p in blocks[1:])
                    dt = min(dt, time.perf_counter() - t9)

                # Stage attribution: replicate the same pipeline with
                # per-stage stopwatches IN ONE LOOP (separate passes
                # skew — adoption/dict caches warm differently and the
                # remainder can go negative).
                from theia_tpu.analytics.heavy_hitters import \
                    HeavyHitterDetector
                from theia_tpu.analytics.streaming import \
                    StreamingDetector
                # Best-of-2 vs CPU steal: each pass rebuilds ALL state
                # (same workload both times — replaying into a grown
                # store / warmed detectors would measure a different
                # pipeline), and the kept stage triple comes from ONE
                # pass (independent per-stage minima could describe an
                # execution that never happened and mis-name the cap).
                t_dec = t_store = t_det = 0.0
                best_total = float("inf")
                stage_samples = {"decode": [], "store": [],
                                 "detector": []}
                for _ in range(2):
                    d2 = TsvDecoder()
                    db2 = FlowDatabase(ttl_seconds=12 * 3600)
                    hh2 = HeavyHitterDetector()
                    sd2 = StreamingDetector()
                    warm = d2.decode_block(blocks[0])
                    db2.insert_flows(warm)
                    hh2.update(warm)
                    sd2.ingest(warm)
                    s_dec = s_store = s_det = 0.0
                    samples = {"decode": [], "store": [],
                               "detector": []}
                    for p in blocks[1:]:
                        ta = time.perf_counter()
                        b = d2.decode_block(p)
                        tb = time.perf_counter()
                        db2.insert_flows(b)
                        tc = time.perf_counter()
                        hh2.update(b)
                        sd2.ingest(b)
                        td = time.perf_counter()
                        s_dec += tb - ta
                        s_store += tc - tb
                        s_det += td - tc
                        samples["decode"].append(tb - ta)
                        samples["store"].append(tc - tb)
                        samples["detector"].append(td - tc)
                    total = s_dec + s_store + s_det
                    if total < best_total:
                        best_total = total
                        t_dec, t_store, t_det = s_dec, s_store, s_det
                        stage_samples = samples

            def _p95_ms(xs):
                xs = sorted(xs)
                return round(
                    xs[min(len(xs) - 1,
                           int(round(0.95 * (len(xs) - 1))))] * 1e3,
                    2)
            e2e_rate = n_e2e / dt
            e2e_stages = {
                "decode_rows_per_sec": round(n_e2e / t_dec),
                "store_rows_per_sec": round(n_e2e / t_store),
                "detector_rows_per_sec": round(n_e2e / t_det),
                # per-block p95 latency per stage: mean rates hide the
                # tail (one slow MV fan-out or jit retrace per pass)
                "decode_p95_ms": _p95_ms(stage_samples["decode"]),
                "store_p95_ms": _p95_ms(stage_samples["store"]),
                "detector_p95_ms": _p95_ms(stage_samples["detector"]),
            }
            # The ingest path runs the store and detector legs
            # OVERLAPPED (manager/ingest.py pipelining), so the
            # steady-state ceiling is decode vs the SLOWER of the two
            # overlapped legs — not the sum of all three. The cap
            # names the stage that sets that pipelined floor.
            overlap_rate = n_e2e / max(t_store, t_det)
            e2e_stages["pipelined_floor_rows_per_sec"] = round(
                min(e2e_stages["decode_rows_per_sec"], overlap_rate))
            if e2e_stages["decode_rows_per_sec"] <= overlap_rate:
                cap = "decode_rows_per_sec"
            elif t_store >= t_det:
                cap = "store_rows_per_sec (overlapped)"
            else:
                cap = "detector_rows_per_sec (overlapped)"
            cores = os.cpu_count() or 1
            print(f"end-to-end ingest (wire->store+views->2 detectors"
                  f"->alerts, store||detector overlapped): "
                  f"{e2e_rate:,.0f} rows/s "
                  f"[decode {n_e2e / t_dec:,.0f}, store "
                  f"{n_e2e / t_store:,.0f}, "
                  f"detectors {n_e2e / t_det:,.0f} rows/s; "
                  f"pipelined floor "
                  f"{e2e_stages['pipelined_floor_rows_per_sec']:,} "
                  f"rows/s; cap: {cap}; host cores={cores}; "
                  f"{e2e_rate / cores:,.0f} rows/s/core, single "
                  f"stream]", file=sys.stderr)

            # Multi-stream scaling structure: k producer threads, one
            # IngestManager, distinct streams (decode parallelizes —
            # the native decoder and group-sum release the GIL; the
            # detector leg serializes on its lock). On a 1-core host
            # expect ~flat; the structure is what a multi-core v5e
            # host scales.
            import gc
            import threading

            # Drop the headline/attribution stores first: three live
            # ~200 MB databases push a small bench VM into swap and
            # the scaling numbers stop measuring the pipeline.
            del im, db2, hh2, sd2, warm
            gc.collect()

            from theia_tpu.schema import ColumnarBatch, \
                StringDictionary

            def reprefix_ips(batch, sid):
                """The same flow shapes moved into producer `sid`'s
                own address blocks (10.{sid}./203.{sid}.): distinct
                producers export distinct flow populations, so their
                detector keys — and shard assignments — differ the
                way real per-node exporters' do. Codes are preserved
                (entries re-encode in code order), only the strings
                move."""
                if sid == 0:
                    return batch
                dicts = dict(batch.dicts)
                for col in ("sourceIP", "destinationIP"):
                    nd = StringDictionary()
                    for s in batch.dicts[col].entries_since(0):
                        if s:
                            s = s.replace(
                                "10.0.", f"10.{sid}.", 1).replace(
                                "203.0.", f"203.{sid}.", 1)
                        nd.encode_one(s)
                    dicts[col] = nd
                return ColumnarBatch(dict(batch.columns), dicts)

            bigs = [reprefix_ips(big, sid) for sid in range(4)]
            with cpu_ctx():
                for k in (1, 2, 4):
                    imk = IngestManager(
                        FlowDatabase(ttl_seconds=12 * 3600))
                    encs = [BlockEncoder(dicts=bigs[i].dicts)
                            for i in range(k)]
                    payloads = [[encs[i].encode(bigs[i])
                                 for _ in range(4)]
                                for i in range(k)]
                    # warm each stream's dict chain + jit
                    for i in range(k):
                        imk.ingest(payloads[i][0], stream=f"s{i}")

                    def feed(i):
                        for p in payloads[i][1:]:
                            imk.ingest(p, stream=f"s{i}")

                    best = float("inf")
                    for _ in range(2):   # best-of-2 vs CPU steal
                        threads = [threading.Thread(target=feed,
                                                    args=(i,))
                                   for i in range(k)]
                        ts = time.perf_counter()
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        best = min(best, time.perf_counter() - ts)
                    rows = k * 3 * len(big)
                    e2e_scaling[str(k)] = round(rows / best)
                    del imk, payloads
                    gc.collect()
                print("multi-stream e2e: " + ", ".join(
                    f"{k} streams {v:,} rows/s"
                    for k, v in e2e_scaling.items()), file=sys.stderr)

                # Detector-leg shard scaling: S shards, S feeder
                # threads, scoring only (no decode/insert) — isolates
                # what lifting the global detector lock buys. Each
                # feeder scores its own distinct flow population
                # (reprefix_ips), so S threads hold different shard
                # locks concurrently where cores exist.
                for s_count in (1, 2, 4):
                    imd = IngestManager(FlowDatabase(),
                                        n_shards=s_count)
                    for sid in range(s_count):   # warm jit+dicts
                        imd.score_batch(bigs[sid])

                    def feed_det(sid, imd=imd):
                        for _ in range(8):
                            imd.score_batch(bigs[sid])

                    best = float("inf")
                    for _ in range(2):   # best-of-2 vs CPU steal
                        threads = [threading.Thread(target=feed_det,
                                                    args=(sid,))
                                   for sid in range(s_count)]
                        ts = time.perf_counter()
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        best = min(best, time.perf_counter() - ts)
                    rows = s_count * 8 * len(big)
                    det_shard_scaling[str(s_count)] = round(
                        rows / best)
                    imd.close()
                    del imd
                    gc.collect()
                print("detector shard scaling: " + ", ".join(
                    f"{k} shards {v:,} rows/s"
                    for k, v in det_shard_scaling.items()),
                    file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"e2e bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Fused-engine legs (the device-resident scoring pipeline,
    # ingest/device_path.py). The engine-parity gate runs FIRST — the
    # same block sequence must yield the same alert stream from both
    # engines before any fused timing is trusted — then the fused
    # detector leg (the comparable to e2e_stages.detector_rows_per_sec:
    # same blocks, same single-shard detector state, but ONE fused
    # dispatch with reused staging buffers instead of two dispatches +
    # two fetches per block) and the fused end-to-end ingest number.
    # THEIA_BENCH_FAST=1 runs only the one-micro-batch parity smoke,
    # so a kernel regression fails fast without the full bench.
    fused_parity_ok = None
    fused_det_rate = 0.0
    sharded_det_2s = 0.0
    fused_e2e = 0.0
    try:
        import contextlib
        import gc as _fgc

        from theia_tpu.ingest import BlockEncoder as _FEnc
        from theia_tpu.ingest import TsvDecoder as _FDec
        from theia_tpu.ingest import native_available as _f_native
        from theia_tpu.manager.ingest import IngestManager as _FIm
        from theia_tpu.store import FlowDatabase as _FDb

        if _f_native():
            fast = os.environ.get("THEIA_BENCH_FAST") == "1"

            def cpu_ctx_f():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()

            cfgf = (SynthConfig(n_series=200, points_per_series=10)
                    if fast else
                    SynthConfig(n_series=2000, points_per_series=30))
            bigf = generate_flows(cfgf)
            encf = _FEnc(dicts=bigf.dicts)
            blocksf = [encf.encode(bigf)
                       for _ in range(3 if fast else 9)]
            decf = _FDec()
            batches = [decf.decode_block(p) for p in blocksf]

            def _strip(conn):
                return [{k: v for k, v in d.items()
                         if k != "latency_s"} for d in conn]

            with cpu_ctx_f():
                # parity gate — before any timed window
                im_s = _FIm(_FDb(), n_shards=4)
                im_f = _FIm(_FDb(), n_shards=4, engine="fused")
                fused_parity_ok = True
                for b in batches[:3]:
                    hs, cs, ns = im_s.score_batch(b)
                    hf, cf, nf = im_f.score_batch(b)
                    if not (hs == hf and ns == nf
                            and _strip(cs) == _strip(cf)):
                        fused_parity_ok = False
                im_f.close()
                im_s.close()
                print("fused engine parity: "
                      + ("ok" if fused_parity_ok else "MISMATCH"),
                      file=sys.stderr)
                _fgc.collect()

                if not fast and fused_parity_ok:
                    # Detector-leg comparison at the pipeline's design
                    # point: two concurrent producer streams (distinct
                    # flow populations), so double-buffered staging
                    # overlaps device scoring and coalescing can fold
                    # blocks — the same structure for both engines so
                    # the fused number is an apples win, not a
                    # measurement artifact. Sequential single-stream
                    # rates go to stderr for the record.
                    import threading as _fthr

                    stream_batches = []
                    for sid in range(2):
                        bs = generate_flows(SynthConfig(
                            n_series=2000, points_per_series=30,
                            seed=sid))
                        es = _FEnc(dicts=bs.dicts)
                        ds = _FDec()
                        stream_batches.append(
                            [ds.decode_block(es.encode(bs))
                             for _ in range(9)])
                    rows2 = sum(len(b) for st in stream_batches
                                for b in st[1:])

                    def det_leg(engine_name):
                        imd = _FIm(_FDb(), n_shards=2,
                                   engine=engine_name)
                        for st in stream_batches:   # warm jit + ring
                            imd.score_batch(st[0])
                        # sequential single-stream rate (diagnostic)
                        t0f = time.perf_counter()
                        for b in stream_batches[0][1:]:
                            imd.score_batch(b)
                        seq = (len(stream_batches[0][1:])
                               * len(stream_batches[0][0])
                               / (time.perf_counter() - t0f))

                        def feed(st):
                            for b in st[1:]:
                                imd.score_batch(b)
                        best = float("inf")
                        for _ in range(2):   # best-of-2 vs CPU steal
                            th = [_fthr.Thread(target=feed,
                                               args=(st,))
                                  for st in stream_batches]
                            t0f = time.perf_counter()
                            for t in th:
                                t.start()
                            for t in th:
                                t.join()
                            best = min(best,
                                       time.perf_counter() - t0f)
                        imd.close()
                        del imd
                        _fgc.collect()
                        return rows2 / best, seq

                    sharded_2s, sharded_seq = det_leg("sharded")
                    sharded_det_2s = sharded_2s
                    fused_det_rate, fused_seq = det_leg("fused")
                    print(f"fused detector leg (2 streams): "
                          f"{fused_det_rate:,.0f} rows/s vs sharded "
                          f"{sharded_2s:,.0f} rows/s "
                          f"[sequential: fused {fused_seq:,.0f}, "
                          f"sharded {sharded_seq:,.0f}; e2e-leg "
                          f"attribution "
                          f"{e2e_stages.get('detector_rows_per_sec', 0):,}]",
                          file=sys.stderr)

                    best = 0.0
                    for _ in range(2):
                        enc2 = _FEnc(dicts=bigf.dicts)
                        payloads = [enc2.encode(bigf)
                                    for _ in range(9)]
                        imf = _FIm(_FDb(ttl_seconds=12 * 3600),
                                   engine="fused")
                        imf.ingest(payloads[0])   # warm dicts + jit
                        t0f = time.perf_counter()
                        nf2 = sum(imf.ingest(p)["rows"]
                                  for p in payloads[1:])
                        best = max(best,
                                   nf2 / (time.perf_counter() - t0f))
                        imf.close()
                        del imf, payloads
                        _fgc.collect()
                    fused_e2e = best
                    print(f"fused e2e ingest: {best:,.0f} rows/s",
                          file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"fused bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Working-set state-tier legs (ingest/state_tier.py): ≥1M distinct
    # 5-tuples (FAST: 100k) with Zipf re-arrival driven through a
    # deliberately small hot-slot budget. The parity gate runs FIRST
    # and is the tier's whole contract: ZERO
    # theia_detector_series_dropped_total, hot occupancy never above
    # the budget, zero transient overflow, and an alert stream
    # bit-identical to an unbounded-slots oracle over the same input —
    # only then is the tiered detector's throughput timed.
    working_set_parity_ok = None
    working_set_rate = 0.0
    working_set_times: list = []
    try:
        import gc as _wgc

        from theia_tpu.analytics.streaming import (
            StreamingDetector as _WDet)
        from theia_tpu.ingest.state_tier import (
            TierConfig as _WCfg, WorkingSetTier as _WTier)
        from theia_tpu.schema import ColumnarBatch as _WBatch

        fast_ws = os.environ.get("THEIA_BENCH_FAST") == "1"
        n_keys = 100_000 if fast_ws else 1_000_000
        budget = 8_192 if fast_ws else 32_768
        batch_rows = 4_096 if fast_ws else 16_384
        rng_ws = np.random.default_rng(7)
        # every key appears at least once (a permutation), then a
        # Zipf-distributed re-arrival tail exercises promote-on-
        # re-arrival against the long tail
        idx_stream = np.concatenate([
            rng_ws.permutation(n_keys),
            rng_ws.zipf(1.3, size=n_keys // 2).astype(np.int64)
            % n_keys])
        vals_stream = rng_ws.random(len(idx_stream)) * 1e3

        def _ws_batch(lo, hi):
            ix = idx_stream[lo:hi]
            n = len(ix)
            return _WBatch({
                "sourceIP": ix.astype(np.int64),
                "sourceTransportPort": np.full(n, 1234, np.int64),
                "destinationIP": (ix * 7).astype(np.int64),
                "destinationTransportPort": np.full(n, 80, np.int64),
                "protocolIdentifier": np.full(n, 6, np.int64),
                "flowStartSeconds": np.full(n, 1, np.int64),
                "throughput": vals_stream[lo:hi],
                "flowEndSeconds": np.full(n, 100, np.int64),
            }, {})

        def _ws_strip(alerts):
            return sorted(
                tuple(sorted((k, v) for k, v in a.items()
                             if k not in ("latency_s", "slot", "row")))
                for a in alerts)

        def _ws_run(det, tier=None):
            drained = []
            for lo in range(0, len(idx_stream), batch_rows):
                drained.append(_ws_strip(
                    det.ingest(_ws_batch(lo, lo + batch_rows))))
                if tier is not None and tier.n_hot > budget:
                    raise AssertionError(
                        f"hot occupancy {tier.n_hot} > budget {budget}")
            return drained

        # parity gate — before any timed window
        tier_g = _WTier(_WCfg(hot_watermark=0.9, evict_to=0.7,
                              age_out_seconds=0.0))
        det_t = _WDet(capacity=budget, tier=tier_g)
        det_o = _WDet(capacity=n_keys + 64)
        a_t = _ws_run(det_t, tier_g)
        a_o = _ws_run(det_o)
        working_set_parity_ok = (
            a_t == a_o and det_t.dropped_series == 0
            and tier_g.overflow == 0 and tier_g.n_hot <= budget)
        print(f"working-set parity ({n_keys:,} keys, budget "
              f"{budget:,}): "
              + ("ok" if working_set_parity_ok else "MISMATCH")
              + f" [evictions {tier_g.evictions:,}, promotions "
              f"{tier_g.promotions_warm + tier_g.promotions_cold:,}]",
              file=sys.stderr)
        del det_t, det_o, tier_g, a_t, a_o
        _wgc.collect()

        if working_set_parity_ok:
            for _ in range(1 if fast_ws else 2):  # best-of-2 vs steal
                det_w = _WDet(capacity=budget, tier=_WTier(
                    _WCfg(hot_watermark=0.9, evict_to=0.7,
                          age_out_seconds=0.0)))
                det_w.ingest(_ws_batch(0, batch_rows))  # warm jit
                t0w = time.perf_counter()
                for lo in range(batch_rows, len(idx_stream),
                                batch_rows):
                    det_w.ingest(_ws_batch(lo, lo + batch_rows))
                working_set_times.append(time.perf_counter() - t0w)
                del det_w
                _wgc.collect()
            rows_w = len(idx_stream) - batch_rows
            working_set_rate = rows_w / min(working_set_times)
            print(f"working-set detector leg: "
                  f"{working_set_rate:,.0f} rows/s "
                  f"({n_keys:,} distinct keys through "
                  f"{budget:,} hot slots)", file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"working-set bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # TBLK zero-copy wire format vs TFB2 on the ACKED e2e path at
    # interval:1 durability (the PR-16 tentpole's design point: the
    # ack is WAL-journaled, and the TBLK body journals VERBATIM).
    # The timed windows run only behind a byte-parity gate: same
    # rows through both formats must produce byte-identical WAL
    # streams and identical alert content first — a fast wrong
    # pipeline must not report a speedup. THEIA_BENCH_FAST runs only
    # the parity gate.
    tblk_parity_ok = None
    tblk_e2e = 0.0
    tfb2_e2e = 0.0
    tblk_leg_times: list = []
    tfb2_leg_times: list = []
    try:
        import contextlib
        import gc as _tgc
        import tempfile as _ttmp

        from theia_tpu.ingest import BlockEncoder as _TEnc2
        from theia_tpu.ingest import TblkEncoder as _TEncB
        from theia_tpu.ingest import native_available as _t_native
        from theia_tpu.manager.ingest import IngestManager as _TIm
        from theia_tpu.store import FlowDatabase as _TDb
        from theia_tpu.store import wal as _twal

        if _t_native():
            fast_t = os.environ.get("THEIA_BENCH_FAST") == "1"

            def cpu_ctx_t():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()

            cfgt = (SynthConfig(n_series=200, points_per_series=10)
                    if fast_t else
                    SynthConfig(n_series=2000, points_per_series=30))
            bigt = generate_flows(cfgt)
            n_blocks = 3 if fast_t else 9

            def wal_bodies(db):
                db._wal.sync()
                frames, _l, algo = db._wal.read_frames(0)
                return [bytes(b) for (_, _, b)
                        in _twal.iter_frames(frames, algo)]

            def alert_canon(im):
                return [
                    {k: v for k, v in a.items()
                     if k not in ("time", "latency_s")}
                    for a in im.recent_alerts(10_000)]

            with cpu_ctx_t():
                # parity gate — before any timed window
                gate = {}
                for name, enc_cls in (("tblk", _TEncB),
                                      ("tfb2", _TEnc2)):
                    with _ttmp.TemporaryDirectory() as wd:
                        enc = enc_cls(dicts=bigt.dicts)
                        dbp = _TDb()
                        dbp.attach_wal(wd, sync="always")
                        imp = _TIm(dbp, n_shards=1)
                        for i in range(3):
                            imp.ingest(enc.encode(bigt),
                                       stream="parity", seq=i)
                        gate[name] = (wal_bodies(dbp),
                                      alert_canon(imp))
                        imp.close()
                        dbp.close_wal()
                        del imp, dbp
                        _tgc.collect()
                tblk_parity_ok = gate["tblk"] == gate["tfb2"]
                print("tblk/tfb2 byte parity (WAL stream + alerts): "
                      + ("ok" if tblk_parity_ok else "MISMATCH"),
                      file=sys.stderr)

                if not fast_t and tblk_parity_ok:
                    def e2e_wal_leg(enc_cls, leg_times):
                        # fresh db + WAL per pass: replaying into a
                        # grown store would measure a different
                        # pipeline; best-of-2 vs CPU steal
                        best = 0.0
                        for _ in range(2):
                            with _ttmp.TemporaryDirectory() as wd:
                                enc = enc_cls(dicts=bigt.dicts)
                                payloads = [enc.encode(bigt)
                                            for _ in range(n_blocks)]
                                dbw = _TDb(ttl_seconds=12 * 3600)
                                dbw.attach_wal(wd, sync="interval:1")
                                imw = _TIm(dbw)
                                imw.ingest(payloads[0],
                                           stream="b", seq=0)
                                t0t = time.perf_counter()
                                nw = sum(
                                    imw.ingest(p, stream="b",
                                               seq=1 + i)["rows"]
                                    for i, p in
                                    enumerate(payloads[1:]))
                                dtw = time.perf_counter() - t0t
                                leg_times.append(dtw)
                                best = max(best, nw / dtw)
                                imw.close()
                                dbw.close_wal()
                                del imw, dbw, payloads
                                _tgc.collect()
                        return best

                    tblk_e2e = e2e_wal_leg(_TEncB, tblk_leg_times)
                    tfb2_e2e = e2e_wal_leg(_TEnc2, tfb2_leg_times)
                    print(f"tblk e2e ingest (acked, WAL interval:1): "
                          f"{tblk_e2e:,.0f} rows/s vs tfb2 "
                          f"{tfb2_e2e:,.0f} rows/s "
                          f"({tblk_e2e / max(tfb2_e2e, 1e-9):.2f}x)",
                          file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"tblk bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Instrumentation overhead: the full IngestManager path with the
    # obs plane DISABLED vs ENABLED (THEIA_METRICS_DISABLED's runtime
    # switch), so the <3% overhead budget of the metrics subsystem is
    # tracked release-over-release instead of assumed.
    metrics_rate = 0.0
    metrics_overhead_pct = None
    try:
        import contextlib

        from theia_tpu.ingest import BlockEncoder, native_available
        from theia_tpu.manager.ingest import IngestManager
        from theia_tpu.obs import metrics as obs_metrics
        from theia_tpu.store import FlowDatabase

        if native_available():
            def cpu_ctx_m():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()
            bigm = generate_flows(SynthConfig(n_series=2000,
                                              points_per_series=30))

            def ingest_pass():
                imm = IngestManager(FlowDatabase(ttl_seconds=12 * 3600))
                encm = BlockEncoder(dicts=bigm.dicts)
                payloads = [encm.encode(bigm) for _ in range(9)]
                imm.ingest(payloads[0])   # warm dicts + jit
                tm = time.perf_counter()
                n = sum(imm.ingest(p)["rows"] for p in payloads[1:])
                dtm = time.perf_counter() - tm
                imm.close()
                return n / dtm

            # INTERLEAVED best-of-3 per mode: consecutive same-mode
            # passes would fold slow host drift (CPU steal, thermal)
            # into the A/B difference and report it as overhead.
            rates = {"disabled": 0.0, "enabled": 0.0}
            with cpu_ctx_m():
                try:
                    for _ in range(3):
                        obs_metrics.disable()
                        rates["disabled"] = max(rates["disabled"],
                                                ingest_pass())
                        obs_metrics.enable()
                        rates["enabled"] = max(rates["enabled"],
                                               ingest_pass())
                finally:
                    obs_metrics.enable()
            metrics_rate = rates["enabled"]
            if rates["disabled"] > 0:
                metrics_overhead_pct = round(
                    (rates["disabled"] - rates["enabled"])
                    / rates["disabled"] * 100, 2)
            print(f"ingest with metrics: {metrics_rate:,.0f} rows/s "
                  f"(disabled: {rates['disabled']:,.0f}; overhead "
                  f"{metrics_overhead_pct}%)", file=sys.stderr)
    except Exception as e:
        print(f"metrics-overhead bench skipped: {e}", file=sys.stderr)

    # Distributed-tracing overhead: the SAME IngestManager A/B shape
    # as the metrics leg, flipping THEIA_TRACE_SAMPLE 0 ↔ 1 — with
    # sampling off no trace context is minted and no header ships, so
    # the delta is the whole cost of sampled tracing on the e2e
    # ingest path (the parity budget: within host noise, ≪ 3%).
    tracing_overhead_pct = None
    try:
        import contextlib

        from theia_tpu.ingest import BlockEncoder, native_available
        from theia_tpu.manager.ingest import IngestManager
        from theia_tpu.store import FlowDatabase

        if native_available():
            def cpu_ctx_t():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()
            bigt = generate_flows(SynthConfig(n_series=2000,
                                              points_per_series=30))

            def trace_pass():
                imt = IngestManager(FlowDatabase(ttl_seconds=12 * 3600))
                enct = BlockEncoder(dicts=bigt.dicts)
                payloads = [enct.encode(bigt) for _ in range(9)]
                imt.ingest(payloads[0])   # warm dicts + jit
                tt = time.perf_counter()
                n = sum(imt.ingest(p)["rows"] for p in payloads[1:])
                dtt = time.perf_counter() - tt
                imt.close()
                return n / dtt

            saved_sample = os.environ.get("THEIA_TRACE_SAMPLE")
            trates = {"off": 0.0, "sampled": 0.0}
            try:
                with cpu_ctx_t():
                    # interleaved best-of-3 (the metrics-leg rationale:
                    # host drift must not masquerade as overhead)
                    for _ in range(3):
                        os.environ["THEIA_TRACE_SAMPLE"] = "0"
                        trates["off"] = max(trates["off"],
                                            trace_pass())
                        os.environ["THEIA_TRACE_SAMPLE"] = "1"
                        trates["sampled"] = max(trates["sampled"],
                                                trace_pass())
            finally:
                if saved_sample is None:
                    os.environ.pop("THEIA_TRACE_SAMPLE", None)
                else:
                    os.environ["THEIA_TRACE_SAMPLE"] = saved_sample
            if trates["off"] > 0:
                tracing_overhead_pct = round(
                    (trates["off"] - trates["sampled"])
                    / trates["off"] * 100, 2)
            print(f"ingest with sampled tracing: "
                  f"{trates['sampled']:,.0f} rows/s "
                  f"(tracing off: {trates['off']:,.0f}; overhead "
                  f"{tracing_overhead_pct}%)", file=sys.stderr)
    except Exception as e:
        print(f"tracing-overhead bench skipped: {e}", file=sys.stderr)

    # Lockdep-witness overhead: the SAME IngestManager A/B shape,
    # flipping THEIA_LOCKDEP 0 <-> 1 around CONSTRUCTION (the witness
    # decision is made at lock creation, so each pass builds a fresh
    # engine; module-level locks keep whatever the process was born
    # with — instance locks dominate the ingest path, and the leg
    # honestly measures the armed-in-this-process cost an operator
    # pays turning the witness on for a deadlock hunt). Budget: <=3%
    # — the witness is a test-time gate, but it must stay cheap
    # enough to arm in production. THEIA_BENCH_FAST runs one
    # interleave instead of three.
    lockdep_rate = 0.0
    lockdep_overhead_pct = None
    lockdep_times = {"off": [], "on": []}
    try:
        import contextlib

        from theia_tpu.ingest import BlockEncoder, native_available
        from theia_tpu.manager.ingest import IngestManager
        from theia_tpu.store import FlowDatabase

        if native_available():
            def cpu_ctx_l():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return contextlib.nullcontext()
            bigl = generate_flows(SynthConfig(n_series=2000,
                                              points_per_series=30))

            def lockdep_pass():
                iml = IngestManager(FlowDatabase(ttl_seconds=12 * 3600))
                encl = BlockEncoder(dicts=bigl.dicts)
                payloads = [encl.encode(bigl) for _ in range(9)]
                iml.ingest(payloads[0])   # warm dicts + jit
                tl = time.perf_counter()
                n = sum(iml.ingest(p)["rows"] for p in payloads[1:])
                dtl = time.perf_counter() - tl
                iml.close()
                return n / dtl, dtl

            saved_ld = os.environ.get("THEIA_LOCKDEP")
            lrates = {"off": 0.0, "on": 0.0}
            iters = (1 if os.environ.get("THEIA_BENCH_FAST") == "1"
                     else 3)
            try:
                with cpu_ctx_l():
                    # interleaved best-of-N with ALTERNATING order:
                    # a fixed off-then-on order folds first-pass
                    # warm-up (allocator, caches) into the SAME side
                    # every interleave and reads as a systematic
                    # bias, not noise — alternation cancels it
                    for i in range(iters):
                        order = ("0", "1") if i % 2 == 0 else ("1",
                                                               "0")
                        for mode in order:
                            os.environ["THEIA_LOCKDEP"] = mode
                            r, dt = lockdep_pass()
                            key = "on" if mode == "1" else "off"
                            lrates[key] = max(lrates[key], r)
                            lockdep_times[key].append(dt)
            finally:
                if saved_ld is None:
                    os.environ.pop("THEIA_LOCKDEP", None)
                else:
                    os.environ["THEIA_LOCKDEP"] = saved_ld
            lockdep_rate = lrates["on"]
            if lrates["off"] > 0:
                lockdep_overhead_pct = round(
                    (lrates["off"] - lrates["on"])
                    / lrates["off"] * 100, 2)
            print(f"ingest with lockdep witness: "
                  f"{lockdep_rate:,.0f} rows/s "
                  f"(witness off: {lrates['off']:,.0f}; overhead "
                  f"{lockdep_overhead_pct}%)", file=sys.stderr)
    except Exception as e:
        print(f"lockdep-overhead bench skipped: {e}", file=sys.stderr)

    # WAL durability tax: e2e ingest throughput (the acceptance
    # surface — decode ∥ store+WAL ∥ detector, where spare cores can
    # absorb the journaling) per sync policy vs the WAL-off baseline,
    # plus bare store-insert rates (the worst case: nothing overlaps)
    # and replay throughput (how fast a crash recovers). Interleaved
    # best-of-3 per mode, same rationale as the metrics A/B:
    # consecutive same-mode passes fold host drift into the
    # difference.
    wal_rates = {}
    wal_store_rates = {}
    wal_recovery = 0.0
    try:
        import shutil
        import tempfile

        from theia_tpu.ingest import BlockEncoder as _WalEnc
        from theia_tpu.manager.ingest import IngestManager as _WalIm
        from theia_tpu.store import FlowDatabase as _WalDb

        bigw = generate_flows(SynthConfig(n_series=2000,
                                          points_per_series=30))

        def wal_store_pass(sync):
            tmpd = tempfile.mkdtemp(prefix="theia-wal-bench-")
            try:
                dbw = _WalDb(ttl_seconds=12 * 3600)
                if sync is not None:
                    dbw.attach_wal(os.path.join(tmpd, "wal"),
                                   sync=sync)
                dbw.insert_flows(bigw)   # warm adopt caches + jit
                tw = time.perf_counter()
                n = sum(dbw.insert_flows(bigw) for _ in range(8))
                dtw = time.perf_counter() - tw
                if sync is not None:
                    dbw.close_wal()
                return n / dtw
            finally:
                shutil.rmtree(tmpd, ignore_errors=True)

        def wal_e2e_pass(sync):
            tmpd = tempfile.mkdtemp(prefix="theia-wal-bench-")
            try:
                dbw = _WalDb(ttl_seconds=12 * 3600)
                if sync is not None:
                    dbw.attach_wal(os.path.join(tmpd, "wal"),
                                   sync=sync)
                imw = _WalIm(dbw)
                encw = _WalEnc(dicts=bigw.dicts)
                payloads = [encw.encode(bigw) for _ in range(9)]
                imw.ingest(payloads[0])   # warm dicts + jit
                tw = time.perf_counter()
                n = sum(imw.ingest(p)["rows"] for p in payloads[1:])
                dtw = time.perf_counter() - tw
                imw.close()
                if sync is not None:
                    dbw.close_wal()
                return n / dtw
            finally:
                shutil.rmtree(tmpd, ignore_errors=True)

        modes = [None, "never", "interval:1", "always"]
        best_e2e = {m: 0.0 for m in modes}
        best_store = {m: 0.0 for m in modes}
        for _ in range(3):
            for m in modes:
                best_e2e[m] = max(best_e2e[m], wal_e2e_pass(m))
                best_store[m] = max(best_store[m], wal_store_pass(m))
        wal_rates = {("off" if m is None else m): round(best_e2e[m])
                     for m in modes}
        wal_store_rates = {("off" if m is None else m):
                           round(best_store[m]) for m in modes}
        if best_e2e[None] > 0:
            wal_rates["interval1_overhead_pct"] = round(
                (best_e2e[None] - best_e2e["interval:1"])
                / best_e2e[None] * 100, 2)
        print("wal e2e ingest: " + ", ".join(
            f"{k} {v:,}" for k, v in wal_rates.items()),
            file=sys.stderr)
        print("wal store insert: " + ", ".join(
            f"{k} {v:,}" for k, v in wal_store_rates.items()),
            file=sys.stderr)

        tmpd = tempfile.mkdtemp(prefix="theia-wal-bench-")
        try:
            dbw = _WalDb()
            dbw.attach_wal(os.path.join(tmpd, "wal"), sync="never")
            for _ in range(8):
                dbw.insert_flows(bigw)
            dbw.wal_sync()
            dbw.close_wal()
            db2 = _WalDb()
            tr = time.perf_counter()
            st_rec = db2.attach_wal(os.path.join(tmpd, "wal"),
                                    sync="never")
            dtr = time.perf_counter() - tr
            wal_recovery = int(st_rec["recoveredRows"]) / dtr
            db2.close_wal()
            print(f"wal recovery: {wal_recovery:,.0f} rows/s "
                  f"({st_rec['recoveredRows']} rows replayed)",
                  file=sys.stderr)
        finally:
            shutil.rmtree(tmpd, ignore_errors=True)
    except Exception as e:
        print(f"wal bench skipped: {e}", file=sys.stderr)

    # Part-based storage engine (THEIA_STORE_ENGINE=parts): insert
    # throughput (seal/encode amortized on the ingest path), resident
    # bytes/row vs the flat engine's raw 284, min/max-pruned window
    # selects vs the flat full-scan+mask, and manifest-based recovery
    # vs wholesale snapshot recovery. The PARITY GATE runs before any
    # timed window (PR 6 playbook): byte-identical scan + pruned
    # select vs flat, or the legs don't report. THEIA_BENCH_FAST runs
    # a one-part smoke (parity + a single timed insert window).
    parts_bench: dict = {}
    parts_parity_ok = None
    try:
        import shutil
        import tempfile

        from theia_tpu.schema import ColumnarBatch as _PCB
        from theia_tpu.schema import FLOW_SCHEMA as _PSchema
        from theia_tpu.store import FlowDatabase as _PDb

        fastp = os.environ.get("THEIA_BENCH_FAST") == "1"
        n_windows = 1 if fastp else 12
        basep = generate_flows(SynthConfig(n_series=2000,
                                           points_per_series=30))

        def _shifted(i):
            cols = dict(basep.columns)
            for c in ("timeInserted", "flowStartSeconds",
                      "flowEndSeconds"):
                cols[c] = basep[c] + i * 3600
            return _PCB(cols, basep.dicts)

        windows = [_shifted(i) for i in range(n_windows)]
        t_lo = int(windows[0]["flowStartSeconds"].min())

        def _scan_equal(a, b) -> bool:
            if len(a) != len(b):
                return False
            for c in _PSchema:
                if not np.array_equal(np.asarray(a[c.name]),
                                      np.asarray(b[c.name])):
                    return False
                if c.is_string and not np.array_equal(
                        a.strings(c.name), b.strings(c.name)):
                    return False
            return True

        flatdb = _PDb(engine="flat")
        partsdb = _PDb(engine="parts")
        for w in windows:
            flatdb.insert_flows(w)
            partsdb.insert_flows(w)
        partsdb.flows.seal()
        # parity gate — before any timed window
        parts_parity_ok = _scan_equal(flatdb.flows.scan(),
                                      partsdb.flows.scan())
        if parts_parity_ok:
            sel_f = flatdb.flows.select(start_time=t_lo,
                                        end_time=t_lo + 1800)
            sel_p = partsdb.flows.select(start_time=t_lo,
                                         end_time=t_lo + 1800)
            parts_parity_ok = _scan_equal(sel_f, sel_p)
        print("parts engine parity: "
              + ("ok" if parts_parity_ok else "MISMATCH"),
              file=sys.stderr)
        if parts_parity_ok:
            n_rows = len(flatdb.flows)
            parts_bench["store_parts_bytes_per_row"] = round(
                partsdb.flows.nbytes / n_rows, 1)
            parts_bench["store_flat_bytes_per_row"] = round(
                flatdb.flows.nbytes / n_rows, 1)

            # insert throughput (includes seal + encode), best-of-3
            best_ins = 0.0
            for _ in range(1 if fastp else 3):
                dbi = _PDb(engine="parts")
                dbi.insert_flows(windows[0])   # warm adopt caches
                ti = time.perf_counter()
                n = sum(dbi.insert_flows(w) for w in windows)
                best_ins = max(best_ins,
                               n / (time.perf_counter() - ti))
            parts_bench["store_parts_insert_rows_per_sec"] = round(
                best_ins)

            # pruned out-of-window select vs flat full-scan+mask
            sel_args = dict(start_time=t_lo - 7200,
                            end_time=t_lo - 3600)
            best_f = best_p = float("inf")
            for _ in range(3):
                ts = time.perf_counter()
                flatdb.flows.select(**sel_args)
                best_f = min(best_f, time.perf_counter() - ts)
                ts = time.perf_counter()
                partsdb.flows.select(**sel_args)
                best_p = min(best_p, time.perf_counter() - ts)
            if best_p > 0:
                parts_bench["store_parts_select_pruned_vs_flat"] = \
                    round(best_f / best_p, 1)

            # recovery: manifest + WAL tail vs wholesale snapshot
            tmpp = tempfile.mkdtemp(prefix="theia-parts-bench-")
            try:
                dbr = _PDb(engine="parts",
                           parts_dir=os.path.join(tmpp, "parts"))
                dbr.attach_wal(os.path.join(tmpp, "wal"),
                               sync="never")
                for w in windows:
                    dbr.insert_flows(w)
                dbr.save(os.path.join(tmpp, "db.npz"))
                dbr.wal_sync()
                dbr.close_wal()
                # two honest numbers: time-to-SERVING (manifest
                # registered lazily + WAL tail — inserts ack, pruned
                # selects run; the parts engine's headline) and
                # time-to-full-materialization (forced whole-table
                # scan — the work-comparable figure vs the flat
                # engine, which materializes during load by
                # construction; both sides pay the scan). Best-of-2
                # like the other legs: a single pass is dominated by
                # host noise on a 2-core box.
                flatdb.save(os.path.join(tmpp, "flat.npz"))
                dt_parts = dt_parts_scan = float("inf")
                dt_flat = dt_flat_scan = float("inf")
                rows_rec = 0
                for _ in range(1 if fastp else 2):
                    tr = time.perf_counter()
                    db2 = _PDb.load(os.path.join(tmpp, "db.npz"))
                    db2.attach_wal(os.path.join(tmpp, "wal"),
                                   sync="never")
                    dt_parts = min(dt_parts,
                                   time.perf_counter() - tr)
                    rows_rec = len(db2.flows.scan())
                    dt_parts_scan = min(dt_parts_scan,
                                        time.perf_counter() - tr)
                    db2.close_wal()
                    tr = time.perf_counter()
                    db3 = _PDb.load(os.path.join(tmpp, "flat.npz"),
                                    engine="flat")
                    dt_flat = min(dt_flat, time.perf_counter() - tr)
                    assert len(db3.flows.scan()) == rows_rec
                    dt_flat_scan = min(dt_flat_scan,
                                       time.perf_counter() - tr)
                parts_bench["store_parts_recovery_rows_per_sec"] = \
                    round(rows_rec / dt_parts)
                parts_bench["store_parts_recovery_scan_rows_per_sec"] \
                    = round(rows_rec / dt_parts_scan)
                parts_bench["store_snapshot_recovery_rows_per_sec"] \
                    = round(rows_rec / dt_flat)
                parts_bench[
                    "store_snapshot_recovery_scan_rows_per_sec"] = \
                    round(rows_rec / dt_flat_scan)
            finally:
                shutil.rmtree(tmpp, ignore_errors=True)
            print("parts engine: " + ", ".join(
                f"{k.replace('store_', '')} {v:,}"
                for k, v in parts_bench.items()), file=sys.stderr)
    except Exception as e:
        print(f"parts bench skipped: {e}", file=sys.stderr)

    # Vectorized query engine over column parts (PR 8,
    # theia_tpu/query/): filtered group-by aggregation running
    # part-NATIVE (pruned, encoded-space filters, late-materializing
    # group keys) vs the decode-then-aggregate baseline (scan() to
    # table code space + the reference executor — what a job would
    # do). The query_parity_ok gate (parts engine == flat engine ==
    # pure-numpy reference, bit for bit) runs before ANY timed
    # window; legs: group-sum rows/s vs baseline, pruned-window
    # speedup, cold-tier scan rate (with a no-promotion check), and
    # cache-hit latency. THEIA_BENCH_FAST runs a one-window smoke.
    query_bench: dict = {}
    #: per-leg {bestMs, medianMs, spreadPct} for multi-iteration timed
    #: legs — lands in the --out artifact under result.leg_stats
    leg_stats: dict = {}
    query_parity_ok = None
    try:
        import shutil
        import tempfile

        from theia_tpu.query import (QueryEngine, parse_plan,
                                     reference_execute)
        from theia_tpu.schema import ColumnarBatch as _QCB
        from theia_tpu.store import FlowDatabase as _QDb

        fastq = os.environ.get("THEIA_BENCH_FAST") == "1"
        nq_windows = 1 if fastq else 12
        baseq = generate_flows(SynthConfig(n_series=2000,
                                           points_per_series=30))

        def _q_shifted(i):
            cols = dict(baseq.columns)
            for c in ("timeInserted", "flowStartSeconds",
                      "flowEndSeconds"):
                cols[c] = baseq[c] + i * 3600
            return _QCB(cols, baseq.dicts)

        qwindows = [_q_shifted(i) for i in range(nq_windows)]
        qflat = _QDb(engine="flat")
        qparts = _QDb(engine="parts")
        for w in qwindows:
            qflat.insert_flows(w)
            qparts.insert_flows(w)
        qparts.flows.seal()
        n_qrows = len(qflat.flows)
        q_lo = int(qwindows[0]["flowStartSeconds"].min())
        groupsum = parse_plan({
            "groupBy": "sourceIP",
            "aggregates": ["sum:octetDeltaCount", "count"], "k": 0})
        windowed = parse_plan({
            "groupBy": "sourceIP,destinationIP",
            "aggregates": ["sum:octetDeltaCount", "mean:throughput"],
            "start": q_lo, "end": q_lo + 1800,
            "filters": [{"column": "destinationTransportPort",
                         "op": ">=", "value": 1}], "k": 10})
        eng_p = QueryEngine(qparts)
        eng_f = QueryEngine(qflat)

        # parity gate — before any timed window
        query_parity_ok = True
        for qp in (groupsum, windowed):
            rp = eng_p.execute(qp, use_cache=False)
            rf = eng_f.execute(qp, use_cache=False)
            rref, gref, _ = reference_execute(
                qp, qflat.flows.scan(), qflat.flows.dicts)
            if not (rp["rows"] == rf["rows"] == rref
                    and rp["groupCount"] == rf["groupCount"] == gref):
                query_parity_ok = False
        print("query engine parity: "
              + ("ok" if query_parity_ok else "MISMATCH"),
              file=sys.stderr)
        if query_parity_ok:
            # group-sum through the engine vs decode-then-aggregate
            iters = 1 if fastq else 3
            t_q: list = []
            t_base: list = []
            for _ in range(iters):
                tq = time.perf_counter()
                eng_p.execute(groupsum, use_cache=False)
                t_q.append(time.perf_counter() - tq)
                tq = time.perf_counter()
                reference_execute(groupsum, qparts.flows.scan(),
                                  qparts.flows.dicts)
                t_base.append(time.perf_counter() - tq)
            best_q, best_base = min(t_q), min(t_base)
            leg_stats["query_groupsum"] = _leg_stats(t_q)
            leg_stats["query_baseline"] = _leg_stats(t_base)
            query_bench["query_groupsum_rows_per_sec"] = round(
                n_qrows / best_q)
            query_bench["query_baseline_rows_per_sec"] = round(
                n_qrows / best_base)
            query_bench["query_groupsum_vs_baseline"] = round(
                best_base / best_q, 1)

            # pruned narrow window vs the same query decoded
            t_qw: list = []
            t_bw: list = []
            for _ in range(iters):
                tq = time.perf_counter()
                eng_p.execute(windowed, use_cache=False)
                t_qw.append(time.perf_counter() - tq)
                tq = time.perf_counter()
                reference_execute(windowed, qparts.flows.scan(),
                                  qparts.flows.dicts)
                t_bw.append(time.perf_counter() - tq)
            best_qw, best_bw = min(t_qw), min(t_bw)
            leg_stats["query_pruned_window"] = _leg_stats(t_qw)
            if best_qw > 0:
                query_bench["query_pruned_window_speedup"] = round(
                    best_bw / best_qw, 1)

            # cold tier: demote everything, re-run group-sum through
            # the column-subset streaming path; the tier must not move
            tmpq = tempfile.mkdtemp(prefix="theia-query-bench-")
            try:
                qcold = _QDb(engine="parts",
                             parts_dir=os.path.join(tmpq, "parts"))
                for w in qwindows:
                    qcold.insert_flows(w)
                qcold.flows.seal()
                qcold.flows.demote_oldest(0)
                before_hot = qcold.flows.parts_stats()["hotBytes"]
                eng_c = QueryEngine(qcold)
                rc = eng_c.execute(groupsum, use_cache=False)
                best_c = float("inf")
                for _ in range(iters):
                    tq = time.perf_counter()
                    eng_c.execute(groupsum, use_cache=False)
                    best_c = min(best_c, time.perf_counter() - tq)
                after_hot = qcold.flows.parts_stats()["hotBytes"]
                query_bench["query_cold_tier_rows_per_sec"] = round(
                    n_qrows / best_c)
                query_bench["query_cold_no_promotion_ok"] = (
                    before_hot == after_hot == 0)
                if rc["rows"] != eng_p.execute(
                        groupsum, use_cache=False)["rows"]:
                    query_parity_ok = False
            finally:
                shutil.rmtree(tmpq, ignore_errors=True)

            # cache hit latency (same plan, unchanged fingerprint)
            eng_p.cache.clear()
            eng_p.execute(groupsum)
            hits = []
            for _ in range(5 if fastq else 20):
                tq = time.perf_counter()
                out = eng_p.execute(groupsum)
                hits.append(time.perf_counter() - tq)
                assert out["cache"] == "hit"
            query_bench["query_cache_hit_ms"] = round(
                sorted(hits)[len(hits) // 2] * 1e3, 3)
            leg_stats["query_cache_hit"] = _leg_stats(hits)

            # Sort-ordered parts + skip indexes (PR 12): a SELECTIVE
            # NON-TIME predicate (one tail destinationIP out of tens
            # of thousands) under a window covering the whole store —
            # the sparse primary index (destination-leading sort key)
            # prunes to a single granule — vs the identical rows in
            # unsorted v1 parts, which must scan everything in the
            # window (the pre-PR-12 behavior, reachable via
            # sort_key=""). Parity (sorted engine == unsorted engine
            # == pure-numpy reference) gates the timed windows;
            # ROADMAP item 2 targets >= 10x on this leg. Store size
            # matters here: the unsorted side scales linearly with
            # retention while the indexed side stays at per-query
            # fixed cost + one granule, so the leg uses a 1.2M-row
            # store (the earlier legs' 60k rows would mostly measure
            # the shared per-query overhead).
            sel_series = 2000 if fastq else 24000
            sel_points = 25 if fastq else 50
            sel_base = generate_flows(SynthConfig(
                n_series=sel_series, points_per_series=sel_points))
            db_sorted = _QDb(engine="parts", parts_config={
                "sort_key": "destinationIP,sourceIP,timeInserted",
                "granule_rows": 512,
                "memtable_rows": 1 << 22})
            db_unsorted = _QDb(engine="parts", parts_config={
                "sort_key": "",
                "memtable_rows": 1 << 22})
            for d in (db_sorted, db_unsorted):
                d.insert_flows(sel_base)
            db_sorted.flows.seal()
            db_unsorted.flows.seal()
            n_sel = len(db_sorted.flows)
            # the least frequent destination, straight from the synth
            # batch (a table scan here would decode 1.2M rows just to
            # pick the filter value) — "selective" must mean a tail
            # value, not the synth mix's heavy hitter
            import numpy as _np
            sel_codes, sel_counts = _np.unique(
                _np.asarray(sel_base["destinationIP"]),
                return_counts=True)
            dst = sel_base.dicts["destinationIP"].decode_one(
                int(sel_codes[_np.argmin(sel_counts)]))
            selective = parse_plan({
                "groupBy": "sourceIP",
                "aggregates": ["sum:octetDeltaCount", "count"],
                "start": int(sel_base["flowStartSeconds"].min()),
                "end": int(sel_base["flowEndSeconds"].max()) + 1,
                "filters": [{"column": "destinationIP", "op": "eq",
                             "value": dst}],
                "k": 0})
            eng_s = QueryEngine(db_sorted)
            eng_u = QueryEngine(db_unsorted)
            rs = eng_s.execute(selective, use_cache=False)
            ru = eng_u.execute(selective, use_cache=False)
            rref_s, gref_s, _ = reference_execute(
                selective, db_unsorted.flows.scan(),
                db_unsorted.flows.dicts)
            if not (rs["rows"] == ru["rows"] == rref_s
                    and rs["groupCount"] == gref_s):
                query_parity_ok = False
                print("selective-predicate parity: MISMATCH",
                      file=sys.stderr)
            else:
                sel_iters = 2 if fastq else 7
                t_sorted: list = []
                t_scan: list = []
                for _ in range(sel_iters):
                    tq = time.perf_counter()
                    eng_s.execute(selective, use_cache=False)
                    t_sorted.append(time.perf_counter() - tq)
                    tq = time.perf_counter()
                    eng_u.execute(selective, use_cache=False)
                    t_scan.append(time.perf_counter() - tq)
                best_s, best_u = min(t_sorted), min(t_scan)
                leg_stats["query_selective_predicate"] = \
                    _leg_stats(t_sorted)
                leg_stats["query_selective_scan"] = \
                    _leg_stats(t_scan)
                query_bench[
                    "query_selective_predicate_rows_per_sec"] = \
                    round(n_sel / best_s)
                query_bench["query_selective_scan_rows_per_sec"] = \
                    round(n_sel / best_u)
                query_bench["query_selective_predicate_speedup"] = \
                    round(best_u / best_s, 1)
                query_bench["query_selective_granules_skipped"] = \
                    int(rs.get("granulesSkipped") or 0)

            print("query engine: " + ", ".join(
                f"{k.replace('query_', '')} {v:,}"
                if isinstance(v, (int, float)) else f"{k} {v}"
                for k, v in query_bench.items()), file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"query bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Rollup views (PR 14): (A) the dashboard-speedup leg — a
    # long-window group-by answered from rollup tiers (1h folds over
    # cold history) vs the SAME plan forced down the raw cold-scan
    # path (`rollup=0`), parity-gated against the reference oracle
    # before any timed window; (B) the maintenance-overhead leg — A/B
    # ingest into identical parts stores with one declared view vs
    # the rollup plane inactive. THEIA_BENCH_FAST runs a one-view,
    # one-window smoke.
    rollup_bench: dict = {}
    rollup_parity_ok = None
    try:
        import json as _ru_json
        import shutil as _ru_shutil
        import tempfile as _ru_tempfile

        from theia_tpu.query import QueryEngine as _RuEng
        from theia_tpu.query import parse_plan as _ru_parse
        from theia_tpu.query import reference_execute as _ru_ref
        from theia_tpu.schema import ColumnarBatch as _RuCB
        from theia_tpu.store import FlowDatabase as _RuDb

        fast_ru = os.environ.get("THEIA_BENCH_FAST") == "1"
        ru_tmp = _ru_tempfile.mkdtemp(prefix="theia-rollup-bench-")
        ru_cfg = os.path.join(ru_tmp, "views.json")
        with open(ru_cfg, "w") as f:
            _ru_json.dump({"views": [{
                "name": "bench_per_source",
                "groupBy": ["sourceIP"],
                "aggregates": ["count", "sum:octetDeltaCount",
                               "mean:throughput"],
                "bucketSeconds": 60,
                "tiers": [{"resolutionSeconds": 3600,
                           "afterSeconds": 21600}],
            }]}, f)
        ru_saved = {k: os.environ.get(k) for k in
                    ("THEIA_ROLLUP_VIEWS", "THEIA_ROLLUP_DEFAULTS")}

        def _ru_env(on: bool) -> None:
            if on:
                os.environ["THEIA_ROLLUP_VIEWS"] = ru_cfg
            else:
                os.environ.pop("THEIA_ROLLUP_VIEWS", None)
            os.environ["THEIA_ROLLUP_DEFAULTS"] = "0"

        try:
            ru_base = generate_flows(SynthConfig(
                n_series=600 if fast_ru else 2000,
                points_per_series=30))
            ru_windows = 2 if fast_ru else 36
            ru_t0 = int(ru_base["timeInserted"].min())

            def _ru_shifted(i):
                # one hour of dashboard-shaped history per block:
                # timeInserted spread uniformly across the hour (the
                # synth generator clusters it in ~30 s, which would
                # leave 59 of 60 buckets empty)
                cols = dict(ru_base.columns)
                for c in ("flowStartSeconds", "flowEndSeconds"):
                    cols[c] = ru_base[c] + i * 3600
                rng = np.random.default_rng(1234 + i)
                cols["timeInserted"] = np.sort(rng.integers(
                    ru_t0 + i * 3600, ru_t0 + (i + 1) * 3600,
                    len(ru_base))).astype(np.int64)
                return _RuCB(cols, ru_base.dicts)

            ru_blocks = [_ru_shifted(i) for i in range(ru_windows)]

            # (A) dashboard speedup: cold month-shaped history,
            # folded to 1h tiers, one long unaligned window
            _ru_env(True)
            ru_db = _RuDb(engine="parts",
                          parts_dir=os.path.join(ru_tmp, "parts"))
            for b in ru_blocks:
                ru_db.insert_flows(b)
            ru_db.flows.seal()
            ru_lo = int(ru_blocks[0]["timeInserted"].min())
            ru_hi = int(ru_blocks[-1]["timeInserted"].max())
            # fold history older than 6h to 1h tiers (the realistic
            # cascade state: old coarse, recent at base resolution),
            # then demote all but the freshest ~10% of raw parts so
            # the forced-raw path pays the cold scans a month-scale
            # dashboard would while the ragged `now` edge stays hot
            ru_db.rollups.maintain(now=ru_hi + 60)
            ru_db.flows.demote_oldest(ru_db.flows.nbytes // 10)
            ru_eng = _RuEng(ru_db)
            # parity gate FIRST, on a fully-ragged window (stitched
            # head AND tail edges), against the forced-raw path and
            # the reference oracle
            gate_plan = _ru_parse({
                "groupBy": "sourceIP",
                "aggregates": ["count", "sum:octetDeltaCount",
                               "mean:throughput"],
                "start": ru_lo + 37, "end": ru_hi - 41,
                "timeColumn": "timeInserted",
                "endColumn": "timeInserted", "k": 0})
            served = ru_eng.execute(gate_plan, use_cache=False)
            forced = ru_eng.execute(gate_plan, use_cache=False,
                                    use_rollup=False)
            rrows, rgroups, _ = _ru_ref(gate_plan, ru_db.flows.scan(),
                                        ru_db.flows.dicts)
            rollup_parity_ok = bool(
                served.get("rollup")
                and served["rows"] == forced["rows"] == rrows
                and served["groupCount"] == rgroups)
            print("rollup parity: "
                  + ("ok" if rollup_parity_ok else "MISMATCH"),
                  file=sys.stderr)
            # the timed dashboard shape: hour-aligned start (a "last
            # N hours" panel), ragged `now` end
            ru_plan = _ru_parse({
                "groupBy": "sourceIP",
                "aggregates": ["count", "sum:octetDeltaCount",
                               "mean:throughput"],
                "start": ru_t0 // 3600 * 3600, "end": ru_hi - 41,
                "timeColumn": "timeInserted",
                "endColumn": "timeInserted", "k": 0})
            served = ru_eng.execute(ru_plan, use_cache=False)
            forced = ru_eng.execute(ru_plan, use_cache=False,
                                    use_rollup=False)
            rollup_parity_ok = bool(
                rollup_parity_ok and served.get("rollup")
                and served["rows"] == forced["rows"])
            if rollup_parity_ok:
                iters = 1 if fast_ru else 5
                t_served: list = []
                t_forced: list = []
                for _ in range(iters):
                    tq = time.perf_counter()
                    ru_eng.execute(ru_plan, use_cache=False)
                    t_served.append(time.perf_counter() - tq)
                    tq = time.perf_counter()
                    ru_eng.execute(ru_plan, use_cache=False,
                                   use_rollup=False)
                    t_forced.append(time.perf_counter() - tq)
                leg_stats["query_rollup_dashboard"] = \
                    _leg_stats(t_served)
                leg_stats["query_rollup_raw_scan"] = \
                    _leg_stats(t_forced)
                rollup_bench["query_rollup_dashboard_ms"] = round(
                    min(t_served) * 1000, 3)
                rollup_bench["query_rollup_raw_scan_ms"] = round(
                    min(t_forced) * 1000, 3)
                rollup_bench["query_rollup_dashboard_speedup"] = \
                    round(min(t_forced) / max(min(t_served), 1e-9), 1)
                rollup_bench["query_rollup_rows_scanned"] = int(
                    served["rowsScanned"])
                rollup_bench["query_rollup_raw_rows_scanned"] = int(
                    forced["rowsScanned"])

            # (B) maintenance overhead: A/B ingest, one declared view
            # vs rollup plane inactive, alternating reps to damp the
            # 2-core host's noise
            reps = 1 if fast_ru else 3
            ab_blocks = ru_blocks[:min(8, len(ru_blocks))]
            t_on: list = []
            t_off: list = []
            ratios: list = []
            for _ in range(reps):
                _ru_env(True)
                db_on = _RuDb(engine="parts")
                _ru_env(False)
                db_off = _RuDb(engine="parts")
                # warm both sides (native-kernel load, allocator)
                db_on.insert_flows(ab_blocks[0])
                db_off.insert_flows(ab_blocks[0])
                # paired, block-interleaved, order-alternated timing:
                # host drift on the 2-core bench box (tens of percent
                # across seconds) hits both members of a pair, and
                # alternating which side runs first cancels the
                # decaying-burst bias; the per-pair RATIO median is
                # the overhead estimator (outlier pairs — a GC or a
                # scheduler burst inside one member — drop out)
                for j, b in enumerate(ab_blocks):
                    order = ((db_on, t_on), (db_off, t_off)) \
                        if j % 2 else ((db_off, t_off), (db_on, t_on))
                    for side_db, sink in order:
                        tq = time.perf_counter()
                        side_db.insert_flows(b)
                        sink.append(time.perf_counter() - tq)
                    ratios.append((t_on[-1] - t_off[-1]) / t_off[-1])
            n_ru_rows = sum(len(b) for b in ab_blocks)
            leg_stats["query_rollup_ingest_on"] = _leg_stats(t_on)
            leg_stats["query_rollup_ingest_off"] = _leg_stats(t_off)
            ratios.sort()
            rollup_bench["query_rollup_maintenance_overhead_pct"] = \
                round(ratios[len(ratios) // 2] * 100, 2)
            rollup_bench["query_rollup_ingest_rows_per_sec"] = round(
                n_ru_rows * reps / sum(t_on))
            print("rollup views: " + ", ".join(
                f"{k.replace('query_rollup_', '')} {v:,}"
                if isinstance(v, (int, float)) else f"{k} {v}"
                for k, v in rollup_bench.items()), file=sys.stderr)
        finally:
            for k, v in ru_saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            _ru_shutil.rmtree(ru_tmp, ignore_errors=True)
    except Exception as e:
        import traceback
        print(f"rollup bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Metrics history (scrape-to-store, PR 13): (A) A/B ingest with a
    # REAL MetricsHistoryLoop thread scraping at a hot cadence vs the
    # plane disabled (THEIA_METRICS_SCRAPE_INTERVAL=0 semantics — no
    # loop at all), reporting the e2e ingest overhead of self-scrape
    # (budget: within host noise, well under the PR-3 3% bar); (B) a
    # 6h-window aggregation over a downsampled `__metrics__` store —
    # the p95-dashboard query shape (bucket series folded per metric/
    # labels) answered from rollup-tier parts — with a raw-vs-rolled
    # parity gate before the timed windows. THEIA_BENCH_FAST shrinks
    # both to a smoke.
    metrics_history_bench: dict = {}
    try:
        import contextlib as _mh_ctx

        from theia_tpu.ingest import BlockEncoder as _MhEnc
        from theia_tpu.ingest import native_available as _mh_native
        from theia_tpu.manager.ingest import IngestManager as _MhIm
        from theia_tpu.obs import history as _mh_history
        from theia_tpu.query import QueryEngine as _MhEng
        from theia_tpu.query import parse_plan as _mh_parse
        from theia_tpu.schema import METRICS_SCHEMA as _MH_SCHEMA
        from theia_tpu.schema import ColumnarBatch as _MhCB
        from theia_tpu.store import FlowDatabase as _MhDb

        fast_mh = os.environ.get("THEIA_BENCH_FAST") == "1"
        if _mh_native():
            def cpu_ctx_mh():
                try:
                    return jax.default_device(jax.devices("cpu")[0])
                except Exception:
                    return _mh_ctx.nullcontext()
            big_mh = generate_flows(SynthConfig(n_series=2000,
                                                points_per_series=30))
            n_payloads = 3 if fast_mh else 9

            def mh_ingest_pass(with_loop: bool) -> float:
                dbm = _MhDb(ttl_seconds=12 * 3600)
                imm = _MhIm(dbm)
                loop = None
                if with_loop:
                    # 1 s cadence — 15x hotter than the production
                    # default, so a ~1 s timed pass pays at least one
                    # real scrape+maintain tick without turning the
                    # leg into a scrape-throughput microbench
                    loop = _mh_history.MetricsHistoryLoop(
                        dbm, interval=1.0)
                    loop.start()
                encm = _MhEnc(dicts=big_mh.dicts)
                payloads = [encm.encode(big_mh)
                            for _ in range(n_payloads)]
                imm.ingest(payloads[0])   # warm dicts + jit
                tm = time.perf_counter()
                n = sum(imm.ingest(p)["rows"] for p in payloads[1:])
                dtm = time.perf_counter() - tm
                if loop is not None:
                    loop.stop()
                imm.close()
                return n / dtm

            # interleaved best-of-N (the metrics-overhead leg's
            # discipline): host drift must not masquerade as overhead
            rates_mh = {"off": 0.0, "on": 0.0}
            with cpu_ctx_mh():
                for _ in range(2 if fast_mh else 3):
                    rates_mh["off"] = max(rates_mh["off"],
                                          mh_ingest_pass(False))
                    rates_mh["on"] = max(rates_mh["on"],
                                         mh_ingest_pass(True))
            metrics_history_bench[
                "metrics_history_ingest_rows_per_sec"] = round(
                    rates_mh["on"])
            if rates_mh["off"] > 0:
                metrics_history_bench[
                    "metrics_history_overhead_pct"] = round(
                        (rates_mh["off"] - rates_mh["on"])
                        / rates_mh["off"] * 100, 2)
            print(f"ingest with metrics history: "
                  f"{rates_mh['on']:,.0f} rows/s (off: "
                  f"{rates_mh['off']:,.0f}; overhead "
                  f"{metrics_history_bench.get('metrics_history_overhead_pct')}%)",
                  file=sys.stderr)

        # (B) 6h-window history query from downsampled parts
        span = 1800 if fast_mh else 21600   # the "6h" window
        raw_mh, roll_mh = _MhDb(), _MhDb()
        hist_rng = np.random.default_rng(5)
        n_series_mh = 4 if fast_mh else 24
        totals = np.zeros(n_series_mh)
        rows_buf: list = []

        def flush_mh():
            for dmh in (raw_mh, roll_mh):
                tabm = _mh_history.metrics_table(dmh)
                tabm.insert(_MhCB.from_rows(
                    rows_buf, _MH_SCHEMA, tabm.dicts))
                tabm.seal()
            rows_buf.clear()

        for t in range(0, span, 15):
            totals += hist_rng.integers(0, 1000, n_series_mh)
            for s in range(n_series_mh):
                v = int(totals[s]) * 1_000_000
                rows_buf.append({
                    "timeInserted": t, "metric": "bench_lat_bucket",
                    "labels": f"le={s}", "node": "n0",
                    "kind": "bucket", "resolution": 15, "value": v,
                    "valueMin": v, "valueMax": v, "valueSum": v,
                    "valueCount": 1})
            if t % 900 == 0 and rows_buf:
                flush_mh()
        if rows_buf:   # the ticks after the last 900s boundary
            flush_mh()
        roll_loop = _mh_history.MetricsHistoryLoop(
            roll_mh, interval=15, retention_seconds=0,
            tiers=[(60, 600), (3600, 3600)])
        roll_loop.maintain(now=span)
        hist_plan = _mh_parse({
            "table": "__metrics__", "groupBy": "metric,labels",
            "agg": ["min:valueMin", "max:valueMax", "sum:valueSum",
                    "sum:valueCount"],
            "start": 0, "end": span, "k": 0})
        eng_raw_mh = _MhEng(raw_mh)
        eng_roll_mh = _MhEng(roll_mh)
        r_raw = eng_raw_mh.execute(hist_plan, use_cache=False)
        r_roll = eng_roll_mh.execute(hist_plan, use_cache=False)
        parity_mh = r_raw["rows"] == r_roll["rows"]
        metrics_history_bench["metrics_history_rollup_parity_ok"] = \
            parity_mh
        if parity_mh:
            t_hq: list = []
            for _ in range(3 if fast_mh else 9):
                tq = time.perf_counter()
                eng_roll_mh.execute(hist_plan, use_cache=False)
                t_hq.append(time.perf_counter() - tq)
            leg_stats["metrics_history_query"] = _leg_stats(t_hq)
            metrics_history_bench["metrics_history_query_ms"] = round(
                sorted(t_hq)[len(t_hq) // 2] * 1e3, 3)
            metrics_history_bench[
                "metrics_history_rollup_rows_scanned"] = \
                int(r_roll["rowsScanned"])
            metrics_history_bench[
                "metrics_history_raw_rows_scanned"] = \
                int(r_raw["rowsScanned"])
        print("metrics history: " + ", ".join(
            f"{k.replace('metrics_history_', '')} {v}"
            for k, v in metrics_history_bench.items()),
            file=sys.stderr)
    except Exception as e:
        import traceback
        print(f"metrics-history bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Overload behavior through a REAL manager (ephemeral port), two
    # phases: (A) flat-out exactly-once producers with admission
    # unlimited measure the HTTP-path capacity of this host; (B) the
    # admission row bucket is pinned to HALF that, so the same
    # producers now offer ~2x the admitted capacity — the 429 +
    # Retry-After path runs end to end while a prober samples
    # /healthz (the control plane must stay responsive while ingest
    # sheds). Reports acked goodput (should hold ≈ the admitted
    # capacity, not collapse), shed fraction (429s / attempts), and
    # /healthz p95.
    overload: dict = {}
    try:
        import gc as _gc
        import threading
        import urllib.request as _urlreq

        _gc.collect()   # drop earlier legs' stores before measuring

        from theia_tpu.ingest import BlockEncoder as _OvEnc
        from theia_tpu.ingest.client import IngestClient
        from theia_tpu.manager import TheiaManagerServer
        from theia_tpu.manager.admission import TokenBucket
        from theia_tpu.store import FlowDatabase as _OvDb

        saved_env = {k: os.environ.get(k) for k in
                     ("THEIA_RETENTION_INTERVAL",)}
        os.environ["THEIA_RETENTION_INTERVAL"] = "0"
        srv = None
        try:
            srv = TheiaManagerServer(
                _OvDb(ttl_seconds=12 * 3600), port=0, workers=1)
            srv.start_background()
            addr = f"http://127.0.0.1:{srv.port}"
            n_prod = 2
            t_end = [0.0]
            # Warm serially BEFORE any timed window: the first block
            # per detector shard pays jit compile (seconds), which
            # would otherwise be billed as shed capacity.
            producers = []
            for ci in range(n_prod):
                enc = _OvEnc()
                # small blocks (2k rows) keep the token-bucket
                # granularity error well under the admitted rate
                blk = generate_flows(SynthConfig(
                    n_series=200, points_per_series=10,
                    seed=10 + ci), dicts=enc.dicts)
                c = IngestClient(addr, stream=f"bench-{ci}",
                                 max_attempts=500,
                                 backoff_base=0.02,
                                 backoff_cap=0.25)
                c.send(enc.encode(blk))
                producers.append((enc, blk, c))
            clients = [c for _, _, c in producers]
            rows_per_block = len(producers[0][1])

            def reset_ledgers():
                for c in clients:
                    c.rows_acked = c.batches_acked = 0
                    c.rejected = c.retries = c.duplicates = 0

            def produce(ci):
                enc, blk, c = producers[ci]
                while time.monotonic() < t_end[0]:
                    try:
                        c.send(enc.encode(blk))
                    except Exception:
                        break

            def run_phase(seconds):
                reset_ledgers()
                t_end[0] = time.monotonic() + seconds
                threads = [threading.Thread(target=produce,
                                            args=(i,))
                           for i in range(n_prod)]
                t0p = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                return time.monotonic() - t0p

            # Phase A: measured capacity of the whole HTTP path
            dt_a = run_phase(2.0)
            cap_http = sum(c.rows_acked for c in clients) / dt_a
            if cap_http <= 0:
                raise RuntimeError("no rows acked in capacity phase")
            # Reset the store so phase B's capacity matches phase A's
            # (a store grown by the capacity probe pays more per
            # insert, which would read as shed capacity).
            dbov = srv.controller.db
            dbov.flows.truncate()
            for v in dbov.views.values():
                v.truncate()
            _gc.collect()
            # Phase B: admit half of capacity → offered ≈ 2x admitted
            admit_rate = cap_http / 2
            srv.ingest.admission.rows = TokenBucket(
                admit_rate, max(2 * rows_per_block, admit_rate / 2))
            healthz_lat: list = []
            stop = threading.Event()

            def probe():
                while not stop.is_set():
                    t0q = time.monotonic()
                    try:
                        with _urlreq.urlopen(addr + "/healthz",
                                             timeout=5) as r:
                            r.read()
                        healthz_lat.append(time.monotonic() - t0q)
                    except Exception:
                        healthz_lat.append(float("inf"))
                    time.sleep(0.05)

            prober = threading.Thread(target=probe)
            prober.start()
            dt_b = run_phase(4.0)
            stop.set()
            prober.join()
            acked = sum(c.rows_acked for c in clients)
            n_429 = sum(c.rejected for c in clients)
            attempts = n_429 + sum(c.batches_acked for c in clients)
            lat_ok = sorted(x for x in healthz_lat
                            if x != float("inf"))
            p95 = (lat_ok[int(0.95 * (len(lat_ok) - 1))]
                   if lat_ok else float("nan"))
            overload = {
                "goodput_under_overload_rows_per_sec": round(
                    acked / dt_b),
                "shed_ratio_at_2x": round(n_429 / attempts, 3)
                if attempts else None,
                "overload_capacity_rows_per_sec": round(cap_http),
                "overload_admitted_rows_per_sec": round(admit_rate),
                "healthz_under_overload_p95_ms": round(p95 * 1e3, 1),
                "healthz_probe_failures": sum(
                    1 for x in healthz_lat if x == float("inf")),
            }
            print(f"overload: HTTP capacity {cap_http:,.0f} rows/s; "
                  f"at 2x offered vs {admit_rate:,.0f} admitted: "
                  f"goodput "
                  f"{overload['goodput_under_overload_rows_per_sec']:,}"
                  f" rows/s, shed ratio "
                  f"{overload['shed_ratio_at_2x']}, healthz p95 "
                  f"{overload['healthz_under_overload_p95_ms']}ms "
                  f"({len(healthz_lat)} probes, "
                  f"{overload['healthz_probe_failures']} failed)",
                  file=sys.stderr)
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            if srv is not None:
                srv.shutdown()
    except Exception as e:
        import traceback
        print(f"overload bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    # Cluster tier (docs/cluster.md) through REAL managers on
    # ephemeral ports: (1) WAL log-shipping replication throughput
    # with quorum vs leader-only acks, behind a CONSERVATION gate —
    # every row the producer was acknowledged for must be on the
    # follower; (2) failover: kill -9 the leader, promote the
    # follower, measure wall time until the producer's next ack on
    # the new leader, gated on zero acked-row loss + dedup-resolved
    # duplicates; (3) router forward rate on a 2-peer mesh, gated on
    # cluster-wide row conservation. THEIA_BENCH_FAST shrinks the
    # block counts to a smoke.
    cluster_bench: dict = {}
    try:
        import json as _cj
        import shutil as _cshutil
        import socket as _csocket
        import tempfile as _ctempfile
        import urllib.request as _curlreq

        from theia_tpu.ingest import BlockEncoder as _ClEnc
        from theia_tpu.ingest.client import IngestClient as _ClClient
        from theia_tpu.manager import TheiaManagerServer as _ClSrv
        from theia_tpu.store import FlowDatabase as _ClDb

        def _cl_port():
            s = _csocket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        fastc = os.environ.get("THEIA_BENCH_FAST") == "1"
        n_blocks = 3 if fastc else 30
        saved_env_c = {k: os.environ.get(k) for k in
                       ("THEIA_RETENTION_INTERVAL",
                        "THEIA_CLUSTER_HEARTBEAT",
                        "THEIA_CLUSTER_BOUNDS_INTERVAL")}
        os.environ["THEIA_RETENTION_INTERVAL"] = "0"
        tmpc = _ctempfile.mkdtemp(prefix="theia-cluster-bench-")
        try:
            # -- replication: quorum vs leader acks ------------------
            for policy in ("quorum", "leader"):
                p0, p1 = _cl_port(), _cl_port()
                peers = (f"n0=http://127.0.0.1:{p0},"
                         f"n1=http://127.0.0.1:{p1}")
                db0 = _ClDb()
                db0.attach_wal(os.path.join(tmpc, f"{policy}-w0"))
                db1 = _ClDb()
                db1.attach_wal(os.path.join(tmpc, f"{policy}-w1"))
                lead = _ClSrv(db0, port=p0, cluster_peers=peers,
                              cluster_self="n0", cluster_role="leader",
                              cluster_acks=policy)
                fol = _ClSrv(db1, port=p1, cluster_peers=peers,
                             cluster_self="n1",
                             cluster_role="follower")
                lead.start_background()
                fol.start_background()
                try:
                    import threading as _cthreading

                    # Concurrent producers: frames from several
                    # streams accumulate while a ship POST is in
                    # flight, so the batched shipping (up to
                    # THEIA_REPL_BATCH_BYTES per POST over the
                    # persistent peer connection) amortizes the
                    # follower roundtrip across streams instead of
                    # paying one per batch.
                    n_prod = 4
                    warm_enc = _ClEnc()
                    blk = generate_flows(SynthConfig(
                        n_series=200, points_per_series=10, seed=31),
                        dicts=warm_enc.dicts)
                    _ClClient(f"http://127.0.0.1:{p0}",
                              stream=f"repl-{policy}-warm").send(
                        warm_enc.encode(blk))   # jit warm, untimed
                    clients = []
                    errors = []

                    def _produce(i, window):
                        enc_i = _ClEnc()
                        blk_i = generate_flows(SynthConfig(
                            n_series=200, points_per_series=10,
                            seed=40 + i), dicts=enc_i.dicts)
                        cl_i = _ClClient(
                            f"http://127.0.0.1:{p0}",
                            stream=f"repl-{policy}-{window}-{i}")
                        clients.append(cl_i)
                        try:
                            for _ in range(n_blocks):
                                cl_i.send(enc_i.encode(blk_i))
                        except Exception as e:
                            errors.append(e)

                    # best-of-2 windows: the 2-core host's scheduling
                    # noise swings single windows by 2x (the PR-8
                    # query-leg discipline)
                    best_rate = 0.0
                    for window in range(1 if fastc else 2):
                        threads = [
                            _cthreading.Thread(target=_produce,
                                               args=(i, window))
                            for i in range(n_prod)]
                        t0c = time.perf_counter()
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        dt_c = time.perf_counter() - t0c
                        if errors:
                            raise errors[0]
                        best_rate = max(
                            best_rate,
                            (n_prod * n_blocks * len(blk)) / dt_c)
                    acked = sum(c.rows_acked for c in clients) \
                        + len(blk)
                    if policy == "leader":
                        # leader-only acks ship async: wait for drain
                        deadline = time.monotonic() + 30
                        while time.monotonic() < deadline and \
                                len(db1.flows) != len(db0.flows):
                            time.sleep(0.02)
                    conserved = (len(db1.flows) == len(db0.flows)
                                 == acked)
                    cluster_bench[
                        f"repl_ship_rows_per_sec_{policy}"] = round(
                        best_rate)
                    ok_key = "repl_conservation_ok"
                    cluster_bench[ok_key] = (
                        cluster_bench.get(ok_key, True) and conserved)
                    if not conserved:
                        print(f"replication CONSERVATION FAILED "
                              f"({policy}): leader {len(db0.flows)} "
                              f"follower {len(db1.flows)} acked "
                              f"{acked}", file=sys.stderr)
                finally:
                    lead.shutdown()
                    fol.shutdown()

            # -- failover recovery time ------------------------------
            # THREE nodes: with only two, a quorum-acks leader
            # promoted after its sole peer died can never meet quorum
            # again (majority of 2 is 2) and every post-failover ack
            # times out — the drill must leave a follower standing.
            fo_ports = [_cl_port() for _ in range(3)]
            peers = ",".join(
                f"n{i}=http://127.0.0.1:{p}"
                for i, p in enumerate(fo_ports))
            fo_dbs = []
            for i in range(3):
                db = _ClDb()
                db.attach_wal(os.path.join(tmpc, f"fo-w{i}"))
                fo_dbs.append(db)
            lead = _ClSrv(fo_dbs[0], port=fo_ports[0],
                          cluster_peers=peers, cluster_self="n0",
                          cluster_role="leader",
                          cluster_acks="quorum")
            fols = [_ClSrv(fo_dbs[i], port=fo_ports[i],
                           cluster_peers=peers, cluster_self=f"n{i}",
                           cluster_role="follower")
                    for i in (1, 2)]
            lead.start_background()
            for f in fols:
                f.start_background()
            try:
                enc = _ClEnc()
                blk = generate_flows(SynthConfig(
                    n_series=200, points_per_series=10, seed=32),
                    dicts=enc.dicts)
                cl = _ClClient(
                    [f"http://127.0.0.1:{p}" for p in fo_ports],
                    stream="fo", max_attempts=60,
                    backoff_base=0.02, backoff_cap=0.2)
                for _ in range(3 if fastc else 6):
                    cl.send(enc.encode(blk))
                acked_before = cl.rows_acked
                t0f = time.perf_counter()
                lead.httpd.shutdown()          # kill -9 equivalence:
                lead.httpd.server_close()      # no drain, no close
                lead.cluster.stop()
                # the runbook promotes the MOST ADVANCED follower at
                # its applied LSN (quorum writes intersect with it)
                best = max(
                    (1, 2),
                    key=lambda i: fo_dbs[i].wal_position() or 0)
                req = _curlreq.Request(
                    f"http://127.0.0.1:{fo_ports[best]}"
                    f"/cluster/promote",
                    data=_cj.dumps(
                        {"atLsn": fo_dbs[best].wal_position()}
                    ).encode(), method="POST")
                with _curlreq.urlopen(req, timeout=30) as r:
                    r.read()
                # the producer retries its LAST acked batch (the one
                # whose ack could have been lost on the wire), then
                # resumes with a fresh encoder chain on the new leader
                dup = cl.send(b"\x00", seq=cl.seq)
                enc2 = _ClEnc()
                blk2 = generate_flows(SynthConfig(
                    n_series=200, points_per_series=10, seed=33),
                    dicts=enc2.dicts)
                cl.send(enc2.encode(blk2))
                dt_fo = time.perf_counter() - t0f
                cluster_bench["failover_recovery_seconds"] = round(
                    dt_fo, 3)
                cluster_bench["failover_conservation_ok"] = bool(
                    dup.get("duplicate")
                    and len(fo_dbs[best].flows)
                    == acked_before + len(blk2))
            finally:
                for f in fols:
                    f.shutdown()

            # -- router forwarding -----------------------------------
            p0, p1 = _cl_port(), _cl_port()
            peers = (f"n0=http://127.0.0.1:{p0},"
                     f"n1=http://127.0.0.1:{p1}")
            db0, db1 = _ClDb(), _ClDb()
            s0 = _ClSrv(db0, port=p0, cluster_peers=peers,
                        cluster_self="n0", cluster_role="peer")
            s1 = _ClSrv(db1, port=p1, cluster_peers=peers,
                        cluster_self="n1", cluster_role="peer")
            s0.start_background()
            s1.start_background()
            try:
                enc = _ClEnc()
                blk = generate_flows(SynthConfig(
                    n_series=200, points_per_series=10, seed=34),
                    dicts=enc.dicts)
                cl = _ClClient(f"http://127.0.0.1:{p0}",
                               stream="mesh")
                cl.send(enc.encode(blk))   # warm both nodes' jit
                t0r = time.perf_counter()
                for _ in range(n_blocks):
                    cl.send(enc.encode(blk))
                dt_r = time.perf_counter() - t0r
                cluster_bench["router_forward_rows_per_sec"] = round(
                    (n_blocks * len(blk)) / dt_r)
                cluster_bench["router_conservation_ok"] = (
                    len(db0.flows) + len(db1.flows) == cl.rows_acked)
            finally:
                s0.shutdown()
                s1.shutdown()

            # -- distributed scatter-gather query --------------------
            # (docs/queries.md "Distributed execution") behind a
            # row-conservation PARITY gate: the cluster-wide group-sum
            # over router-spread ingest must be bit-identical —
            # groups, sums, means, top-K order — to the single-node
            # engine over the same rows, with bytes on the wire
            # proportional to surviving GROUPS (never rows).
            # THEIA_BENCH_FAST runs a two-node smoke.
            from theia_tpu.query import QueryEngine as _DqEngine
            from theia_tpu.query import parse_plan as _dq_parse
            from theia_tpu.store.wal import (
                RECORD_MAGIC as _DQ_MAGIC,
                encode_record_body as _dq_encode,
            )
            os.environ["THEIA_CLUSTER_HEARTBEAT"] = "0.1"
            os.environ["THEIA_CLUSTER_BOUNDS_INTERVAL"] = "0.05"
            n_nodes = 2 if fastc else 3
            dq_ports = [_cl_port() for _ in range(n_nodes)]
            dq_peers = ",".join(
                f"n{i}=http://127.0.0.1:{p}"
                for i, p in enumerate(dq_ports))
            dq_dbs = [_ClDb() for _ in range(n_nodes)]
            dq_srvs = [
                _ClSrv(dq_dbs[i], port=dq_ports[i],
                       cluster_peers=dq_peers, cluster_self=f"n{i}",
                       cluster_role="peer")
                for i in range(n_nodes)]
            for s in dq_srvs:
                s.start_background()
            oracle_db = _ClDb()
            try:
                # wave A: routed ingest through n0 (spread by
                # destination hash); the oracle holds the same rows
                enc = _ClEnc()
                cl = _ClClient(f"http://127.0.0.1:{dq_ports[0]}",
                               stream="dq")
                dq_rows = 0
                for i in range(2 if fastc else 8):
                    blk = generate_flows(SynthConfig(
                        n_series=300, points_per_series=10,
                        anomaly_fraction=0.0, seed=60 + i),
                        dicts=enc.dicts)
                    cl.send(enc.encode(blk))
                    oracle_db.insert_flows(blk)
                    dq_rows += len(blk)
                # wave B: per-node TREC placement with DISJOINT time
                # ranges ABOVE wave A's (TREC is never re-routed), so
                # a window over the LAST node's range proves every
                # other peer's flowStart maximum is below it
                from theia_tpu.data.synth import (
                    DEFAULT_START as _DQ_T0,
                )
                bases = [_DQ_T0 + (i + 1) * 30 * 86_400
                         for i in range(n_nodes)]
                for i, port in enumerate(dq_ports):
                    enc_b = _ClEnc()
                    blk_b = generate_flows(SynthConfig(
                        n_series=120, points_per_series=10,
                        anomaly_fraction=0.0, seed=80 + i,
                        start_time=bases[i]), dicts=enc_b.dicts)
                    _ClClient(f"http://127.0.0.1:{port}",
                              stream=f"dqp-n{i}").send(
                        _DQ_MAGIC + _dq_encode("flows", blk_b))
                    oracle_db.insert_flows(blk_b)
                    dq_rows += len(blk_b)
                assert sum(len(db.flows) for db in dq_dbs) == dq_rows
                # heartbeats must carry current fingerprints+bounds
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline:
                    if all(
                        (s.cluster.cmap.peer_info(o.cluster.cmap.self_id)
                         .get("store") or {}).get("fingerprint")
                        == o.queries.fingerprint_hash()
                        for s in dq_srvs for o in dq_srvs if s is not o):
                        break
                    time.sleep(0.05)
                plan_doc = {
                    "groupBy": "destinationIP",
                    "aggregates": ["sum:octetDeltaCount",
                                   "mean:throughput", "count"],
                    "k": 100,
                }
                oracle_doc = _DqEngine(oracle_db).execute(
                    _dq_parse(plan_doc), use_cache=False)

                def _dq_query(port, doc):
                    req = _curlreq.Request(
                        f"http://127.0.0.1:{port}/query",
                        data=_cj.dumps(doc).encode(), method="POST")
                    with _curlreq.urlopen(req, timeout=60) as r:
                        return _cj.load(r)

                got = _dq_query(dq_ports[1],
                                {**plan_doc, "cache": False})
                parity = (got["rows"] == oracle_doc["rows"]
                          and got["groupCount"]
                          == oracle_doc["groupCount"]
                          and not got["partial"])
                cluster_bench["distquery_parity_ok"] = parity
                if parity:
                    n_q = 3 if fastc else 12
                    t0q = time.perf_counter()
                    for _ in range(n_q):
                        got = _dq_query(dq_ports[1],
                                        {**plan_doc, "cache": False})
                    dt_q = time.perf_counter() - t0q
                    cluster_bench["distquery_groupsum_rows_per_sec"] \
                        = round(n_q * dq_rows / dt_q)
                    cluster_bench["distquery_bytes_shipped_per_group"] \
                        = round(got["bytesShipped"]
                                / max(got["groupCount"], 1), 1)
                    # tracing A/B on the distributed leg: the same
                    # queries with THEIA_TRACE_SAMPLE=0 (no contexts
                    # minted, no traceparent on the fan-out wire —
                    # every in-process node flips at once); the
                    # default-sampled loop above is the B side
                    saved_ts = os.environ.get("THEIA_TRACE_SAMPLE")
                    os.environ["THEIA_TRACE_SAMPLE"] = "0"
                    try:
                        t0n = time.perf_counter()
                        for _ in range(n_q):
                            _dq_query(dq_ports[1],
                                      {**plan_doc, "cache": False})
                        dt_n = time.perf_counter() - t0n
                    finally:
                        if saved_ts is None:
                            os.environ.pop("THEIA_TRACE_SAMPLE",
                                           None)
                        else:
                            os.environ["THEIA_TRACE_SAMPLE"] = \
                                saved_ts
                    if dt_n > 0:
                        cluster_bench[
                            "distquery_tracing_overhead_pct"] = round(
                            (dt_q - dt_n) / dt_n * 100, 2)
                    # pruned leg: window covering ONLY the last
                    # node's placed range — every other peer prunes
                    win = {"start": bases[-1] - 1000,
                           "end": bases[-1] + 86_000}
                    wdoc = {**plan_doc, **win, "cache": False}
                    worcle = _DqEngine(oracle_db).execute(
                        _dq_parse({**plan_doc, **win}),
                        use_cache=False)
                    wgot = _dq_query(dq_ports[-1], wdoc)
                    pruned_ok = (
                        wgot["rows"] == worcle["rows"]
                        and wgot["peers"]["pruned"] == n_nodes - 1)
                    cluster_bench["distquery_pruned_parity_ok"] = \
                        pruned_ok
                    if pruned_ok:
                        n_w = 3 if fastc else 12
                        t0w = time.perf_counter()
                        for _ in range(n_w):
                            _dq_query(dq_ports[-1], wdoc)
                        dt_w = time.perf_counter() - t0w
                        cluster_bench["distquery_peer_pruned_speedup"] \
                            = round((dt_q / n_q) / (dt_w / n_w), 1)
                else:
                    print("distributed query PARITY FAILED: "
                          f"cluster {got['groupCount']} groups vs "
                          f"oracle {oracle_doc['groupCount']} "
                          f"(partial={got.get('partial')})",
                          file=sys.stderr)
            finally:
                for s in dq_srvs:
                    s.shutdown()
            print("cluster: " + ", ".join(
                f"{k.replace('repl_', '').replace('router_', 'router ')}"
                f" {v:,}" if isinstance(v, int) else f"{k} {v}"
                for k, v in cluster_bench.items()), file=sys.stderr)
        finally:
            for k, v in saved_env_c.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            _cshutil.rmtree(tmpc, ignore_errors=True)
    except Exception as e:
        import traceback
        print(f"cluster bench skipped: {e}", file=sys.stderr)
        traceback.print_exc(file=sys.stderr)

    try:
        import contextlib

        from theia_tpu.analytics.streaming import StreamingDetector
        try:
            # Host cpu backend, same rationale as the e2e leg: the
            # detector state is host-resident in production; under
            # axon the remote tunnel would dominate the p50.
            cpu_ctx2 = jax.default_device(jax.devices("cpu")[0])
        except Exception:
            cpu_ctx2 = contextlib.nullcontext()
        with cpu_ctx2:
            det = StreamingDetector(capacity=1024)
            S, T = cfg.n_series, cfg.points_per_series
            idx = np.arange(len(batch)).reshape(S, T)
            lat = []
            for t in range(min(T, 40)):
                micro = batch.take(idx[:, t])
                t9 = time.perf_counter()
                det.ingest(micro)
                lat.append(time.perf_counter() - t9)
        p50 = sorted(lat)[len(lat) // 2]
        print(f"streaming micro-batch p50: {p50 * 1e3:.2f} ms "
              f"({S} series/batch)", file=sys.stderr)
        result_extra_p50 = p50
    except Exception as e:
        print(f"streaming bench skipped: {e}", file=sys.stderr)
        result_extra_p50 = None

    result = {
        "metric": "tad_ewma_scoring_records_per_sec",
        "value": round(records_per_sec),
        "unit": "records/s",
        "vs_baseline": round(records_per_sec / BASELINE_RECORDS_PER_SEC,
                             1),
        "platform": dev.platform,
        "e2e_ingest_rows_per_sec": round(e2e_rate),
        "degraded_write_rows_per_sec": round(degraded_write),
        "ingest_with_metrics_rows_per_sec": round(metrics_rate),
    }
    if metrics_overhead_pct is not None:
        result["ingest_metrics_overhead_pct"] = metrics_overhead_pct
    if tracing_overhead_pct is not None:
        result["ingest_tracing_overhead_pct"] = tracing_overhead_pct
    if lockdep_overhead_pct is not None:
        result["ingest_lockdep_rows_per_sec"] = round(lockdep_rate)
        result["lockdep_overhead_pct"] = lockdep_overhead_pct
        leg_stats["ingest_lockdep_on"] = _leg_stats(
            lockdep_times["on"])
        leg_stats["ingest_lockdep_off"] = _leg_stats(
            lockdep_times["off"])
    if wal_rates:
        result["wal_ingest_rows_per_sec"] = wal_rates
    if wal_store_rates:
        result["wal_store_insert_rows_per_sec"] = wal_store_rates
    if wal_recovery:
        result["wal_recovery_rows_per_sec"] = round(wal_recovery)
    if parts_parity_ok is not None:
        result["parts_parity_ok"] = parts_parity_ok
    if parts_bench:
        result.update(parts_bench)
    if query_parity_ok is not None:
        result["query_parity_ok"] = query_parity_ok
    if query_bench:
        result.update(query_bench)
    if rollup_parity_ok is not None:
        result["query_rollup_parity_ok"] = rollup_parity_ok
    if rollup_bench:
        result.update(rollup_bench)
    if metrics_history_bench:
        result.update(metrics_history_bench)
    if leg_stats:
        result["leg_stats"] = leg_stats
    if overload:
        result.update(overload)
    if cluster_bench:
        result.update(cluster_bench)
    if working_set_parity_ok is not None:
        result["working_set_parity_ok"] = working_set_parity_ok
    if working_set_rate:
        result["detector_working_set_rows_per_sec"] = round(
            working_set_rate)
    if working_set_times:
        leg_stats["detector_working_set"] = _leg_stats(
            working_set_times)
        result["leg_stats"] = leg_stats
    if fused_parity_ok is not None:
        result["fused_parity_ok"] = fused_parity_ok
    if fused_det_rate:
        result["fused_detector_rows_per_sec"] = round(fused_det_rate)
    if sharded_det_2s:
        # the same 2-stream structure on the sharded engine — the
        # apples comparable for fused_detector_rows_per_sec
        result["detector_2stream_rows_per_sec"] = round(sharded_det_2s)
    if fused_e2e:
        result["e2e_ingest_fused_rows_per_sec"] = round(fused_e2e)
    if tblk_parity_ok is not None:
        result["tblk_parity_ok"] = tblk_parity_ok
    if tblk_e2e:
        result["e2e_ingest_tblk_rows_per_sec"] = round(tblk_e2e)
        if tfb2_e2e:
            result["e2e_ingest_tblk_vs_tfb2_speedup"] = round(
                tblk_e2e / tfb2_e2e, 2)
        # honest-host caveat: the 2-core bench box's CPU steal swings
        # identical runs by 2-3x, so the speedup carries its per-leg
        # spread rather than pretending to a clean ratio
        leg_stats["e2e_tblk_wal"] = dict(
            _leg_stats(tblk_leg_times),
            caveat="2-core shared host; best-of-2 over CPU-steal "
                   "noise — compare spreads before trusting the "
                   "speedup ratio")
        leg_stats["e2e_tfb2_wal"] = _leg_stats(tfb2_leg_times)
        result["leg_stats"] = leg_stats
    if e2e_stages:
        result["e2e_stages"] = e2e_stages
    if e2e_scaling:
        result["e2e_multi_stream_rows_per_sec"] = e2e_scaling
        result["e2e_rows_per_sec_per_core"] = round(
            e2e_rate / (os.cpu_count() or 1))
    if det_shard_scaling:
        result["detector_shard_scaling_rows_per_sec"] = \
            det_shard_scaling
        result["ingest_detector_shards"] = \
            default_ingest_shards()
    if result_extra_p50 is not None:
        result["streaming_alert_p50_ms"] = round(
            result_extra_p50 * 1e3, 2)
    if dev.platform == "cpu":
        result["degraded"] = "cpu fallback (accelerator unavailable)"
    return result


if __name__ == "__main__":
    main()
