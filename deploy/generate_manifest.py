#!/usr/bin/env python
"""Render the flow-visibility deployment manifest.

Counterpart of the reference's hack/generate-manifest.sh (options
--spark-operator/--theia-manager/--no-grafana/--ch-size/
--ch-monitor-threshold) plus its theia-cli RBAC templates
(build/charts/theia/templates/theia-cli: ServiceAccount + Role +
RoleBinding so the CLI can read its token and port-forward the
manager). Emits a single Kubernetes YAML deploying the theia-tpu
stack into the `flow-visibility` namespace. There is no ClickHouse
operator, ZooKeeper, Grafana or Spark operator to deploy — the store,
dashboards and analytics engine live inside the manager process; the
runner image exists for out-of-process batch jobs on TPU node pools.

Usage:
  python deploy/generate_manifest.py [--no-manager] [--tls] [--auth]
      [--pvc SIZE] [--dispatch thread|subprocess]
      [--checkpoint-interval N] [--capacity-bytes N] [--ttl-seconds N]
      [--namespace NS] > flow-visibility.yml
"""

from __future__ import annotations

import argparse
import secrets
import sys


def _manager_deployment(namespace: str, tls: bool, auth: bool,
                        capacity_bytes: int, ttl_seconds: int,
                        image: str, pvc: str, dispatch: str,
                        checkpoint_interval: int) -> str:
    extra_args = ""
    if tls:
        extra_args += """
            - --tls-cert-dir
            - /certs"""
    if dispatch != "thread":
        extra_args += f"""
            - --dispatch
            - {dispatch}"""
    extra_args += f"""
            - --checkpoint-interval
            - "{checkpoint_interval}\""""
    auth_env = """
            - name: THEIA_AUTH_TOKEN
              valueFrom:
                secretKeyRef:
                  name: theia-api-token
                  key: token""" if auth else ""
    data_volume = f"""\
        - name: data
          persistentVolumeClaim:
            claimName: theia-manager-data""" if pvc else f"""\
        - name: data
          emptyDir:
            sizeLimit: {max(capacity_bytes // (1 << 30), 1)}Gi"""
    return f"""\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: theia-manager
  namespace: {namespace}
  labels:
    app: theia-manager
spec:
  replicas: 1
  selector:
    matchLabels:
      app: theia-manager
  template:
    metadata:
      labels:
        app: theia-manager
    spec:
      serviceAccountName: theia-manager
      containers:
        - name: theia-manager
          image: {image}
          args:
            - --db
            - /data/flows.npz
            - --address
            - 0.0.0.0
            - --capacity-bytes
            - "{capacity_bytes}"{extra_args}
          env:
            - name: POD_NAMESPACE
              valueFrom:
                fieldRef:
                  fieldPath: metadata.namespace
            - name: THEIA_TTL_SECONDS
              value: "{ttl_seconds}"{auth_env}
          ports:
            - containerPort: 11347
              name: api
          readinessProbe:
            httpGet:
              path: /healthz
              port: 11347
              scheme: {"HTTPS" if tls else "HTTP"}
            initialDelaySeconds: 3
          volumeMounts:
            - name: data
              mountPath: /data
            - name: certs
              mountPath: /certs
      volumes:
{data_volume}
        - name: certs
          emptyDir: {{}}
"""


#: CRD kinds (group crd.theia.antrea.io, reference
#: pkg/apis/crd/v1alpha1/types.go) — plural, singular, kind, short
_CRD_KINDS = (
    ("networkpolicyrecommendations", "networkpolicyrecommendation",
     "NetworkPolicyRecommendation", "npr"),
    ("throughputanomalydetectors", "throughputanomalydetector",
     "ThroughputAnomalyDetector", "tad"),
    ("trafficdropdetections", "trafficdropdetection",
     "TrafficDropDetection", "tdd"),
    ("flowpatternminings", "flowpatternmining",
     "FlowPatternMining", "fpm"),
    ("spatialanomalydetections", "spatialanomalydetection",
     "SpatialAnomalyDetection", "sad"),
)


def _crds() -> list:
    """CustomResourceDefinitions for the five job kinds: the
    declarative API surface (`kubectl apply` a CR, the manager's
    reconciler — theia_tpu/manager/reconciler.py — turns it into a
    job). Spec schemas stay open (preserve-unknown-fields): the
    manager validates, like the reference controllers do."""
    docs = []
    for plural, singular, kind, short in _CRD_KINDS:
        docs.append(f"""\
apiVersion: apiextensions.k8s.io/v1
kind: CustomResourceDefinition
metadata:
  name: {plural}.crd.theia.antrea.io
spec:
  group: crd.theia.antrea.io
  scope: Namespaced
  names:
    plural: {plural}
    singular: {singular}
    kind: {kind}
    shortNames: ["{short}"]
  versions:
    - name: v1alpha1
      served: true
      storage: true
      subresources:
        status: {{}}
      schema:
        openAPIV3Schema:
          type: object
          properties:
            spec:
              type: object
              x-kubernetes-preserve-unknown-fields: true
            status:
              type: object
              x-kubernetes-preserve-unknown-fields: true
      additionalPrinterColumns:
        - name: State
          type: string
          jsonPath: .status.state
        - name: Completed
          type: integer
          jsonPath: .status.completedStages
""")
    return docs


def _rbac(namespace: str, auth: bool) -> list:
    """theia-cli access plumbing, mirroring the reference's
    theia-cli templates: a ServiceAccount an operator can `kubectl
    exec`/impersonate, a Role reading the API token Secret and
    port-forwarding the manager Service, and the binding."""
    docs = [f"""\
apiVersion: v1
kind: ServiceAccount
metadata:
  name: theia-cli
  namespace: {namespace}
""", f"""\
apiVersion: rbac.authorization.k8s.io/v1
kind: Role
metadata:
  name: theia-cli
  namespace: {namespace}
rules:
  - apiGroups: [""]
    resources: ["services"]
    resourceNames: ["theia-manager"]
    verbs: ["get"]
  - apiGroups: [""]
    resources: ["pods"]
    verbs: ["get", "list"]
  - apiGroups: [""]
    resources: ["pods/portforward"]
    verbs: ["create"]"""
            + ("""
  - apiGroups: [""]
    resources: ["secrets"]
    resourceNames: ["theia-api-token"]
    verbs: ["get"]
""" if auth else "\n"), f"""\
apiVersion: rbac.authorization.k8s.io/v1
kind: RoleBinding
metadata:
  name: theia-cli
  namespace: {namespace}
roleRef:
  apiGroup: rbac.authorization.k8s.io
  kind: Role
  name: theia-cli
subjects:
  - kind: ServiceAccount
    name: theia-cli
    namespace: {namespace}
"""]
    return docs


def manifest(namespace: str, manager: bool, tls: bool,
             capacity_bytes: int, ttl_seconds: int,
             image: str, auth: bool = False, pvc: str = "",
             dispatch: str = "thread",
             checkpoint_interval: int = 60,
             token: str = "", crds: bool = False) -> str:
    docs = [f"""\
apiVersion: v1
kind: Namespace
metadata:
  name: {namespace}
  labels:
    app: theia-tpu
"""]
    if crds:
        docs.extend(_crds())
    if manager:
        if auth:
            # Render-time random token (the self-signed-cert
            # discipline applied to authn): manager env and CLI both
            # read this Secret, the reference's ServiceAccount-token
            # Secret role.
            token = token or secrets.token_hex(32)
            docs.append(f"""\
apiVersion: v1
kind: Secret
metadata:
  name: theia-api-token
  namespace: {namespace}
type: Opaque
stringData:
  token: {token}
""")
        if pvc:
            docs.append(f"""\
apiVersion: v1
kind: PersistentVolumeClaim
metadata:
  name: theia-manager-data
  namespace: {namespace}
spec:
  accessModes: ["ReadWriteOnce"]
  resources:
    requests:
      storage: {pvc}
""")
        docs.append(_manager_deployment(
            namespace, tls, auth, capacity_bytes, ttl_seconds, image,
            pvc, dispatch, checkpoint_interval))
        docs.append(f"""\
apiVersion: v1
kind: Service
metadata:
  name: theia-manager
  namespace: {namespace}
  labels:
    app: theia-manager
spec:
  selector:
    app: theia-manager
  ports:
    - name: api
      port: 11347
      targetPort: api
""")
        docs.append(f"""\
apiVersion: v1
kind: ServiceAccount
metadata:
  name: theia-manager
  namespace: {namespace}
""")
        docs.extend(_rbac(namespace, auth))
    return "---\n".join(docs)


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--namespace", default="flow-visibility")
    p.add_argument("--no-manager", action="store_true")
    p.add_argument("--tls", action="store_true")
    p.add_argument("--auth", action="store_true",
                   help="bearer-token authn: Secret + manager env + "
                        "CLI read RBAC")
    p.add_argument("--crds", action="store_true",
                   help="include CustomResourceDefinitions for the "
                        "five job kinds (declarative CR surface)")
    p.add_argument("--pvc", default="",
                   help="PersistentVolumeClaim size for /data (e.g. "
                        "16Gi); default emptyDir")
    p.add_argument("--dispatch", default="thread",
                   choices=["thread", "subprocess"])
    p.add_argument("--checkpoint-interval", type=int, default=60)
    p.add_argument("--capacity-bytes", type=int, default=8 << 30)
    p.add_argument("--ttl-seconds", type=int, default=12 * 3600)
    p.add_argument("--image", default="theia-tpu/manager:latest")
    args = p.parse_args(argv)
    sys.stdout.write(manifest(
        args.namespace, not args.no_manager, args.tls,
        args.capacity_bytes, args.ttl_seconds, args.image,
        auth=args.auth, pvc=args.pvc, dispatch=args.dispatch,
        checkpoint_interval=args.checkpoint_interval,
        crds=args.crds))


if __name__ == "__main__":
    main()
