#!/usr/bin/env python
"""Render the flow-visibility deployment manifest.

Counterpart of the reference's hack/generate-manifest.sh (options
--spark-operator/--theia-manager/--no-grafana/--ch-size/
--ch-monitor-threshold): emits a single Kubernetes YAML deploying the
theia-tpu stack into the `flow-visibility` namespace. There is no
ClickHouse operator, ZooKeeper, Grafana or Spark operator to deploy —
the store, dashboards and analytics engine live inside the manager
process; the runner image exists for out-of-process batch jobs on TPU
node pools.

Usage:
  python deploy/generate_manifest.py [--no-manager] [--tls]
      [--capacity-bytes N] [--ttl-seconds N] [--namespace NS]
      > flow-visibility.yml
"""

from __future__ import annotations

import argparse
import sys


def manifest(namespace: str, manager: bool, tls: bool,
             capacity_bytes: int, ttl_seconds: int,
             image: str) -> str:
    docs = [f"""\
apiVersion: v1
kind: Namespace
metadata:
  name: {namespace}
  labels:
    app: theia-tpu
"""]
    if manager:
        tls_args = """
            - --tls-cert-dir
            - /certs""" if tls else ""
        docs.append(f"""\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: theia-manager
  namespace: {namespace}
  labels:
    app: theia-manager
spec:
  replicas: 1
  selector:
    matchLabels:
      app: theia-manager
  template:
    metadata:
      labels:
        app: theia-manager
    spec:
      containers:
        - name: theia-manager
          image: {image}
          args:
            - --db
            - /data/flows.npz
            - --address
            - 0.0.0.0
            - --capacity-bytes
            - "{capacity_bytes}"{tls_args}
          env:
            - name: POD_NAMESPACE
              valueFrom:
                fieldRef:
                  fieldPath: metadata.namespace
            - name: THEIA_TTL_SECONDS
              value: "{ttl_seconds}"
          ports:
            - containerPort: 11347
              name: api
          readinessProbe:
            httpGet:
              path: /healthz
              port: 11347
              scheme: {"HTTPS" if tls else "HTTP"}
            initialDelaySeconds: 3
          volumeMounts:
            - name: data
              mountPath: /data
            - name: certs
              mountPath: /certs
      volumes:
        - name: data
          emptyDir:
            sizeLimit: {max(capacity_bytes // (1 << 30), 1)}Gi
        - name: certs
          emptyDir: {{}}
""")
        docs.append(f"""\
apiVersion: v1
kind: Service
metadata:
  name: theia-manager
  namespace: {namespace}
  labels:
    app: theia-manager
spec:
  selector:
    app: theia-manager
  ports:
    - name: api
      port: 11347
      targetPort: api
""")
        docs.append(f"""\
apiVersion: v1
kind: ServiceAccount
metadata:
  name: theia-manager
  namespace: {namespace}
""")
    return "---\n".join(docs)


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--namespace", default="flow-visibility")
    p.add_argument("--no-manager", action="store_true")
    p.add_argument("--tls", action="store_true")
    p.add_argument("--capacity-bytes", type=int, default=8 << 30)
    p.add_argument("--ttl-seconds", type=int, default=12 * 3600)
    p.add_argument("--image", default="theia-tpu/manager:latest")
    args = p.parse_args(argv)
    sys.stdout.write(manifest(
        args.namespace, not args.no_manager, args.tls,
        args.capacity_bytes, args.ttl_seconds, args.image))


if __name__ == "__main__":
    main()
