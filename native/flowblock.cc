// flowblock — native columnar ingest for theia_tpu.
//
// Plays the role of the reference's native ingest tier (ClickHouse's C++
// TabSeparated/native-protocol parsers receiving FlowAggregator inserts;
// schema contract build/charts/theia/provisioning/datasources/
// create_table.sh:31-84): decode TSV flow records straight into
// fixed-width columnar buffers with per-column dictionary encoding, so
// Python never touches row objects and the arrays are ready for
// jax.device_put.
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   fb_new(n_cols, kinds)        kinds[i]: 0 = int64, 1 = float64,
//                                2 = dictionary-encoded string
//   fb_seed(h, col, s, len)      append an existing dictionary entry
//                                (call in code order to mirror Python)
//   fb_decode(h, buf, nbytes, max_rows, out_ints, out_codes)
//                                parse rows; column-major outputs:
//                                out_ints [n_numeric][max_rows],
//                                out_codes [n_string][max_rows];
//                                returns rows decoded, or -1-row_index
//                                on a malformed row
//   fb_dict_size(h, col)         current dictionary size
//   fb_dict_get(h, col, idx, &len) read one dictionary entry (for
//                                syncing codes minted here back into
//                                the Python StringDictionary)
//   fb_free(h)
//
// Build: g++ -O3 -shared -fPIC (driven by theia_tpu/ingest/native.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum Kind : int32_t { kInt = 0, kFloat = 1, kString = 2 };

struct Dict {
  // Stored strings own the bytes; the map's string_views point into
  // them. std::deque never reallocates existing elements.
  std::deque<std::string> strings;
  std::unordered_map<std::string_view, int32_t> to_code;

  Dict() { add("", 0); }

  void add(std::string_view s, int32_t code) {
    strings.emplace_back(s);
    to_code.emplace(std::string_view(strings.back()), code);
  }

  int32_t encode(std::string_view s) {
    auto it = to_code.find(s);
    if (it != to_code.end()) return it->second;
    int32_t code = static_cast<int32_t>(strings.size());
    add(s, code);
    return code;
  }
};

struct Decoder {
  std::vector<int32_t> kinds;
  // per-column slot within its kind group (numeric vs string)
  std::vector<int32_t> slot;
  int32_t n_numeric = 0;
  int32_t n_string = 0;
  std::vector<Dict> dicts;  // indexed by string slot
};

inline bool parse_int(const char* b, const char* e, int64_t* out) {
  if (b == e) { *out = 0; return true; }
  bool neg = false;
  if (*b == '-') { neg = true; ++b; }
  int64_t v = 0;
  for (; b != e; ++b) {
    if (*b < '0' || *b > '9') return false;
    v = v * 10 + (*b - '0');
  }
  *out = neg ? -v : v;
  return true;
}

}  // namespace

extern "C" {

void* fb_new(int32_t n_cols, const int32_t* kinds) {
  auto* d = new Decoder();
  d->kinds.assign(kinds, kinds + n_cols);
  d->slot.resize(n_cols);
  for (int32_t i = 0; i < n_cols; ++i) {
    if (kinds[i] == kString) {
      d->slot[i] = d->n_string++;
      d->dicts.emplace_back();
    } else {
      d->slot[i] = d->n_numeric++;
    }
  }
  return d;
}

void fb_seed(void* h, int32_t col, const char* s, int64_t len) {
  auto* d = static_cast<Decoder*>(h);
  Dict& dict = d->dicts[d->slot[col]];
  std::string_view sv(s, static_cast<size_t>(len));
  if (dict.to_code.find(sv) == dict.to_code.end()) {
    dict.add(sv, static_cast<int32_t>(dict.strings.size()));
  }
}

int64_t fb_decode(void* h, const char* buf, int64_t nbytes,
                  int64_t max_rows, int64_t* out_ints,
                  int32_t* out_codes) {
  auto* d = static_cast<Decoder*>(h);
  const int32_t n_cols = static_cast<int32_t>(d->kinds.size());
  const char* p = buf;
  const char* end = buf + nbytes;
  int64_t row = 0;

  while (p < end && row < max_rows) {
    const char* line_end =
        static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    if (line_end == p) { ++p; continue; }  // skip blank lines

    const char* f = p;
    for (int32_t c = 0; c < n_cols; ++c) {
      const char* f_end = static_cast<const char*>(
          memchr(f, '\t', line_end - f));
      if (f_end == nullptr) f_end = line_end;
      if (c == n_cols - 1) f_end = line_end;

      const int32_t slot = d->slot[c];
      switch (d->kinds[c]) {
        case kInt: {
          int64_t v;
          if (!parse_int(f, f_end, &v)) return -1 - row;
          out_ints[slot * max_rows + row] = v;
          break;
        }
        case kFloat: {
          // stored through the int64 plane; Python reinterprets
          char tmp[64];
          size_t n = static_cast<size_t>(f_end - f);
          if (n >= sizeof(tmp)) return -1 - row;
          memcpy(tmp, f, n);
          tmp[n] = 0;
          double v = (n == 0) ? 0.0 : strtod(tmp, nullptr);
          memcpy(&out_ints[slot * max_rows + row], &v, sizeof(double));
          break;
        }
        case kString: {
          std::string_view sv(f, static_cast<size_t>(f_end - f));
          out_codes[slot * max_rows + row] =
              d->dicts[slot].encode(sv);
          break;
        }
      }
      f = (f_end < line_end) ? f_end + 1 : line_end;
    }
    ++row;
    p = (line_end < end) ? line_end + 1 : end;
  }
  return row;
}

int64_t fb_dict_size(void* h, int32_t col) {
  auto* d = static_cast<Decoder*>(h);
  return static_cast<int64_t>(d->dicts[d->slot[col]].strings.size());
}

const char* fb_dict_get(void* h, int32_t col, int64_t idx,
                        int64_t* len) {
  auto* d = static_cast<Decoder*>(h);
  const std::string& s = d->dicts[d->slot[col]].strings[
      static_cast<size_t>(idx)];
  *len = static_cast<int64_t>(s.size());
  return s.data();
}

void fb_free(void* h) { delete static_cast<Decoder*>(h); }

}  // extern "C"
