// flowblock — native columnar ingest for theia_tpu.
//
// Plays the role of the reference's native ingest tier (ClickHouse's C++
// TabSeparated/native-protocol parsers receiving FlowAggregator inserts;
// schema contract build/charts/theia/provisioning/datasources/
// create_table.sh:31-84): decode TSV flow records straight into
// fixed-width columnar buffers with per-column dictionary encoding, so
// Python never touches row objects and the arrays are ready for
// jax.device_put.
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   fb_new(n_cols, kinds)        kinds[i]: 0 = int64, 1 = float64,
//                                2 = dictionary-encoded string
//   fb_seed(h, col, s, len)      append an existing dictionary entry
//                                (call in code order to mirror Python)
//   fb_decode(h, buf, nbytes, max_rows, out_ints, out_codes)
//                                parse rows; column-major outputs:
//                                out_ints [n_numeric][max_rows],
//                                out_codes [n_string][max_rows];
//                                returns rows decoded, or -1-row_index
//                                on a malformed row
//   fb_decode_block(h, buf, nbytes, max_rows, out_ints, out_codes)
//                                decode one binary columnar block (the
//                                "TFB1" format below — the analogue of
//                                ClickHouse's column-major native
//                                protocol): header, per-string-column
//                                dictionary delta, then raw column
//                                planes bulk-copied into the outputs.
//                                Returns rows, or a negative error code
//                                (-1 malformed, -2 dictionary desync,
//                                -3 outputs too small)
//   fb_dict_size(h, col)         current dictionary size
//   fb_dict_get(h, col, idx, &len) read one dictionary entry (for
//                                syncing codes minted here back into
//                                the Python StringDictionary)
//   fb_free(h)
//
// Build: g++ -O3 -shared -fPIC (driven by theia_tpu/ingest/native.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

enum Kind : int32_t { kInt = 0, kFloat = 1, kString = 2 };

struct Dict {
  // Stored strings own the bytes; the map's string_views point into
  // them. std::deque never reallocates existing elements.
  std::deque<std::string> strings;
  std::unordered_map<std::string_view, int32_t> to_code;

  Dict() { add("", 0); }

  void add(std::string_view s, int32_t code) {
    strings.emplace_back(s);
    to_code.emplace(std::string_view(strings.back()), code);
  }

  int32_t encode(std::string_view s) {
    auto it = to_code.find(s);
    if (it != to_code.end()) return it->second;
    int32_t code = static_cast<int32_t>(strings.size());
    add(s, code);
    return code;
  }
};

struct Decoder {
  std::vector<int32_t> kinds;
  // per-column slot within its kind group (numeric vs string)
  std::vector<int32_t> slot;
  int32_t n_numeric = 0;
  int32_t n_string = 0;
  std::vector<Dict> dicts;  // indexed by string slot
};

inline bool parse_int(const char* b, const char* e, int64_t* out) {
  if (b == e) { *out = 0; return true; }
  bool neg = false;
  if (*b == '-') { neg = true; ++b; }
  int64_t v = 0;
  for (; b != e; ++b) {
    if (*b < '0' || *b > '9') return false;
    v = v * 10 + (*b - '0');
  }
  *out = neg ? -v : v;
  return true;
}

// Walk + validate the dictionary-delta section shared by both block
// formats; advances *pp past the deltas without mutating any
// dictionary. Delta entries must be novel (not already in the
// dictionary, and not repeated within the delta) — a duplicate would
// grow `strings` without a matching to_code entry and desync the code
// sequence for good. Fills new_sizes (indexed by string slot) with the
// post-delta dictionary sizes. Returns 0, or -1 malformed / -2 desync /
// -5 duplicate entry.
int32_t validate_deltas(const Decoder* d, const char** pp,
                        const char* end,
                        std::vector<int32_t>* new_sizes) {
  const char* p = *pp;
  auto need = [&](int64_t n) { return end - p >= n; };
  const int32_t n_cols = static_cast<int32_t>(d->kinds.size());
  for (int32_t c = 0; c < n_cols; ++c) {
    if (d->kinds[c] != kString) continue;
    const Dict& dict = d->dicts[d->slot[c]];
    int32_t base, count;
    if (!need(8)) return -1;
    memcpy(&base, p, 4); p += 4;
    memcpy(&count, p, 4); p += 4;
    if (count < 0) return -1;
    if (base != static_cast<int32_t>(dict.strings.size())) return -2;
    std::unordered_map<std::string_view, int32_t> fresh;
    for (int32_t i = 0; i < count; ++i) {
      int32_t len;
      if (!need(4)) return -1;
      memcpy(&len, p, 4); p += 4;
      if (len < 0 || !need(len)) return -1;
      std::string_view sv(p, static_cast<size_t>(len));
      if (dict.to_code.find(sv) != dict.to_code.end()) return -5;
      if (!fresh.emplace(sv, i).second) return -5;
      p += len;
    }
    (*new_sizes)[d->slot[c]] = base + count;
  }
  *pp = p;
  return 0;
}

// Append the delta entries (assumes validate_deltas passed over the
// same bytes); advances *pp past the deltas.
void commit_deltas(Decoder* d, const char** pp) {
  const char* p = *pp;
  const int32_t n_cols = static_cast<int32_t>(d->kinds.size());
  for (int32_t c = 0; c < n_cols; ++c) {
    if (d->kinds[c] != kString) continue;
    Dict& dict = d->dicts[d->slot[c]];
    int32_t base, count;
    memcpy(&base, p, 4); p += 4;
    memcpy(&count, p, 4); p += 4;
    for (int32_t i = 0; i < count; ++i) {
      int32_t len;
      memcpy(&len, p, 4); p += 4;
      dict.add(std::string_view(p, static_cast<size_t>(len)),
               base + i);
      p += len;
    }
  }
  *pp = p;
}

}  // namespace

extern "C" {

void* fb_new(int32_t n_cols, const int32_t* kinds) {
  auto* d = new Decoder();
  d->kinds.assign(kinds, kinds + n_cols);
  d->slot.resize(n_cols);
  for (int32_t i = 0; i < n_cols; ++i) {
    if (kinds[i] == kString) {
      d->slot[i] = d->n_string++;
      d->dicts.emplace_back();
    } else {
      d->slot[i] = d->n_numeric++;
    }
  }
  return d;
}

void fb_seed(void* h, int32_t col, const char* s, int64_t len) {
  auto* d = static_cast<Decoder*>(h);
  Dict& dict = d->dicts[d->slot[col]];
  std::string_view sv(s, static_cast<size_t>(len));
  if (dict.to_code.find(sv) == dict.to_code.end()) {
    dict.add(sv, static_cast<int32_t>(dict.strings.size()));
  }
}

int64_t fb_decode(void* h, const char* buf, int64_t nbytes,
                  int64_t max_rows, int64_t* out_ints,
                  int32_t* out_codes) {
  auto* d = static_cast<Decoder*>(h);
  const int32_t n_cols = static_cast<int32_t>(d->kinds.size());
  const char* p = buf;
  const char* end = buf + nbytes;
  int64_t row = 0;

  while (p < end && row < max_rows) {
    const char* line_end =
        static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    if (line_end == p) { ++p; continue; }  // skip blank lines

    const char* f = p;
    for (int32_t c = 0; c < n_cols; ++c) {
      const char* f_end = static_cast<const char*>(
          memchr(f, '\t', line_end - f));
      if (f_end == nullptr) f_end = line_end;
      if (c == n_cols - 1) f_end = line_end;

      const int32_t slot = d->slot[c];
      switch (d->kinds[c]) {
        case kInt: {
          int64_t v;
          if (!parse_int(f, f_end, &v)) return -1 - row;
          out_ints[slot * max_rows + row] = v;
          break;
        }
        case kFloat: {
          // stored through the int64 plane; Python reinterprets
          char tmp[64];
          size_t n = static_cast<size_t>(f_end - f);
          if (n >= sizeof(tmp)) return -1 - row;
          memcpy(tmp, f, n);
          tmp[n] = 0;
          double v = (n == 0) ? 0.0 : strtod(tmp, nullptr);
          memcpy(&out_ints[slot * max_rows + row], &v, sizeof(double));
          break;
        }
        case kString: {
          std::string_view sv(f, static_cast<size_t>(f_end - f));
          out_codes[slot * max_rows + row] =
              d->dicts[slot].encode(sv);
          break;
        }
      }
      f = (f_end < line_end) ? f_end + 1 : line_end;
    }
    ++row;
    p = (line_end < end) ? line_end + 1 : end;
  }
  return row;
}

// Binary columnar block ("TFB1", little-endian):
//   "TFB1" | n_rows:i64 | n_cols:i32
//   per string column (schema order): base:i32 | count:i32 |
//       count x (len:i32 | bytes)     -- dictionary delta; `base` must
//                                        equal the decoder's current
//                                        dictionary size (codes are a
//                                        shared, append-only sequence)
//   per column (schema order): raw plane —
//       numeric: n_rows x 8 bytes (int64 / f64 through the int plane)
//       string:  n_rows x 4 bytes (int32 codes)
// Error codes: -1 malformed, -2 dictionary desync (delta base !=
// dictionary size), -3 outputs too small, -4 string code out of
// dictionary range, -5 delta repeats an existing or intra-delta entry.
// The block is fully validated BEFORE any dictionary mutation or
// output write, so a bad block leaves the decoder exactly as it was
// (no poisoned state).
int64_t fb_decode_block(void* h, const char* buf, int64_t nbytes,
                        int64_t max_rows, int64_t* out_ints,
                        int32_t* out_codes) {
  auto* d = static_cast<Decoder*>(h);
  const char* p = buf;
  const char* end = buf + nbytes;
  auto need = [&](int64_t n) { return end - p >= n; };

  if (!need(4) || memcmp(p, "TFB1", 4) != 0) return -1;
  p += 4;
  int64_t n_rows;
  int32_t n_cols;
  if (!need(12)) return -1;
  memcpy(&n_rows, p, 8); p += 8;
  memcpy(&n_cols, p, 4); p += 4;
  if (n_rows < 0 || n_cols != static_cast<int32_t>(d->kinds.size()))
    return -1;
  if (n_rows > max_rows) return -3;

  // -- validation pass: walk the whole block without mutating anything.
  const char* delta_start = p;
  std::vector<int32_t> new_sizes(d->dicts.size());
  if (int32_t err = validate_deltas(d, &p, end, &new_sizes)) return err;
  const char* planes_start = p;
  for (int32_t c = 0; c < n_cols; ++c) {
    const int64_t width = (d->kinds[c] == kString) ? 4 : 8;
    if (!need(n_rows * width)) return -1;
    if (d->kinds[c] == kString) {
      // every code must resolve against the post-delta dictionary
      const int32_t limit = new_sizes[d->slot[c]];
      for (int64_t r = 0; r < n_rows; ++r) {
        int32_t code;
        memcpy(&code, p + r * 4, 4);
        if (code < 0 || code >= limit) return -4;
      }
    }
    p += n_rows * width;
  }

  // -- commit pass: append dictionary deltas, bulk-copy planes.
  p = delta_start;
  commit_deltas(d, &p);
  p = planes_start;
  for (int32_t c = 0; c < n_cols; ++c) {
    const int32_t slot = d->slot[c];
    if (d->kinds[c] == kString) {
      memcpy(&out_codes[static_cast<int64_t>(slot) * max_rows], p,
             static_cast<size_t>(n_rows * 4));
      p += n_rows * 4;
    } else {
      memcpy(&out_ints[static_cast<int64_t>(slot) * max_rows], p,
             static_cast<size_t>(n_rows * 8));
      p += n_rows * 8;
    }
  }
  return n_rows;
}

// Binary columnar block v2 ("TFB2", little-endian) — the production
// wire format. Identical header + dictionary-delta layout to TFB1, but
// column planes carry each column's NATIVE width (widths[c] bytes per
// element: 1/2/4/8 for numerics, always 4 for string codes) and land
// directly in per-column output buffers (out_cols[c], allocated by the
// caller at the column's final dtype) — no 8-byte widening on the wire
// and no re-narrowing pass after decode. String-code validation runs
// over the copied (aligned) output plane so the compiler can vectorize
// the min/max scan instead of per-row unaligned loads.
// Error codes match fb_decode_block. Dictionary state is only mutated
// after every check passes; output buffers may hold partial data on
// error (callers discard them on raise).
int64_t fb_decode_block2(void* h, const char* buf, int64_t nbytes,
                         int64_t max_rows, const int32_t* widths,
                         void** out_cols) {
  auto* d = static_cast<Decoder*>(h);
  const char* p = buf;
  const char* end = buf + nbytes;
  auto need = [&](int64_t n) { return end - p >= n; };

  if (!need(4) || memcmp(p, "TFB2", 4) != 0) return -1;
  p += 4;
  int64_t n_rows;
  int32_t n_cols;
  if (!need(12)) return -1;
  memcpy(&n_rows, p, 8); p += 8;
  memcpy(&n_cols, p, 4); p += 4;
  if (n_rows < 0 || n_cols != static_cast<int32_t>(d->kinds.size()))
    return -1;
  if (n_rows > max_rows) return -3;

  // -- dictionary-delta validation pass (no mutation).
  const char* delta_start = p;
  std::vector<int32_t> new_sizes(d->dicts.size());
  if (int32_t err = validate_deltas(d, &p, end, &new_sizes)) return err;

  // -- plane copy + code validation (dicts still untouched).
  for (int32_t c = 0; c < n_cols; ++c) {
    const int64_t plane = n_rows * widths[c];
    if (widths[c] <= 0 || !need(plane)) return -1;
    if (d->kinds[c] == kString && widths[c] != 4) return -1;
    memcpy(out_cols[c], p, static_cast<size_t>(plane));
    if (d->kinds[c] == kString) {
      const int32_t* codes = static_cast<const int32_t*>(out_cols[c]);
      int32_t lo = 0, hi = -1;
      if (n_rows > 0) { lo = codes[0]; hi = codes[0]; }
      for (int64_t r = 1; r < n_rows; ++r) {
        const int32_t v = codes[r];
        lo = v < lo ? v : lo;
        hi = v > hi ? v : hi;
      }
      if (n_rows > 0 &&
          (lo < 0 || hi >= new_sizes[d->slot[c]])) return -4;
    }
    p += plane;
  }

  // -- commit: append dictionary deltas.
  p = delta_start;
  commit_deltas(d, &p);
  return n_rows;
}

int64_t fb_dict_size(void* h, int32_t col) {
  auto* d = static_cast<Decoder*>(h);
  return static_cast<int64_t>(d->dicts[d->slot[col]].strings.size());
}

const char* fb_dict_get(void* h, int32_t col, int64_t idx,
                        int64_t* len) {
  auto* d = static_cast<Decoder*>(h);
  const std::string& s = d->dicts[d->slot[col]].strings[
      static_cast<size_t>(idx)];
  *len = static_cast<int64_t>(s.size());
  return s.data();
}

void fb_free(void* h) { delete static_cast<Decoder*>(h); }

}  // extern "C"
