// Native series builder: group flow rows by an integer key tuple into
// padded per-series time arrays — the host tensorize step of the TAD
// job (theia_tpu/analytics/series.py). Replaces two numpy lexsorts
// (group_reduce + _pack_and_pad) with one hash-group pass + per-group
// sorts; semantics are bit-identical to the numpy path:
//
//   * duplicate (key, time) rows reduce with op (0 = max, 1 = sum) —
//     the reference job's max(throughput)/sum(throughput) stage
//     (plugins/anomaly-detection/anomaly_detection.py:507-614);
//   * series are emitted in lexicographic key order, points in time
//     order, padded to the longest series with a validity mask.
//
// Exposed via ctypes (no pybind11 in the image) from the same shared
// object as the flowblock decoder; see theia_tpu/ingest/native.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Builder {
  int64_t S = 0, T = 0, k = 0;
  std::vector<int64_t> group_keys;  // S*k, lexicographically sorted
  // per series: (time, value), time-sorted, duplicate times merged
  std::vector<std::vector<std::pair<int64_t, int64_t>>> series;
};

inline uint64_t hash_row(const int64_t* row, int64_t k) {
  uint64_t h = 1469598103934665603ull;  // FNV offset basis
  for (int64_t i = 0; i < k; ++i) {
    uint64_t x = static_cast<uint64_t>(row[i]);
    x *= 0xff51afd7ed558ccdull;  // splitmix-style scramble per word
    x ^= x >> 33;
    h ^= x;
    h *= 1099511628211ull;
  }
  return h;
}

}  // namespace

extern "C" {

// keys: [n, k] row-major int64; times/values: [n] int64.
// op: 0 = max, 1 = sum for duplicate (key, time) rows.
void* sb_build(const int64_t* keys, const int64_t* times,
               const int64_t* values, int64_t n, int64_t k, int32_t op) {
  auto* b = new Builder();
  b->k = k;
  if (n == 0) return b;

  // Open-addressing map: slot -> (representative row, group id).
  size_t cap = 1;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  std::vector<int64_t> slot_row(cap, -1);
  std::vector<int32_t> slot_gid(cap, -1);
  std::vector<int64_t> rep_rows;
  std::vector<std::vector<std::pair<int64_t, int64_t>>> groups;

  for (int64_t r = 0; r < n; ++r) {
    const int64_t* row = keys + r * k;
    uint64_t h = hash_row(row, k) & (cap - 1);
    int32_t gid = -1;
    for (;;) {
      if (slot_row[h] < 0) {
        gid = static_cast<int32_t>(groups.size());
        slot_row[h] = r;
        slot_gid[h] = gid;
        rep_rows.push_back(r);
        groups.emplace_back();
        break;
      }
      if (!memcmp(keys + slot_row[h] * k, row,
                  static_cast<size_t>(k) * sizeof(int64_t))) {
        gid = slot_gid[h];
        break;
      }
      h = (h + 1) & (cap - 1);
    }
    groups[gid].emplace_back(times[r], values[r]);
  }

  // Emit groups in lexicographic key order (np.lexsort parity).
  const int64_t S = static_cast<int64_t>(groups.size());
  std::vector<int32_t> order(S);
  for (int64_t i = 0; i < S; ++i) order[i] = static_cast<int32_t>(i);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t c) {
    const int64_t* ra = keys + rep_rows[a] * k;
    const int64_t* rc = keys + rep_rows[c] * k;
    for (int64_t i = 0; i < k; ++i)
      if (ra[i] != rc[i]) return ra[i] < rc[i];
    return false;
  });

  b->S = S;
  b->group_keys.resize(static_cast<size_t>(S) * k);
  b->series.resize(S);
  int64_t T = 0;
  for (int64_t gi = 0; gi < S; ++gi) {
    const int32_t g = order[gi];
    memcpy(&b->group_keys[gi * k], keys + rep_rows[g] * k,
           static_cast<size_t>(k) * sizeof(int64_t));
    auto& pts = groups[g];
    std::sort(pts.begin(), pts.end(),
              [](const std::pair<int64_t, int64_t>& x,
                 const std::pair<int64_t, int64_t>& y) {
                return x.first < y.first;
              });
    auto& out = b->series[gi];
    out.reserve(pts.size());
    for (const auto& p : pts) {
      if (!out.empty() && out.back().first == p.first) {
        if (op == 0)
          out.back().second = std::max(out.back().second, p.second);
        else
          out.back().second += p.second;
      } else {
        out.push_back(p);
      }
    }
    T = std::max<int64_t>(T, static_cast<int64_t>(out.size()));
  }
  b->T = T;
  return b;
}

void sb_dims(void* h, int64_t* S, int64_t* T) {
  auto* b = static_cast<Builder*>(h);
  *S = b->S;
  *T = b->T;
}

// out_keys: [S, k] int64; out_values: [S, T] double;
// out_times: [S, T] int64; out_mask: [S, T] uint8. Caller-allocated.
void sb_fill(void* h, int64_t* out_keys, double* out_values,
             int64_t* out_times, uint8_t* out_mask) {
  auto* b = static_cast<Builder*>(h);
  const int64_t S = b->S, T = b->T, k = b->k;
  if (S && k)
    memcpy(out_keys, b->group_keys.data(),
           static_cast<size_t>(S) * k * sizeof(int64_t));
  if (!S || !T) return;
  memset(out_values, 0, static_cast<size_t>(S) * T * sizeof(double));
  memset(out_times, 0, static_cast<size_t>(S) * T * sizeof(int64_t));
  memset(out_mask, 0, static_cast<size_t>(S) * T);
  for (int64_t s = 0; s < S; ++s) {
    const auto& pts = b->series[s];
    for (size_t t = 0; t < pts.size(); ++t) {
      out_values[s * T + t] = static_cast<double>(pts[t].second);
      out_times[s * T + t] = pts[t].first;
      out_mask[s * T + t] = 1;
    }
  }
}

void sb_free(void* h) { delete static_cast<Builder*>(h); }

}  // extern "C"
