// Native GROUP BY ... SUM for the materialized-view insert path.
//
// Plays the role of ClickHouse's SummingMergeTree per-insert-block
// aggregation (the three MVs at build/charts/theia/provisioning/
// datasources/create_table.sh:92-351): group an insert block by 9-20
// integer key columns and sum 6-8 metric columns. The numpy path needs
// a 15-20-key lexsort plus several full-matrix gathers; this is one
// hash-grouping pass with sums accumulated in place — no sort at all
// (part group order is irrelevant: exact lexsort-compaction happens at
// read time, where ClickHouse also collapses parts).
//
// C API (ctypes; same .so as flowblock/seriesbuild):
//   gs_build(key_cols, key_widths, n, k, val_cols, val_widths, m)
//       key_cols/val_cols: arrays of column pointers (column-major
//       input, no row-major staging copy in Python); widths are the
//       per-column element sizes in bytes (4 = int32, 8 = int64).
//       Returns a handle.
//   gs_dims(h, &g)            number of groups
//   gs_fill(h, out_keys, out_values)
//       out_keys [g,k] int64 row-major, out_values [g,m] int64.
//   gs_free(h)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct GroupSum {
  int64_t g = 0;
  int32_t k = 0, m = 0;
  std::vector<int64_t> keys;   // g*k, group-representative keys
  std::vector<int64_t> sums;   // g*m
};

inline int64_t read_cell(const void* col, int32_t width, int64_t r) {
  if (width == 8)
    return static_cast<const int64_t*>(col)[r];
  return static_cast<const int32_t*>(col)[r];  // width == 4
}

inline uint64_t mix(uint64_t x) {
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  return x;
}

}  // namespace

extern "C" {

void* gs_build(const void** key_cols, const int32_t* key_widths,
               int64_t n, int32_t k,
               const void** val_cols, const int32_t* val_widths,
               int32_t m) {
  auto* gs = new GroupSum();
  gs->k = k;
  gs->m = m;
  if (n == 0) return gs;

  // Pass 1: per-row key hash, computed COLUMNWISE — sequential reads
  // of each key column and sequential writes of hash[n]. (The previous
  // version staged keys row-major first: for k≈20 the c-strided writes
  // touched a fresh cache line per cell, and that staging dominated
  // the whole group-by.) Column order is applied identically for every
  // row, so the hash equals the row-major FNV of the same cells.
  std::vector<uint64_t> hash(n, 1469598103934665603ull);
  for (int32_t c = 0; c < k; ++c) {
    const int32_t w = key_widths[c];
    uint64_t* hp = hash.data();
    if (w == 8) {
      const int64_t* src = static_cast<const int64_t*>(key_cols[c]);
      for (int64_t r = 0; r < n; ++r)
        hp[r] = (hp[r] ^ mix(static_cast<uint64_t>(src[r])))
                * 1099511628211ull;
    } else {
      const int32_t* src = static_cast<const int32_t*>(key_cols[c]);
      for (int64_t r = 0; r < n; ++r)
        hp[r] = (hp[r] ^ mix(static_cast<uint64_t>(src[r])))
                * 1099511628211ull;
    }
  }

  size_t cap = 1;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  std::vector<int64_t> slot_row(cap, -1);   // representative row
  std::vector<int64_t> slot_gid(cap, -1);
  std::vector<uint64_t> slot_hash(cap, 0);

  // Pass 2: probe to a group id per row. Equality first checks the
  // full 64-bit hash, then compares cells straight from the original
  // columns (k scattered reads only on genuine hash match — nearly
  // always a real group hit).
  std::vector<int64_t> gid(n);
  // Worst case every row is its own group (true for the flows views,
  // whose keys include per-row timestamps) — preallocate so the
  // new-group path is a straight write, then shrink once at the end.
  gs->keys.resize(static_cast<size_t>(n) * k);
  for (int64_t r = 0; r < n; ++r) {
    const uint64_t hv = hash[r];
    size_t h = hv & (cap - 1);
    for (;;) {
      if (slot_row[h] < 0) {
        slot_row[h] = r;
        slot_gid[h] = gs->g;
        slot_hash[h] = hv;
        int64_t* dst = gs->keys.data() +
                       static_cast<size_t>(gs->g) * k;
        for (int32_t i = 0; i < k; ++i)
          dst[i] = read_cell(key_cols[i], key_widths[i], r);
        gid[r] = gs->g++;
        break;
      }
      if (slot_hash[h] == hv) {
        const int64_t rep = slot_row[h];
        bool eq = true;
        for (int32_t i = 0; i < k; ++i) {
          if (read_cell(key_cols[i], key_widths[i], r) !=
              read_cell(key_cols[i], key_widths[i], rep)) {
            eq = false;
            break;
          }
        }
        if (eq) {
          gid[r] = slot_gid[h];
          break;
        }
      }
      h = (h + 1) & (cap - 1);
    }
  }

  gs->keys.resize(static_cast<size_t>(gs->g) * k);

  // Pass 3: accumulate sums COLUMNWISE — each value column is read
  // sequentially; the accumulator rows are few and stay cache-hot.
  gs->sums.assign(static_cast<size_t>(gs->g) * m, 0);
  for (int32_t j = 0; j < m; ++j) {
    const int32_t w = val_widths[j];
    int64_t* sums = gs->sums.data() + j;
    if (w == 8) {
      const int64_t* src = static_cast<const int64_t*>(val_cols[j]);
      for (int64_t r = 0; r < n; ++r)
        sums[static_cast<size_t>(gid[r]) * m] += src[r];
    } else {
      const int32_t* src = static_cast<const int32_t*>(val_cols[j]);
      for (int64_t r = 0; r < n; ++r)
        sums[static_cast<size_t>(gid[r]) * m] += src[r];
    }
  }
  return gs;
}

void gs_dims(void* h, int64_t* g) {
  *g = static_cast<GroupSum*>(h)->g;
}

void gs_fill(void* h, int64_t* out_keys, int64_t* out_values) {
  auto* gs = static_cast<GroupSum*>(h);
  memcpy(out_keys, gs->keys.data(),
         gs->keys.size() * sizeof(int64_t));
  memcpy(out_values, gs->sums.data(),
         gs->sums.size() * sizeof(int64_t));
}

void gs_free(void* h) { delete static_cast<GroupSum*>(h); }

}  // extern "C"
