// Native GROUP BY ... SUM for the materialized-view insert path.
//
// Plays the role of ClickHouse's SummingMergeTree per-insert-block
// aggregation (the three MVs at build/charts/theia/provisioning/
// datasources/create_table.sh:92-351): group an insert block by 9-20
// integer key columns and sum 6-8 metric columns. The numpy path needs
// a 15-20-key lexsort plus several full-matrix gathers; this is one
// hash-grouping pass with sums accumulated in place — no sort at all
// (part group order is irrelevant: exact lexsort-compaction happens at
// read time, where ClickHouse also collapses parts).
//
// C API (ctypes; same .so as flowblock/seriesbuild):
//   gs_build(key_cols, key_widths, n, k, val_cols, val_widths, m)
//       key_cols/val_cols: arrays of column pointers (column-major
//       input, no row-major staging copy in Python); widths are the
//       per-column element sizes in bytes (4 = int32, 8 = int64).
//       Returns a handle.
//   gs_dims(h, &g)            number of groups
//   gs_fill(h, out_keys, out_values)
//       out_keys [g,k] int64 row-major, out_values [g,m] int64.
//   gs_free(h)

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct GroupSum {
  int64_t g = 0;
  int32_t k = 0, m = 0;
  std::vector<int64_t> keys;   // g*k, group-representative keys
  std::vector<int64_t> sums;   // g*m
};

inline int64_t read_cell(const void* col, int32_t width, int64_t r) {
  if (width == 8)
    return static_cast<const int64_t*>(col)[r];
  return static_cast<const int32_t*>(col)[r];  // width == 4
}

inline uint64_t mix(uint64_t x) {
  x *= 0xff51afd7ed558ccdull;
  x ^= x >> 33;
  return x;
}

}  // namespace

extern "C" {

void* gs_build(const void** key_cols, const int32_t* key_widths,
               int64_t n, int32_t k,
               const void** val_cols, const int32_t* val_widths,
               int32_t m) {
  auto* gs = new GroupSum();
  gs->k = k;
  gs->m = m;
  if (n == 0) return gs;

  // Stage keys row-major once (C loop beats k numpy astype+stack).
  std::vector<int64_t> rows(static_cast<size_t>(n) * k);
  for (int32_t c = 0; c < k; ++c) {
    const void* col = key_cols[c];
    const int32_t w = key_widths[c];
    int64_t* out = rows.data() + c;
    if (w == 8) {
      const int64_t* src = static_cast<const int64_t*>(col);
      for (int64_t r = 0; r < n; ++r) out[r * k] = src[r];
    } else {
      const int32_t* src = static_cast<const int32_t*>(col);
      for (int64_t r = 0; r < n; ++r) out[r * k] = src[r];
    }
  }

  size_t cap = 1;
  while (cap < static_cast<size_t>(n) * 2) cap <<= 1;
  std::vector<int64_t> slot_row(cap, -1);   // representative row
  std::vector<int64_t> slot_gid(cap, -1);

  gs->keys.reserve(static_cast<size_t>(n) * k / 4);
  gs->sums.reserve(static_cast<size_t>(n) * m / 4);
  for (int64_t r = 0; r < n; ++r) {
    const int64_t* row = rows.data() + r * k;
    uint64_t h = 1469598103934665603ull;
    for (int32_t i = 0; i < k; ++i) {
      h ^= mix(static_cast<uint64_t>(row[i]));
      h *= 1099511628211ull;
    }
    h &= cap - 1;
    int64_t gid;
    for (;;) {
      if (slot_row[h] < 0) {
        gid = gs->g++;
        slot_row[h] = r;
        slot_gid[h] = gid;
        gs->keys.insert(gs->keys.end(), row, row + k);
        gs->sums.insert(gs->sums.end(), m, 0);
        break;
      }
      if (!memcmp(rows.data() + slot_row[h] * k, row,
                  static_cast<size_t>(k) * sizeof(int64_t))) {
        gid = slot_gid[h];
        break;
      }
      h = (h + 1) & (cap - 1);
    }
    int64_t* acc = gs->sums.data() + gid * m;
    for (int32_t j = 0; j < m; ++j)
      acc[j] += read_cell(val_cols[j], val_widths[j], r);
  }
  return gs;
}

void gs_dims(void* h, int64_t* g) {
  *g = static_cast<GroupSum*>(h)->g;
}

void gs_fill(void* h, int64_t* out_keys, int64_t* out_values) {
  auto* gs = static_cast<GroupSum*>(h);
  memcpy(out_keys, gs->keys.data(),
         gs->keys.size() * sizeof(int64_t));
  memcpy(out_values, gs->sums.data(),
         gs->sums.size() * sizeof(int64_t));
}

void gs_free(void* h) { delete static_cast<GroupSum*>(h); }

}  // extern "C"
