"""Vectorized query engine (theia_tpu/query/).

The load-bearing contract is the PARITY ORACLE: for any plan, the
parts engine (pruned, encoded-space, late-materializing, possibly
jax-kerneled), the flat engine (reference executor over a scan), and
the standalone pure-numpy reference must answer BIT-IDENTICALLY —
through seals, merges, deletes, TTL, demotion to the cold tier, and
cache hits. Plus the machinery around it: plan validation, min/max +
dictionary-code pruning, cold parts streaming without promotion,
column-subset part-file decode, the cold small-part merge pass, the
fingerprint-keyed result cache, the admission ladder's query rung,
and the /query HTTP surface.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from theia_tpu.data.synth import SynthConfig, generate_flows
from theia_tpu.query import (PlanError, QueryEngine, parse_plan,
                             plan_from_params, reference_execute)
from theia_tpu.query import kernels as qkernels
from theia_tpu.schema import FLOW_SCHEMA, ColumnarBatch
from theia_tpu.store import FlowDatabase, ShardedFlowDatabase
from theia_tpu.store.parts import read_part_file

pytestmark = pytest.mark.query


def _batch(n_series=20, points=10, seed=0, shift=0):
    b = generate_flows(SynthConfig(n_series=n_series,
                                   points_per_series=points,
                                   seed=seed))
    if shift:
        for col in ("timeInserted", "flowStartSeconds",
                    "flowEndSeconds"):
            b.columns[col] = b[col] + shift
    return b


def _pair(tmp_path=None, memtable_rows=128, ttl_seconds=None, **cfg):
    parts_cfg = {"memtable_rows": memtable_rows, **cfg}
    flat = FlowDatabase(engine="flat", ttl_seconds=ttl_seconds)
    parts = FlowDatabase(
        engine="parts", ttl_seconds=ttl_seconds,
        parts_dir=str(tmp_path / "parts") if tmp_path else None,
        parts_config=parts_cfg)
    return flat, parts


def _assert_same_answer(plan, flat, parts, check_reference=True):
    """The parity oracle: parts engine == flat engine == pure-numpy
    reference, bit for bit (ints compare exactly; means come from the
    same int sums + one float64 division, so == is exact too)."""
    rf = QueryEngine(flat).execute(plan, use_cache=False)
    rp = QueryEngine(parts).execute(plan, use_cache=False)
    assert rf["rows"] == rp["rows"], (rf["rows"][:3], rp["rows"][:3])
    assert rf["groupCount"] == rp["groupCount"]
    if check_reference:
        rows_ref, groups_ref, _ = reference_execute(
            plan, flat.flows.scan(), flat.flows.dicts)
        assert rows_ref == rf["rows"]
        assert groups_ref == rf["groupCount"]
    return rp


# -- plan parsing ---------------------------------------------------------


def test_plan_validation_errors():
    with pytest.raises(PlanError):
        parse_plan({"groupBy": "noSuchColumn"})
    with pytest.raises(PlanError):
        parse_plan({"agg": "sum:noSuchColumn"})
    with pytest.raises(PlanError):
        parse_plan({"agg": "median:throughput"})
    with pytest.raises(PlanError):
        parse_plan({"agg": "sum:sourceIP"})   # string aggregation
    with pytest.raises(PlanError):
        parse_plan({"filters": [{"column": "sourceIP", "op": ">=",
                                 "value": "x"}]})
    with pytest.raises(PlanError):
        parse_plan({"filters": [{"column": "throughput", "op": "in",
                                 "value": []}]})
    with pytest.raises(PlanError):
        parse_plan({"agg": "count", "orderBy": "sum(throughput)"})
    with pytest.raises(PlanError):
        parse_plan({"k": -1})
    with pytest.raises(PlanError):
        parse_plan({"groupBy": "sourceIP,sourceIP"})
    # a string column cannot anchor the time window (it would die
    # inside the encoded-part evaluator as a 500 instead of a 400)
    with pytest.raises(PlanError):
        parse_plan({"timeColumn": "sourceIP", "start": 5})
    with pytest.raises(PlanError):
        parse_plan({"endColumn": "tcpState", "end": 5})


def test_plan_normalization_is_spelling_invariant():
    a = parse_plan({
        "groupBy": ["sourceIP"],
        "aggregates": [{"op": "sum", "column": "throughput"}],
        "filters": [
            {"column": "destinationTransportPort", "op": ">=",
             "value": 10},
            {"column": "sourceIP", "op": "=", "value": "a"}]})
    b = parse_plan({
        "groupBy": "sourceIP",
        "agg": "sum:throughput",
        "filters": [
            {"column": "sourceIP", "op": "eq", "value": "a"},
            {"column": "destinationTransportPort", "op": "ge",
             "value": "10"}]})
    assert a.normalized() == b.normalized()
    assert a.fingerprint() == b.fingerprint()


def test_plan_from_get_params_matches_post_body():
    via_get = plan_from_params({
        "group_by": "sourceIP,destinationIP",
        "agg": "sum:octetDeltaCount,count",
        "where": "destinationTransportPort:ge:100;sourceIP:eq:10.0.0.1",
        "start": "5", "end": "99", "k": "7"})
    via_post = parse_plan({
        "groupBy": ["sourceIP", "destinationIP"],
        "aggregates": ["sum:octetDeltaCount", "count"],
        "filters": [
            {"column": "destinationTransportPort", "op": ">=",
             "value": 100},
            {"column": "sourceIP", "op": "=", "value": "10.0.0.1"}],
        "start": 5, "end": 99, "k": 7})
    assert via_get.normalized() == via_post.normalized()


def test_plan_columns_touched():
    plan = parse_plan({"groupBy": "sourceIP",
                       "agg": "sum:octetDeltaCount",
                       "filters": [{"column": "tcpState", "op": "=",
                                    "value": "ESTABLISHED"}],
                       "start": 1, "end": 2})
    touched = plan.columns_touched()
    assert set(touched) == {"sourceIP", "octetDeltaCount", "tcpState",
                            "flowStartSeconds", "flowEndSeconds"}


# -- engine parity --------------------------------------------------------


def test_groupby_parity_flat_parts_reference():
    flat, parts = _pair()
    for i in range(4):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    plan = parse_plan({
        "groupBy": "sourceIP,destinationIP",
        "aggregates": ["sum:octetDeltaCount", "count",
                       "mean:throughput", "min:packetDeltaCount",
                       "max:packetDeltaCount"],
        "k": 0})
    out = _assert_same_answer(plan, flat, parts)
    assert out["engine"] == "parts"
    assert out["groupCount"] > 1


def test_global_aggregate_and_empty_window():
    flat, parts = _pair()
    b = _batch()
    flat.insert_flows(b)
    parts.insert_flows(b)
    # global (no group-by)
    plan = parse_plan({"agg": ["count", "sum:octetDeltaCount",
                               "mean:throughput"]})
    out = _assert_same_answer(plan, flat, parts)
    assert out["rows"][0]["count"] == len(b)
    # empty window: one zero row globally, no rows grouped
    empty = parse_plan({"agg": "count", "start": 0, "end": 1})
    out = _assert_same_answer(empty, flat, parts)
    assert out["rows"] == [{"count": 0}]
    gempty = parse_plan({"groupBy": "sourceIP", "agg": "count",
                         "start": 0, "end": 1})
    out = _assert_same_answer(gempty, flat, parts)
    assert out["rows"] == []


def test_string_filters_eq_ne_in_and_unknown_value():
    flat, parts = _pair()
    for i in range(3):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    some_ip = flat.flows.dicts["sourceIP"].decode_one(
        int(flat.flows.scan()["sourceIP"][0]))
    for filters in (
            [{"column": "sourceIP", "op": "=", "value": some_ip}],
            [{"column": "sourceIP", "op": "!=", "value": some_ip}],
            [{"column": "sourceIP", "op": "in",
              "value": [some_ip, "10.99.99.99"]}],
            # unknown value: eq matches nothing, ne matches everything
            [{"column": "sourceIP", "op": "=", "value": "nope"}],
            [{"column": "sourceIP", "op": "!=", "value": "nope"}]):
        plan = parse_plan({"groupBy": "destinationIP", "agg": "count",
                           "filters": filters, "k": 0})
        _assert_same_answer(plan, flat, parts)


def test_numeric_filters_encoded_space_thresholds():
    """Width-reduced compare: thresholds inside, below, and above the
    narrow stored range — the clamp logic must agree with the decoded
    reference bit for bit."""
    flat, parts = _pair()
    for i in range(2):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    port = int(flat.flows.scan()["destinationTransportPort"][0])
    cases = [
        [{"column": "destinationTransportPort", "op": ">=",
          "value": port}],
        [{"column": "destinationTransportPort", "op": "<",
          "value": port}],
        [{"column": "destinationTransportPort", "op": "=",
          "value": port}],
        [{"column": "destinationTransportPort", "op": "!=",
          "value": port}],
        [{"column": "destinationTransportPort", "op": "in",
          "value": [port, 1, 10 ** 12]}],
        # far outside any narrow range, both directions
        [{"column": "octetDeltaCount", "op": ">=", "value": -10 ** 15}],
        [{"column": "octetDeltaCount", "op": ">=", "value": 10 ** 15}],
        [{"column": "octetDeltaCount", "op": "<", "value": -10 ** 15}],
        [{"column": "octetDeltaCount", "op": "<", "value": 10 ** 15}],
        [{"column": "octetDeltaCount", "op": "=", "value": 10 ** 15}],
    ]
    for filters in cases:
        plan = parse_plan({"groupBy": "sourceIP", "agg": "count",
                           "filters": filters, "k": 0})
        _assert_same_answer(plan, flat, parts)


def test_numeric_groupby_widens_with_base():
    flat, parts = _pair()
    for i in range(2):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    plan = parse_plan({"groupBy": "destinationTransportPort,sourceIP",
                       "agg": ["count", "sum:octetDeltaCount"],
                       "k": 0})
    _assert_same_answer(plan, flat, parts)


def test_time_window_parity_and_pruning_counters():
    flat, parts = _pair()
    for i in range(3):
        b = _batch(seed=i, shift=i * 24 * 3600)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    lo = int(flat.flows.scan()["flowStartSeconds"].min())
    plan = parse_plan({"groupBy": "sourceIP", "agg": "count",
                       "start": lo, "end": lo + 3600, "k": 0})
    out = _assert_same_answer(plan, flat, parts)
    assert out["partsPruned"] >= 2, out
    assert out["partsScanned"] >= 1


def test_string_filter_code_set_prunes_whole_parts():
    """A string eq whose code set misses a part's unique codes skips
    the part without touching a row."""
    flat, parts = _pair()
    a = _batch(seed=1)
    flat.insert_flows(a)
    parts.insert_flows(a)
    parts.flows.seal()
    # rows whose sourceIP only exists in the SECOND part
    rows = [{"timeInserted": 1, "flowStartSeconds": 1,
             "flowEndSeconds": 2, "sourceIP": "192.168.77.1",
             "destinationIP": "10.0.0.1", "octetDeltaCount": 5}]
    flat.insert_flow_rows(rows)
    parts.insert_flow_rows(rows)
    parts.flows.seal()
    plan = parse_plan({"groupBy": "destinationIP", "agg": "count",
                       "filters": [{"column": "sourceIP", "op": "=",
                                    "value": "192.168.77.1"}],
                       "k": 0})
    out = _assert_same_answer(plan, flat, parts)
    # the first part's unique-code set misses the value → it counts
    # as PRUNED (dictionary-code pruning), not scanned
    assert out["partsPruned"] >= 1, out
    # duplicate values in an `in` list must not trip the
    # assume_unique intersection
    dup = parse_plan({"groupBy": "destinationIP", "agg": "count",
                      "filters": [{"column": "sourceIP", "op": "in",
                                   "value": ["192.168.77.1",
                                             "192.168.77.1"]}],
                      "k": 0})
    _assert_same_answer(dup, flat, parts)


def test_randomized_oracle_with_deletes_ttl_demotion(tmp_path, rng):
    """The gate: random inserts, value deletes, TTL eviction, forced
    demotion — then a battery of plans, all three answers identical."""
    flat, parts = _pair(tmp_path, memtable_rows=96, ttl_seconds=48 * 3600)
    t0 = None
    for i in range(5):
        b = _batch(n_series=int(rng.integers(10, 40)),
                   points=int(rng.integers(4, 12)),
                   seed=int(rng.integers(0, 1000)),
                   shift=i * 3600)
        if t0 is None:
            t0 = int(b["timeInserted"].min())
        now = int(b["timeInserted"].max())
        flat.insert_flows(b, now=now)
        parts.insert_flows(b, now=now)
        if i == 2:
            # value-based delete through the dictionary
            ip = flat.flows.dicts["sourceIP"].decode_one(
                int(flat.flows.scan()["sourceIP"][-1]))
            flat.flows.delete_ids([ip], column="sourceIP")
            parts.flows.delete_ids([ip], column="sourceIP")
        if i == 3:
            parts.flows.seal()
            parts.flows.demote_oldest(0)   # everything cold
    assert parts.flows.parts_stats()["cold"] >= 1
    some_ip = flat.flows.dicts["destinationIP"].decode_one(
        int(flat.flows.scan()["destinationIP"][0]))
    plans = [
        {"groupBy": "sourceIP", "agg": "sum:octetDeltaCount", "k": 0},
        {"groupBy": "sourceIP,destinationIP",
         "agg": ["count", "mean:throughput"], "k": 5},
        {"groupBy": "destinationIP",
         "agg": ["min:flowStartSeconds", "max:flowEndSeconds"],
         "k": 0},
        {"agg": ["count", "sum:reverseOctetDeltaCount"]},
        {"groupBy": "ingressNetworkPolicyName", "agg": "count",
         "filters": [{"column": "destinationIP", "op": "=",
                      "value": some_ip}], "k": 0},
        {"groupBy": "sourceIP", "agg": "sum:throughput",
         "start": t0 + 1800, "end": t0 + 3 * 3600,
         "timeColumn": "timeInserted", "endColumn": "timeInserted",
         "k": 0},
        {"groupBy": "destinationTransportPort", "agg": "count",
         "filters": [{"column": "octetDeltaCount", "op": ">=",
                      "value": 1000}], "k": 0},
    ]
    for doc in plans:
        _assert_same_answer(parse_plan(doc), flat, parts)
    # no read above promoted a demoted part (cold stays fileless)
    assert all(p.chunks is None for p in parts.flows._parts
               if p.tier == "cold")


def test_topk_ordering_is_deterministic():
    flat, parts = _pair()
    b = _batch(seed=7)
    flat.insert_flows(b)
    parts.insert_flows(b)
    plan = parse_plan({"groupBy": "sourceIP", "agg": "count", "k": 3})
    r1 = QueryEngine(parts).execute(plan, use_cache=False)
    r2 = QueryEngine(parts).execute(plan, use_cache=False)
    assert r1["rows"] == r2["rows"]
    counts = [r["count"] for r in r1["rows"]]
    assert counts == sorted(counts, reverse=True)
    assert len(r1["rows"]) == 3


# -- cold tier ------------------------------------------------------------


def test_cold_query_streams_without_promotion(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(3):
        b = _batch(seed=i, shift=i * 3600)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    parts.flows.demote_oldest(0)
    before = parts.flows.parts_stats()
    assert before["hotBytes"] == 0 and before["cold"] >= 3
    lo = int(flat.flows.scan()["flowStartSeconds"].min())
    plan = parse_plan({"groupBy": "sourceIP",
                       "agg": "sum:octetDeltaCount",
                       "start": lo, "end": lo + 2 * 3600, "k": 0})
    engine = QueryEngine(parts, cold_buffer=1)
    out = engine.execute(plan)
    rf = QueryEngine(flat).execute(plan)
    assert out["rows"] == rf["rows"]
    # the acceptance check: tier residency unchanged — no cold part
    # was promoted back to RAM by the scan
    after = parts.flows.parts_stats()
    assert after["hotBytes"] == before["hotBytes"] == 0
    assert after["cold"] == before["cold"]
    assert all(p.chunks is None for p in parts.flows._parts)


def test_cold_global_count_touches_no_plan_columns(tmp_path):
    """A bare global count has an EMPTY column-touch set; the cold
    path must still carry the row count (regression: subset decode of
    zero columns yields zero rows)."""
    flat, parts = _pair(tmp_path, memtable_rows=64)
    b = _batch(seed=4)
    flat.insert_flows(b)
    parts.insert_flows(b)
    parts.flows.seal()
    parts.flows.demote_oldest(0)
    plan = parse_plan({"agg": "count"})
    out = _assert_same_answer(plan, flat, parts)
    assert out["rows"] == [{"count": len(b)}]
    assert parts.flows.parts_stats()["hotBytes"] == 0


def test_cold_part_column_subset_decode(tmp_path):
    _, parts = _pair(tmp_path, memtable_rows=64)
    parts.insert_flows(_batch(seed=3))
    parts.flows.seal()
    part = parts.flows._parts[0]
    full = read_part_file(part.path)
    sub = read_part_file(part.path,
                         columns=["sourceIP", "octetDeltaCount"])
    assert set(sub.columns) == {"sourceIP", "octetDeltaCount"}
    np.testing.assert_array_equal(sub["octetDeltaCount"],
                                  full["octetDeltaCount"])
    np.testing.assert_array_equal(
        sub.dicts["sourceIP"].decode(sub["sourceIP"]),
        full.dicts["sourceIP"].decode(full["sourceIP"]))


def test_projected_select_parity_including_cold(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(2):
        b = _batch(seed=i, shift=i * 3600)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    parts.flows.demote_oldest(0)
    lo = int(flat.flows.scan()["flowStartSeconds"].min())
    cols = ["sourceIP", "octetDeltaCount"]
    sf = flat.flows.select(start_time=lo, end_time=lo + 3600,
                           columns=cols)
    sp = parts.flows.select(start_time=lo, end_time=lo + 3600,
                            columns=cols)
    assert set(sf.columns) == set(sp.columns) == set(cols)
    np.testing.assert_array_equal(sf["octetDeltaCount"],
                                  sp["octetDeltaCount"])
    np.testing.assert_array_equal(sf.strings("sourceIP"),
                                  sp.strings("sourceIP"))
    # projection did not promote anything
    assert parts.flows.parts_stats()["hotBytes"] == 0


def test_cold_small_parts_merge_on_disk(tmp_path):
    """Satellite fix: adjacent small SAME-PARTITION cold parts
    coalesce on disk (previously only hot parts merged, so a
    long-retention tier accumulated tiny files forever) — without
    promoting a byte back to RAM."""
    flat, parts = _pair(tmp_path, memtable_rows=64, part_rows=4096)
    for i in range(4):
        b = _batch(seed=i)     # same hour partition
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    parts.flows.demote_oldest(0)
    before = parts.flows.parts_stats()
    assert before["cold"] >= 4 and before["hotBytes"] == 0
    merges = parts.flows.maintain()
    after = parts.flows.parts_stats()
    assert merges >= 1
    assert after["coldMerges"] >= 1
    assert after["cold"] < before["cold"]
    assert after["hotBytes"] == 0          # never promoted
    assert all(p.tier == "cold" and p.chunks is None
               for p in parts.flows._parts)
    # byte-identical content after the disk rewrite
    a, b = flat.flows.scan(), parts.flows.scan()
    assert len(a) == len(b)
    for c in FLOW_SCHEMA:
        np.testing.assert_array_equal(np.asarray(a[c.name]),
                                      np.asarray(b[c.name]),
                                      err_msg=c.name)
    # old files are retired at the next gc; the new file exists
    assert all(os.path.exists(p.path) for p in parts.flows._parts)


def test_hot_merge_still_works_and_cold_skipped_without_dir():
    _, parts = _pair(None, memtable_rows=64, part_rows=4096)
    for i in range(4):
        parts.insert_flows(_batch(seed=i))
    parts.flows.seal()
    assert parts.flows.parts_stats()["count"] >= 2
    merges = parts.flows.maintain()
    st = parts.flows.parts_stats()
    assert merges >= 1 and st["merges"] >= 1
    assert st["coldMerges"] == 0   # no directory → no cold tier


# -- result cache ---------------------------------------------------------


def test_cache_hit_and_structural_invalidation(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=64, part_rows=4096)
    for i in range(3):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    engine = QueryEngine(parts)
    plan = parse_plan({"groupBy": "sourceIP", "agg": "count", "k": 0})
    first = engine.execute(plan)
    assert first["cache"] == "miss"
    hit = engine.execute(plan)
    assert hit["cache"] == "hit" and hit["rows"] == first["rows"]
    # an insert moves the fingerprint
    parts.insert_flows(_batch(seed=9))
    after_insert = engine.execute(plan)
    assert after_insert["cache"] == "miss"
    # a merge moves the fingerprint but not the answer
    parts.flows.seal()
    warmed = engine.execute(plan)
    assert engine.execute(plan)["cache"] == "hit"
    assert parts.flows.maintain() >= 1
    after_merge = engine.execute(plan)
    assert after_merge["cache"] == "miss"
    assert after_merge["rows"] == warmed["rows"]
    # demotion moves the fingerprint too (tier is part of the key)
    assert engine.execute(plan)["cache"] == "hit"
    parts.flows.demote_oldest(0)
    after_demote = engine.execute(plan)
    assert after_demote["cache"] == "miss"
    assert after_demote["rows"] == warmed["rows"]
    stats = engine.cache.stats()
    assert stats["hits"] >= 3 and stats["misses"] >= 4


def test_cache_bounded_by_bytes():
    flat, parts = _pair()
    parts.insert_flows(_batch(seed=1))
    engine = QueryEngine(parts, cache_bytes=1)   # nothing fits
    plan = parse_plan({"groupBy": "sourceIP", "agg": "count", "k": 0})
    engine.execute(plan)
    assert engine.execute(plan)["cache"] == "miss"
    assert engine.cache.stats()["entries"] == 0


# -- kernels --------------------------------------------------------------


def test_kernels_jax_numpy_bit_parity(monkeypatch, rng):
    keys = rng.integers(0, 50, size=(4096, 2)).astype(np.int64)
    values = {
        "a": rng.integers(-(10 ** 12), 10 ** 12, 4096).astype(np.int64),
        "b": rng.integers(0, 10 ** 9, 4096).astype(np.int64)}
    specs = [("count", "count", None), ("sum(a)", "sum", "a"),
             ("min(b)", "min", "b"), ("max(a)", "max", "a")]
    monkeypatch.setenv("THEIA_QUERY_JAX", "0")
    uk_np, agg_np = qkernels.aggregate(keys, values, specs)
    assert qkernels.kernel_mode() == "numpy"
    monkeypatch.setenv("THEIA_QUERY_JAX", "1")
    assert qkernels.kernel_mode() == "jax"
    uk_jx, agg_jx = qkernels.aggregate(keys, values, specs)
    np.testing.assert_array_equal(uk_np, uk_jx)
    for label, _, _ in specs:
        np.testing.assert_array_equal(agg_np[label], agg_jx[label],
                                      err_msg=label)


def test_kernel_mode_auto_respects_x64():
    # conftest enables x64 on the CPU test config, so auto → jax here
    import jax
    expected = "jax" if jax.config.jax_enable_x64 else "numpy"
    assert qkernels.kernel_mode() in (expected, "numpy")


def test_merge_partials_semantics(rng):
    specs = [("count", "count", None), ("sum(a)", "sum", "a"),
             ("min(a)", "min", "a"), ("max(a)", "max", "a")]
    keys = rng.integers(0, 10, size=(512, 1)).astype(np.int64)
    vals = {"a": rng.integers(-100, 100, 512).astype(np.int64)}
    whole_k, whole = qkernels.aggregate(keys, vals, specs)
    half_a = qkernels.aggregate(keys[:200], {"a": vals["a"][:200]},
                                specs)
    half_b = qkernels.aggregate(keys[200:], {"a": vals["a"][200:]},
                                specs)
    merged_k, merged = qkernels.merge_partials([half_a, half_b], specs)
    np.testing.assert_array_equal(whole_k, merged_k)
    for label, _, _ in specs:
        np.testing.assert_array_equal(whole[label], merged[label],
                                      err_msg=label)


# -- sharded stores -------------------------------------------------------


def test_cold_merge_gc_gives_readers_a_grace_pass(tmp_path):
    """A reader that snapshotted the part list just before a cold
    merge retired a run must still be able to decode those files: the
    manifest-less maintenance GC unlinks a file only after TWO
    consecutive passes found it unreferenced."""
    _, parts = _pair(tmp_path, memtable_rows=64, part_rows=4096)
    for i in range(4):
        parts.insert_flows(_batch(seed=i))
    parts.flows.seal()
    parts.flows.demote_oldest(0)
    held, _ = parts.flows._snapshot_refs()   # a slow reader's view
    assert parts.flows.maintain() >= 1       # cold merge + GC pass 1
    # the retired files survive the first pass — the reader can
    # still stream every part it captured
    total = sum(len(parts.flows._decode_part(p)) for p in held)
    assert total == sum(p.rows for p in held)
    # the NEXT pass (reader gone) collects them
    parts.flows.maintain()
    import glob
    live = {os.path.basename(p.path) for p in parts.flows._parts}
    on_disk = {os.path.basename(f) for f in
               glob.glob(str(tmp_path / "parts" / "part-*.tprt"))}
    assert on_disk == live


def test_sharded_numeric_groupby_tiebreak_matches_plain():
    """Equal aggregate values tie-break by the NUMERIC key value in
    the sharded merge path too (an object-dtype key column would
    compare '80' < '9' as strings)."""
    rows = [{"timeInserted": 1, "flowStartSeconds": 1,
             "flowEndSeconds": 2, "destinationTransportPort": port,
             "octetDeltaCount": 12}
            for port in (80, 9)]
    plain = FlowDatabase(engine="flat")
    plain.insert_flow_rows(rows)
    sharded = ShardedFlowDatabase(n_shards=2, seed=3)
    sharded.insert_flow_rows(rows)
    plan = parse_plan({"groupBy": "destinationTransportPort",
                       "agg": "sum:octetDeltaCount", "k": 1})
    rp = QueryEngine(plain).execute(plan, use_cache=False)
    rs = QueryEngine(sharded).execute(plan, use_cache=False)
    assert rp["rows"] == rs["rows"]
    assert rp["rows"][0]["destinationTransportPort"] == 9


def test_sharded_query_merges_across_dictionaries():
    db = ShardedFlowDatabase(n_shards=3, seed=11)
    b = _batch(seed=5, n_series=30)
    db.insert_flows(b)
    plan = parse_plan({"groupBy": "sourceIP,destinationIP",
                       "aggregates": ["count", "sum:octetDeltaCount",
                                      "min:throughput"],
                       "k": 0})
    out = QueryEngine(db).execute(plan, use_cache=False)
    scan = db.flows.scan()   # concat reconciles shard dictionaries
    rows_ref, groups_ref, _ = reference_execute(plan, scan, scan.dicts)
    assert out["rows"] == rows_ref
    assert out["groupCount"] == groups_ref


# -- admission ladder -----------------------------------------------------


def test_admission_query_rung(monkeypatch):
    from theia_tpu.manager.admission import (AdmissionController,
                                             AdmissionRejected)
    adm = AdmissionController(rate=1e9)
    assert adm.admit_query() == 0
    monkeypatch.setenv("THEIA_ADMISSION_FORCE_LEVEL", "sampled")
    assert adm.admit_query() == 1     # sampled still serves queries
    monkeypatch.setenv("THEIA_ADMISSION_FORCE_LEVEL", "shed_detector")
    with pytest.raises(AdmissionRejected) as e:
        adm.admit_query()
    assert e.value.reason == "query_shed"
    monkeypatch.setenv("THEIA_ADMISSION_FORCE_LEVEL", "reject")
    with pytest.raises(AdmissionRejected):
        adm.admit_query()


# -- HTTP surface ---------------------------------------------------------


@pytest.fixture()
def server(monkeypatch):
    from theia_tpu.manager import TheiaManagerServer
    monkeypatch.setenv("THEIA_RETENTION_INTERVAL", "0")
    db = FlowDatabase(engine="parts",
                      parts_config={"memtable_rows": 256})
    for i in range(2):
        db.insert_flows(_batch(seed=i, n_series=30))
    srv = TheiaManagerServer(db, port=0, workers=1)
    srv.start_background()
    yield srv
    srv.shutdown()


def _get_json(srv, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=10) as r:
        return json.loads(r.read())


def test_query_http_get_post_and_errors(server, monkeypatch):
    body = {"groupBy": "sourceIP",
            "aggregates": ["sum:octetDeltaCount", "count"], "k": 5}
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/query",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        posted = json.loads(r.read())
    assert posted["engine"] == "parts" and len(posted["rows"]) == 5
    got = _get_json(server,
                    "/query?group_by=sourceIP"
                    "&agg=sum:octetDeltaCount,count&k=5")
    assert got["rows"] == posted["rows"]
    assert got["cache"] == "hit"
    # malformed plan → 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _get_json(server, "/query?group_by=noSuchColumn")
    assert e.value.code == 400
    # healthz carries the query section; /metrics exposes the series
    doc = _get_json(server, "/healthz")
    assert doc["query"]["queries"] >= 2
    assert doc["query"]["cache"]["hits"] >= 1
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics",
            timeout=10) as r:
        text = r.read().decode()
    assert "theia_query_seconds" in text
    assert "theia_query_cache_hits_total" in text
    # shed rung: queries 429 with Retry-After, control plane serves on
    monkeypatch.setenv("THEIA_ADMISSION_FORCE_LEVEL", "shed_detector")
    with pytest.raises(urllib.error.HTTPError) as e:
        _get_json(server, "/query?agg=count")
    assert e.value.code == 429
    assert int(e.value.headers["Retry-After"]) >= 1
    assert _get_json(server, "/healthz")["status"] == "degraded"


def test_query_auth_gated(monkeypatch):
    from theia_tpu.manager import TheiaManagerServer
    monkeypatch.setenv("THEIA_RETENTION_INTERVAL", "0")
    db = FlowDatabase(engine="flat")
    db.insert_flows(_batch())
    srv = TheiaManagerServer(db, port=0, workers=1,
                             auth_token="sekrit")
    srv.start_background()
    try:
        url = f"http://127.0.0.1:{srv.port}/query?agg=count"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url, timeout=10)
        assert e.value.code == 401
        req = urllib.request.Request(
            url, headers={"Authorization": "Bearer sekrit"})
        with urllib.request.urlopen(req, timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["rows"][0]["count"] == len(db.flows)
        assert doc["engine"] == "flat"
    finally:
        srv.shutdown()


def test_flat_engine_served_through_engine_object():
    db = FlowDatabase(engine="flat")
    b = _batch(seed=2)
    db.insert_flows(b)
    out = QueryEngine(db).execute(
        parse_plan({"groupBy": "sourceIP", "agg": "count", "k": 0}))
    assert out["engine"] == "flat"
    assert sum(r["count"] for r in out["rows"]) == len(b)
