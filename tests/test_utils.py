"""Tests for theia_tpu.utils: validation, logging ring buffer, env.

Reference behaviors: ParseRecommendationName (pkg/util/utils.go),
K8s quantity validation on job resource fields
(pkg/controller/networkpolicyrecommendation/controller.go:586-608),
klog -v levels, POD_NAMESPACE default (pkg/util/env/env.go).
"""

import io
import json
import tarfile
import uuid

import pytest

from theia_tpu.utils import (
    clear_logs,
    dump_logs,
    get_logger,
    get_theia_namespace,
    parse_job_name,
    parse_k8s_quantity,
    set_verbosity,
    split_job_name,
    validate_agg_flow,
    validate_algo,
    validate_k8s_quantity,
    validate_policy_type,
)


def test_parse_job_name_roundtrip():
    u = str(uuid.uuid4())
    assert parse_job_name(f"pr-{u}", "pr-") == u
    assert split_job_name(f"tad-{u}") == ("tad", u)
    with pytest.raises(ValueError):
        parse_job_name("pr-not-a-uuid", "pr-")
    with pytest.raises(ValueError):
        parse_job_name(f"tad-{u}", "pr-")
    with pytest.raises(ValueError):
        split_job_name("job-123")


@pytest.mark.parametrize("text,value", [
    ("200m", 0.2),
    ("512M", 512e6),
    ("1Gi", 2.0 ** 30),
    ("1.5", 1.5),
    ("2e3", 2000.0),
    ("100Ki", 102400.0),
    ("12E", 12e18),
])
def test_k8s_quantity_parse(text, value):
    assert parse_k8s_quantity(text) == pytest.approx(value)


@pytest.mark.parametrize("bad", ["", "abc", "1GiB", "--1", "1 Gi", "Mi"])
def test_k8s_quantity_rejects(bad):
    with pytest.raises(ValueError):
        parse_k8s_quantity(bad)
    with pytest.raises(ValueError):
        validate_k8s_quantity(bad, "--driver-memory")


def test_enum_validators():
    assert validate_algo("EWMA") == "EWMA"
    assert validate_agg_flow("pod") == "pod"
    assert validate_policy_type("k8s-np") == "k8s-np"
    for fn, bad in ((validate_algo, "KMEANS"),
                    (validate_agg_flow, "node"),
                    (validate_policy_type, "bogus")):
        with pytest.raises(ValueError):
            fn(bad)


def test_logging_ring_and_verbosity(capsys):
    clear_logs()
    set_verbosity(0)
    log = get_logger("t")
    log.info("always")
    log.v(2).info("debug-only %d", 7)
    text = dump_logs()
    assert "always" in text and "debug-only" not in text
    set_verbosity(2)
    log.v(2).info("debug-only %d", 7)
    assert "debug-only 7" in dump_logs()
    set_verbosity(0)
    clear_logs()


def test_env_namespace_default(monkeypatch):
    monkeypatch.delenv("POD_NAMESPACE", raising=False)
    assert get_theia_namespace() == "flow-visibility"
    monkeypatch.setenv("POD_NAMESPACE", "custom-ns")
    assert get_theia_namespace() == "custom-ns"


def test_support_bundle_includes_manager_logs():
    """The bundle tar must carry logs/theia-manager.log with recent
    lines (ManagerDumper parity, pkg/support/dump.go)."""
    from theia_tpu.manager.api import SupportBundleManager
    from theia_tpu.manager.jobs import JobController
    from theia_tpu.manager.stats import StatsProvider
    from theia_tpu.store import FlowDatabase

    clear_logs()
    get_logger("t").info("bundle-me")
    db = FlowDatabase()
    controller = JobController(db, workers=1)
    try:
        bundles = SupportBundleManager(
            controller, StatsProvider(db, capacity_bytes=1 << 20))
        bundles.create()
        for _ in range(100):
            if bundles.status == "collected":
                break
            import time
            time.sleep(0.05)
        assert bundles.status == "collected"
        with tarfile.open(fileobj=io.BytesIO(bundles.data()),
                          mode="r:gz") as tar:
            names = tar.getnames()
            assert "logs/theia-manager.log" in names
            raw = tar.extractfile("logs/theia-manager.log").read()
            assert b"bundle-me" in raw
            jobs = json.loads(tar.extractfile("jobs.json").read())
            assert jobs == []
    finally:
        controller.shutdown()
        clear_logs()
