"""XLA profiler capture over the system API + CLI.

SURVEY §5: the reference's runtime introspection stops at Spark-UI
scraping and ClickHouse system tables; the TPU build adds a real
accelerator profiler surface (§7.7 "XLA-profile hooks — cheap win").
"""

import io
import tarfile
import threading
import time

import pytest

from theia_tpu.cli.__main__ import main as cli_main
from theia_tpu.data.synth import SynthConfig, generate_flows
from theia_tpu.manager import TheiaManagerServer
from theia_tpu.manager.profiling import ProfileManager
from theia_tpu.store import FlowDatabase


def _busy_device(stop):
    import jax.numpy as jnp
    x = jnp.ones((256, 256))
    while not stop.is_set():
        (x @ x).block_until_ready()


def test_profile_manager_captures_trace():
    pm = ProfileManager()
    stop = threading.Event()
    t = threading.Thread(target=_busy_device, args=(stop,),
                         daemon=True)
    t.start()
    try:
        doc = pm.create(duration_seconds=0.5)
        assert doc["status"] == "collecting"
        # profiler start_trace alone takes ~15s on sandboxed hosts
        # (gVisor) and the whole capture ~60s under device load — the
        # deadline bounds runaway hangs, not capture speed
        deadline = time.time() + 240
        while pm.status == "collecting" and time.time() < deadline:
            time.sleep(0.05)
        assert pm.status == "collected", pm.to_api()
        data = pm.data()
        assert data
        names = tarfile.open(fileobj=io.BytesIO(data),
                             mode="r:gz").getnames()
        assert names, "trace directory should contain profile files"
    finally:
        stop.set()
        t.join(timeout=10)


def test_second_create_during_capture_does_not_deadlock(monkeypatch):
    """POST while collecting must answer (status collecting), not
    deadlock on the manager's own lock, and must not serve the
    previous capture's data as the new one."""
    from theia_tpu.manager import profiling
    monkeypatch.setattr(profiling, "MAX_DURATION_SECONDS", 1.0)
    pm = ProfileManager()
    pm.create(duration_seconds=1.0)
    doc = pm.create(duration_seconds=1.0)   # second, while in flight
    assert doc["status"] == "collecting"
    assert pm.data() is None                # no stale trace served
    deadline = time.time() + 60
    while pm.status == "collecting" and time.time() < deadline:
        time.sleep(0.05)
    assert pm.status == "collected"


def test_profile_duration_capped(monkeypatch):
    from theia_tpu.manager import profiling
    # shrink the cap so the capture (which holds the GLOBAL jax
    # profiler) finishes within the test
    monkeypatch.setattr(profiling, "MAX_DURATION_SECONDS", 0.3)
    pm = ProfileManager()
    doc = pm.create(duration_seconds=10_000)
    assert doc["durationSeconds"] <= 0.3
    deadline = time.time() + 60
    while pm.status == "collecting" and time.time() < deadline:
        time.sleep(0.05)
    assert pm.status == "collected", pm.to_api()


def test_profile_cli_end_to_end(tmp_path, capsys):
    db = FlowDatabase()
    db.insert_flows(generate_flows(SynthConfig(
        n_series=4, points_per_series=10, seed=4)))
    srv = TheiaManagerServer(db, port=0)
    srv.start_background()
    stop = threading.Event()
    t = threading.Thread(target=_busy_device, args=(stop,),
                         daemon=True)
    t.start()
    try:
        out = tmp_path / "prof.tar.gz"
        cli_main(["--manager-addr", f"http://127.0.0.1:{srv.port}",
                  "profile", "-d", "0.5", "-f", str(out)])
        assert "XLA profile written" in capsys.readouterr().out
        assert out.stat().st_size > 0
    finally:
        stop.set()
        t.join(timeout=10)
        srv.shutdown()


def test_profile_requires_auth_when_enabled():
    import json
    import urllib.error
    import urllib.request

    srv = TheiaManagerServer(FlowDatabase(), port=0,
                             auth_token="secret")
    srv.start_background()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/apis/"
            "system.theia.antrea.io/v1alpha1/profiles",
            method="POST", data=json.dumps({}).encode())
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 401
    finally:
        srv.shutdown()
