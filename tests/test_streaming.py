"""Streaming detector: recurrence parity, alerts, capacity, latency."""

import numpy as np

from theia_tpu.analytics.streaming import (
    StreamingDetector,
    init_state,
    stream_update,
)
from theia_tpu.data.synth import SynthConfig, generate_flows


def test_stream_update_matches_batch_ewma(rng):
    # Feeding points one at a time must reproduce the batch EWMA
    # recurrence exactly.
    from theia_tpu.ops import ewma
    xs = rng.uniform(1e5, 1e7, size=20)
    state = init_state(4)
    seen = []
    import jax.numpy as jnp
    for v in xs:
        x = np.zeros(4, np.float32); x[1] = v
        a = np.zeros(4, bool); a[1] = True
        state, _ = stream_update(state, jnp.asarray(x), jnp.asarray(a))
        seen.append(float(state.ewma[1]))
    ref = np.asarray(ewma(jnp.asarray(xs.astype(np.float32))))
    np.testing.assert_allclose(seen, ref, rtol=1e-5)


def test_streaming_detects_spike_with_ground_truth():
    cfg = SynthConfig(n_series=16, points_per_series=40,
                      anomaly_fraction=0.25, anomaly_magnitude=60.0,
                      seed=13)
    batch = generate_flows(cfg)
    det = StreamingDetector(capacity=64)
    # stream one timestep at a time (micro-batches of one point/series)
    S, T = cfg.n_series, cfg.points_per_series
    idx = np.arange(len(batch)).reshape(S, T)
    alerted_series = set()
    for t in range(T):
        micro = batch.take(idx[:, t])
        for alert in det.ingest(micro):
            info = det.describe_alert(micro, alert)
            alerted_series.add((info["sourceIP"],
                               info["sourceTransportPort"]))
    assert det.n_series == S
    sip = batch.strings("sourceIP").reshape(S, T)[:, 0]
    sport = batch["sourceTransportPort"].reshape(S, T)[:, 0]
    for i in np.nonzero(batch.ground_truth_anomalous)[0]:
        assert (sip[i], int(sport[i])) in alerted_series, \
            f"missed ground-truth spike in series {i}"


def test_streaming_multiple_points_per_batch_ordered():
    # all points of each series in ONE micro-batch: ticks preserve order
    cfg = SynthConfig(n_series=4, points_per_series=30,
                      anomaly_fraction=1.0, anomaly_magnitude=80.0,
                      seed=3)
    batch = generate_flows(cfg)
    det = StreamingDetector(capacity=16)
    alerts = det.ingest(batch)
    assert alerts  # every series has a spike
    assert det.n_series == 4


def test_capacity_overflow_drops_and_counts():
    cfg = SynthConfig(n_series=8, points_per_series=2, seed=1)
    batch = generate_flows(cfg)
    det = StreamingDetector(capacity=3)
    det.ingest(batch)
    assert det.n_series == 3
    assert det.dropped_series > 0


def test_alert_latency_recorded():
    cfg = SynthConfig(n_series=8, points_per_series=30,
                      anomaly_fraction=1.0, anomaly_magnitude=80.0,
                      seed=5)
    batch = generate_flows(cfg)
    det = StreamingDetector(capacity=16)
    alerts = det.ingest(batch)
    assert alerts and all(0 < a["latency_s"] < 60 for a in alerts)


def test_dropped_series_counted_once():
    cfg = SynthConfig(n_series=8, points_per_series=10, seed=1)
    batch = generate_flows(cfg)
    det = StreamingDetector(capacity=3)
    det.ingest(batch)
    det.ingest(batch)  # same overflow series again
    assert det.n_series == 3
    assert det.dropped_series == 5  # once per series, not per row
