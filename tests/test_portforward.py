"""CLI port-forward path (reference pkg/theia/portforwarder): the CLI
tunnels to the in-cluster manager via `kubectl port-forward`,
exercised here with a fake kubectl that fronts a real manager."""

import stat
import time

import pytest

from theia_tpu.cli.__main__ import main as cli_main
from theia_tpu.cli.portforward import PortForwarder, PortForwardError
from theia_tpu.data.synth import SynthConfig, generate_flows
from theia_tpu.manager import TheiaManagerServer
from theia_tpu.store import FlowDatabase


def _fake_kubectl(tmp_path, port: int, lines=None, rc=0):
    """A kubectl stand-in: prints the port-forward banner for `port`
    then stays alive (like the real forwarder does)."""
    script = tmp_path / "kubectl"
    body = lines if lines is not None else [
        f"Forwarding from 127.0.0.1:{port} -> 11347",
        f"Forwarding from [::1]:{port} -> 11347",
    ]
    script.write_text(
        "#!/bin/sh\n"
        + "".join(f"echo '{line}'\n" for line in body)
        + (f"exit {rc}\n" if rc else "sleep 600\n"))
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    return str(script)


@pytest.fixture()
def server():
    db = FlowDatabase()
    db.insert_flows(generate_flows(SynthConfig(
        n_series=6, points_per_series=10, seed=5)))
    srv = TheiaManagerServer(db, port=0)
    srv.start_background()
    yield srv
    srv.shutdown()


def test_port_forwarder_parses_port_and_stops(server, tmp_path):
    kc = _fake_kubectl(tmp_path, server.port)
    fw = PortForwarder("flow-visibility", kubectl=kc)
    try:
        assert fw.start() == server.port
        assert fw._proc.poll() is None   # forwarder held open
    finally:
        fw.stop()
    assert fw._proc is None


def test_cli_use_port_forward_end_to_end(server, tmp_path, capsys):
    kc = _fake_kubectl(tmp_path, server.port)
    cli_main(["--use-port-forward", "--kubectl", kc,
              "tad", "run", "--algo", "EWMA", "--wait"])
    out = capsys.readouterr().out
    assert "Successfully started" in out
    # the forwarder child was torn down with the command
    import subprocess
    time.sleep(0.2)
    left = subprocess.run(["pgrep", "-f", kc], capture_output=True,
                          text=True).stdout.strip()
    assert not left


def test_missing_kubectl_is_a_clean_error():
    fw = PortForwarder("ns", kubectl="/nonexistent/kubectl")
    with pytest.raises(PortForwardError, match="PATH"):
        fw.start()


def test_kubectl_failure_reports_output(tmp_path):
    kc = _fake_kubectl(tmp_path, 0,
                       lines=["error: unable to forward"], rc=1)
    fw = PortForwarder("ns", kubectl=kc)
    # the operator sees kubectl's own words, not just "did not come up"
    with pytest.raises(PortForwardError,
                       match="unable to forward"):
        fw.start()
