"""Part-based columnar storage engine (store/parts.py).

The contract under test is PARITY: a parts-engine FlowDatabase fed the
same operations as a flat one returns byte-identical `scan()` /
`select()` results — through seals, merges, pruned selects, positional
and value deletes, TTL eviction, tiered demotion, and kill -9
recovery (manifest + WAL tail, torn manifest falling back to the
previous generation). Plus the engine-specific machinery: min/max
pruning counters, O(parts) retention boundary selection, cold-tier
round trips, part-file GC, and concurrent insert-during-merge.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np
import pytest

from theia_tpu.data.synth import SynthConfig, generate_flows
from theia_tpu.obs import metrics as obs_metrics
from theia_tpu.schema import FLOW_SCHEMA
from theia_tpu.store import FlowDatabase, PartTable, ShardedFlowDatabase
from theia_tpu.store.parts import MANIFEST_NAME

pytestmark = pytest.mark.parts


def _batch(n_series=20, points=10, seed=0, shift=0):
    b = generate_flows(SynthConfig(n_series=n_series,
                                   points_per_series=points,
                                   seed=seed))
    if shift:
        for col in ("timeInserted", "flowStartSeconds",
                    "flowEndSeconds"):
            b.columns[col] = b[col] + shift
    return b


def assert_batches_equal(a, b, schema=FLOW_SCHEMA):
    """Byte-identical: same length, same decoded strings, same numeric
    values, and same dictionary CODES (the parts engine decodes into
    table-global code space, so even codes must match the flat
    engine's)."""
    assert len(a) == len(b)
    for c in schema:
        if c.is_string:
            np.testing.assert_array_equal(
                a.strings(c.name), b.strings(c.name), err_msg=c.name)
            np.testing.assert_array_equal(a[c.name], b[c.name],
                                          err_msg=f"{c.name} codes")
        else:
            np.testing.assert_array_equal(a[c.name], b[c.name],
                                          err_msg=c.name)


def _pair(tmp_path=None, memtable_rows=128, ttl_seconds=None, **cfg):
    """(flat, parts) FlowDatabases; parts sealed small so a few
    hundred rows exercise multi-part structure."""
    parts_cfg = {"memtable_rows": memtable_rows, **cfg}
    flat = FlowDatabase(engine="flat", ttl_seconds=ttl_seconds)
    parts = FlowDatabase(
        engine="parts", ttl_seconds=ttl_seconds,
        parts_dir=str(tmp_path / "parts") if tmp_path else None,
        parts_config=parts_cfg)
    return flat, parts


def _counter(name, **labels):
    m = obs_metrics.REGISTRY.get(name)
    if m is None:
        return 0.0
    child = m.labels(**labels) if labels else m
    return child.value()


# -- seal / scan / select parity ------------------------------------------


def test_seal_and_scan_parity():
    flat, parts = _pair()
    for i in range(4):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    st = parts.flows.parts_stats()
    assert st["count"] >= 1 and st["sealed"] >= 1
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())


def test_parts_compress_resident_bytes():
    flat, parts = _pair()
    b = _batch(n_series=100)
    flat.insert_flows(b)
    parts.insert_flows(b)
    parts.flows.seal()
    n = len(flat.flows)
    flat_bpr = flat.flows.nbytes / n
    parts_bpr = parts.flows.nbytes / n
    # acceptance floor: ≤ 120 B/row resident, ≥ 2.3x vs flat's 284
    assert parts_bpr <= 120, parts_bpr
    assert flat_bpr / parts_bpr >= 2.3


def test_select_prunes_parts_and_counts():
    flat, parts = _pair()
    # three disjoint hour partitions
    for i in range(3):
        b = _batch(seed=i, shift=i * 3600 * 24)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    n_parts = parts.flows.parts_stats()["count"]
    assert n_parts >= 3
    lo = int(flat.flows.scan()["flowStartSeconds"].min())
    pruned0 = _counter("theia_store_parts_pruned_total")
    # a window covering only the first day must prune later parts
    sel_f = flat.flows.select(start_time=lo, end_time=lo + 3600 * 12)
    sel_p = parts.flows.select(start_time=lo, end_time=lo + 3600 * 12)
    assert len(sel_p) > 0
    assert_batches_equal(sel_f, sel_p)
    assert _counter("theia_store_parts_pruned_total") > pruned0
    # fully out-of-window select prunes everything sealed
    sel_f = flat.flows.select(start_time=10**12, end_time=10**12 + 1)
    sel_p = parts.flows.select(start_time=10**12, end_time=10**12 + 1)
    assert len(sel_f) == len(sel_p) == 0


def test_randomized_parity_with_deletes_and_ttl():
    rng = np.random.default_rng(7)
    flat, parts = _pair(memtable_rows=97, ttl_seconds=3600 * 48)
    for step in range(12):
        op = rng.integers(0, 4)
        if op <= 1:   # insert (weighted)
            b = _batch(n_series=int(rng.integers(5, 30)),
                       seed=int(rng.integers(0, 50)),
                       shift=int(rng.integers(0, 4)) * 3600)
            now = int(max(b["timeInserted"].max(),
                          (flat.flows.min_value() or 0)))
            flat.insert_flows(b, now=now)
            parts.insert_flows(b, now=now)
        elif op == 2 and len(flat.flows):   # boundary delete
            t = np.asarray(flat.flows.scan()["timeInserted"])
            boundary = int(np.quantile(t, float(rng.random())))
            d1 = flat.delete_flows_older_than(boundary)
            d2 = parts.delete_flows_older_than(boundary)
            assert d1 == d2
        elif op == 3 and len(flat.flows):   # value delete by ids
            ips = flat.flows.scan().strings("sourceIP")
            pick = list(np.unique(ips[:8])) + ["10.99.99.99"]
            d1 = flat.flows.delete_ids(pick, column="sourceIP")
            d2 = parts.flows.delete_ids(pick, column="sourceIP")
            assert d1 == d2
        assert_batches_equal(flat.flows.scan(), parts.flows.scan())
        if len(flat.flows):
            t = np.asarray(flat.flows.scan()["flowStartSeconds"])
            lo, hi = int(t.min()), int(t.max())
            mid = (lo + hi) // 2
            assert_batches_equal(
                flat.flows.select(start_time=lo, end_time=mid),
                parts.flows.select(start_time=lo, end_time=mid))


def test_delete_where_positional_mask_parity():
    flat, parts = _pair()
    for i in range(3):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    n = len(flat.flows)
    mask = np.zeros(n, bool)
    mask[::3] = True
    assert flat.flows.delete_where(mask.copy()) == \
        parts.flows.delete_where(mask.copy())
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())


def test_delete_ids_invert_and_missing():
    flat, parts = _pair()
    b = _batch()
    flat.insert_flows(b)
    parts.insert_flows(b)
    keep = [str(s) for s in np.unique(b.dicts["sourceIP"]
                                      .decode(b["sourceIP"]))[:3]]
    d1 = flat.flows.delete_ids(keep, column="sourceIP", invert=True)
    d2 = parts.flows.delete_ids(keep, column="sourceIP", invert=True)
    assert d1 == d2 > 0
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())
    # ids absent from the dictionary match nothing (no allocation)
    assert flat.flows.delete_ids(["no.such.ip"],
                                 column="sourceIP") == 0
    assert parts.flows.delete_ids(["no.such.ip"],
                                  column="sourceIP") == 0


# -- merges ---------------------------------------------------------------


def test_merge_compacts_and_preserves_parity(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=50, part_rows=10000)
    for i in range(6):
        b = _batch(n_series=10, seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    before = parts.flows.parts_stats()["count"]
    merges = parts.maintenance_tick()
    after = parts.flows.parts_stats()
    assert merges >= 1
    assert after["count"] < before
    assert after["merges"] == merges
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())


def test_concurrent_insert_during_merge(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=40, part_rows=100000)
    batches = [_batch(n_series=8, seed=i) for i in range(12)]
    done = threading.Event()

    def inserter():
        for b in batches:
            parts.insert_flows(b)
        done.set()

    t = threading.Thread(target=inserter)
    t.start()
    while not done.is_set():
        parts.maintenance_tick()
    t.join()
    parts.maintenance_tick()
    for b in batches:
        flat.insert_flows(b)
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())


# -- tiered retention ------------------------------------------------------


def test_cold_demote_reload_roundtrip(tmp_path):
    flat, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(4):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    parts.flows.seal()
    resident_before = parts.flows.nbytes
    freed = parts.demote_cold(resident_before // 3)
    st = parts.flows.parts_stats()
    assert freed > 0 and st["cold"] > 0
    assert parts.flows.nbytes == resident_before - freed
    # cold parts decode on demand from their self-contained files
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())
    # pruned selects skip cold decodes too
    lo = int(flat.flows.scan()["flowStartSeconds"].min())
    assert_batches_equal(
        flat.flows.select(start_time=lo, end_time=lo + 600),
        parts.flows.select(start_time=lo, end_time=lo + 600))


def test_demote_requires_directory():
    _, parts = _pair(None)
    parts.insert_flows(_batch())
    parts.flows.seal()
    assert parts.demote_cold(0) == 0   # nowhere to spill


def test_retention_demotes_before_deleting(tmp_path):
    _, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(4):
        parts.insert_flows(_batch(seed=i))
    parts.flows.seal()
    rows = len(parts.flows)
    mon = parts.monitor(capacity_bytes=max(parts.flows.nbytes // 2, 1),
                        threshold=0.5, skip_rounds=0)
    deleted = mon.tick()
    # over capacity, but demotion alone reaches the threshold: data
    # survives on the cold tier instead of being deleted
    assert deleted == 0
    assert mon.bytes_demoted > 0
    assert len(parts.flows) == rows
    assert parts.flows.parts_stats()["cold"] > 0


def test_retention_deletes_when_demotion_cannot_help():
    _, parts = _pair(None, memtable_rows=64)   # no directory
    for i in range(4):
        parts.insert_flows(_batch(seed=i))
    parts.flows.seal()
    rows = len(parts.flows)
    mon = parts.monitor(capacity_bytes=max(parts.flows.nbytes // 2, 1),
                        threshold=0.5, delete_percentage=0.5,
                        skip_rounds=0)
    deleted = mon.tick()
    assert deleted > 0
    assert len(parts.flows) == rows - deleted


def test_retention_boundary_matches_full_sort():
    rng = np.random.default_rng(3)
    flat, parts = _pair(None, memtable_rows=77)
    for i in range(5):
        b = _batch(n_series=15, seed=i,
                   shift=int(rng.integers(0, 3)) * 1800)
        flat.insert_flows(b)
        parts.insert_flows(b)
    t = np.sort(np.asarray(flat.flows.scan()["timeInserted"]))
    for frac in (0.1, 0.5, 0.9):
        k = int(len(t) * frac)
        want = int(t[k - 1])
        assert flat.flows.retention_boundary(k) == want
        assert parts.flows.retention_boundary(k) == want


def test_min_value_cached_through_mutations():
    flat, parts = _pair(None, memtable_rows=64)
    for i in range(3):
        b = _batch(seed=i, shift=i * 3600)
        flat.insert_flows(b)
        parts.insert_flows(b)
    for db in (flat, parts):
        data = db.flows.scan()
        assert db.flows.min_value("timeInserted") == \
            int(data["timeInserted"].min())
    boundary = int(np.quantile(
        np.asarray(flat.flows.scan()["timeInserted"]), 0.4))
    flat.delete_flows_older_than(boundary)
    parts.delete_flows_older_than(boundary)
    for db in (flat, parts):
        data = db.flows.scan()
        assert db.flows.min_value("timeInserted") == \
            int(data["timeInserted"].min())


# -- manifest recovery -----------------------------------------------------


def test_manifest_recovery_with_wal_tail(tmp_path):
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 128})
    db.attach_wal(d + "/wal", sync="always")
    db.insert_flows(_batch(seed=1))
    db.save(d + "/db.npz")
    db.insert_flows(_batch(seed=2))   # WAL tail above the stamp
    # kill -9: no close, no final save — acked rows must survive
    db2 = FlowDatabase.load(d + "/db.npz")
    assert db2.engine == "parts"
    st = db2.attach_wal(d + "/wal")
    assert st["recoveredRows"] > 0
    assert_batches_equal(db.flows.scan(), db2.flows.scan())
    # views recovered too (restored aggregates + replayed tail)
    for name in db.views:
        va, vb = db.views[name].scan(), db2.views[name].scan()
        assert len(va) == len(vb), name
    db.close_wal()
    db2.close_wal()


def test_manifest_parts_load_lazily(tmp_path):
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 64})
    db.insert_flows(_batch(seed=1))
    db.flows.seal()
    db.save(d + "/db.npz")
    db2 = FlowDatabase.load(d + "/db.npz")
    assert isinstance(db2.flows, PartTable)
    with db2.flows._lock:
        lazy = [p.chunks is None for p in db2.flows._parts]
    assert lazy and all(lazy)   # metadata resident, columns deferred
    assert len(db2.flows) == len(db.flows)   # counts from manifest
    assert_batches_equal(db.flows.scan(), db2.flows.scan())


def test_torn_manifest_falls_back_to_previous_generation(tmp_path):
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 128})
    db.attach_wal(d + "/wal", sync="always")
    db.insert_flows(_batch(seed=1))
    db.save(d + "/db.npz")          # generation 1
    db.insert_flows(_batch(seed=2))
    db.save(d + "/db.npz")          # generation 2
    with open(os.path.join(d, "parts", MANIFEST_NAME), "w") as f:
        f.write("{torn garbage")    # primary manifest destroyed
    db2 = FlowDatabase.load(d + "/db.npz")
    st = db2.attach_wal(d + "/wal")
    # generation-1 snapshot + manifest pair loads; the lag-one WAL GC
    # kept the tail above ITS stamp, so nothing is lost
    assert st["recoveredRows"] > 0
    assert_batches_equal(db.flows.scan(), db2.flows.scan())
    db.close_wal()
    db2.close_wal()


def test_manifest_missing_part_file_falls_back(tmp_path):
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 64})
    db.attach_wal(d + "/wal", sync="always")
    db.insert_flows(_batch(seed=1))
    db.flows.seal()
    db.save(d + "/db.npz")
    db.insert_flows(_batch(seed=2))
    db.flows.seal()
    db.save(d + "/db.npz")
    # destroy a part file referenced by the CURRENT manifest only
    with open(os.path.join(d, "parts", MANIFEST_NAME)) as f:
        cur = {e["file"] for e in json.load(f)["parts"]}
    with open(os.path.join(d, "parts",
                           MANIFEST_NAME + ".prev")) as f:
        prev = {e["file"] for e in json.load(f)["parts"]}
    victim = sorted(cur - prev)
    assert victim, "second save should have sealed new parts"
    os.unlink(os.path.join(d, "parts", victim[0]))
    db2 = FlowDatabase.load(d + "/db.npz")
    db2.attach_wal(d + "/wal")
    assert_batches_equal(db.flows.scan(), db2.flows.scan())
    db.close_wal()
    db2.close_wal()


def test_orphan_manifest_generation_repaired_on_recovery(tmp_path):
    """Crash between manifest publish and npz publish leaves an
    orphan manifest generation. Recovery must repair the slot state
    so a LATER publish's rotation cannot evict the generation the
    `.prev` snapshot still pairs with (one crash must not void the
    fallback forever)."""
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 64})
    db.attach_wal(d + "/wal", sync="always")
    db.insert_flows(_batch(seed=1))
    db.save(d + "/db.npz")          # gen 1
    db.insert_flows(_batch(seed=2))
    db.save(d + "/db.npz")          # gen 2
    # simulate the crash window: a manifest generation published with
    # NO paired npz (kill -9 between the two publishes)
    entries, _ = db.flows.snapshot_parts_state()
    db.flows.publish_manifest(entries, db.wal_position())   # gen 3
    db.close_wal()
    db2 = FlowDatabase.load(d + "/db.npz")   # matches via .prev (2)
    db2.attach_wal(d + "/wal")
    assert_batches_equal(db.flows.scan(), db2.flows.scan())
    db2.insert_flows(_batch(seed=3))
    db2.save(d + "/db.npz")   # next generation must rotate cleanly
    # the corrupt-primary fallback still works after the repair
    with open(d + "/db.npz", "wb") as f:
        f.write(b"garbage")
    db3 = FlowDatabase.load(d + "/db.npz")
    db3.attach_wal(d + "/wal")
    assert_batches_equal(db2.flows.scan(), db3.flows.scan())
    db2.close_wal()
    db3.close_wal()


def test_delete_then_save_keeps_inflight_manifest_loadable(tmp_path):
    """A part file retired by a delete must survive on disk until the
    GC can prove no manifest generation references it — deleting
    between a save's entry capture and its publish would otherwise
    produce an unloadable recovery point."""
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 64})
    db.insert_flows(_batch(seed=1))
    db.flows.seal()
    # capture entries (as save() would under quiesce) ...
    entries, payload = db.flows.snapshot_parts_state()
    # ... then a retention delete retires every part before publish
    db.delete_flows_older_than(10**12)
    assert len(db.flows) == 0
    gen = db.flows.publish_manifest(entries, None)
    # the captured generation must still load: every referenced file
    # must exist with the manifested size
    db.flows.gc_part_files()
    fresh = FlowDatabase(engine="parts", parts_dir=d + "/parts")
    assert fresh.flows.load_manifest(gen) == sum(
        e["rows"] for e in entries)


def test_part_file_gc_keeps_manifest_pair(tmp_path):
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 50,
                                    "part_rows": 10000})
    for i in range(6):
        db.insert_flows(_batch(n_series=10, seed=i))
    db.save(d + "/db.npz")
    db.maintenance_tick()           # merge → old files unreferenced
    db.insert_flows(_batch(seed=9))
    db.save(d + "/db.npz")          # publishes + GCs
    disk = {n for n in os.listdir(d + "/parts")
            if n.endswith(".tprt")}
    referenced = set()
    for suffix in ("", ".prev"):
        with open(os.path.join(d, "parts",
                               MANIFEST_NAME + suffix)) as f:
            referenced |= {e["file"] for e in json.load(f)["parts"]}
    assert disk == referenced   # nothing dangling, nothing missing


def test_parts_snapshot_loads_into_flat_engine(tmp_path):
    """Engine-flip escape hatch: a parts-aware snapshot must load
    into a flat store (cross-engine donor path)."""
    d = str(tmp_path)
    db = FlowDatabase(engine="parts", parts_dir=d + "/parts",
                      parts_config={"memtable_rows": 64})
    db.insert_flows(_batch(seed=1))
    db.save(d + "/db.npz")
    db2 = FlowDatabase.load(d + "/db.npz", engine="flat")
    assert db2.engine == "flat"
    assert_batches_equal(db.flows.scan(), db2.flows.scan())


def test_dirless_parts_engine_snapshots_wholesale(tmp_path):
    """No part directory → save falls back to the legacy full npz
    (correct, just not incremental) and round-trips."""
    d = str(tmp_path)
    _, parts = _pair(None)
    parts.insert_flows(_batch(seed=1))
    parts.flows.seal()
    parts.save(d + "/db.npz")
    db2 = FlowDatabase.load(d + "/db.npz", engine="flat")
    assert_batches_equal(parts.flows.scan(), db2.flows.scan())


# -- sharded / stats -------------------------------------------------------


def test_sharded_parts_parity_and_stats():
    flat = ShardedFlowDatabase(n_shards=2, seed=11, engine="flat")
    parts = ShardedFlowDatabase(
        n_shards=2, seed=11, engine="parts",
        parts_config={"memtable_rows": 64})
    for i in range(3):
        b = _batch(seed=i)
        flat.insert_flows(b)
        parts.insert_flows(b)
    # same seed → same rand() routing → byte-identical logical order
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())
    st = parts.store_stats()
    assert st["engine"] == "parts" and st["shards"] == 2
    assert st["parts"]["count"] >= 1
    assert parts.maintenance_tick() >= 0
    # positional delete through the distributed facade
    n = len(flat.flows)
    mask = np.zeros(n, bool)
    mask[::2] = True
    assert flat.flows.delete_where(mask.copy()) == \
        parts.flows.delete_where(mask.copy())
    assert_batches_equal(flat.flows.scan(), parts.flows.scan())


def test_replicated_cold_dir_save_load_roundtrip(tmp_path, monkeypatch):
    """With THEIA_STORE_COLD_DIR set, replicas resolve per-replica
    subdirectories (no shared GC), and a save/load round trip works:
    the snapshot's recorded directory — the replica subdir, not the
    env base — is where its manifest lives."""
    from theia_tpu.store import ReplicatedFlowDatabase
    monkeypatch.setenv("THEIA_STORE_ENGINE", "parts")
    monkeypatch.setenv("THEIA_STORE_COLD_DIR", str(tmp_path / "cold"))
    monkeypatch.setenv("THEIA_STORE_MEMTABLE_ROWS", "64")
    db = ReplicatedFlowDatabase(replicas=2)
    dirs = {r.flows.directory for r in db.replicas}
    assert len(dirs) == 2, "replicas must not share a part directory"
    db.insert_flows(_batch(seed=1))
    db.replicas[0].flows.seal()
    db.save(str(tmp_path / "db.npz"))
    db2 = ReplicatedFlowDatabase.load(str(tmp_path / "db.npz"),
                                      replicas=2)
    assert_batches_equal(db.flows.scan(), db2.flows.scan())


def test_cold_part_rewrite_stays_cold(tmp_path):
    """A retention delete straddling a COLD part must not re-promote
    its survivors to RAM — that would migrate the cold tier back into
    memory one retention round at a time."""
    _, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(3):
        parts.insert_flows(_batch(seed=i, shift=i * 3600))
    parts.flows.seal()
    parts.demote_cold(0)   # everything demotable goes cold
    assert parts.flows.parts_stats()["cold"] >= 3
    t = np.asarray(parts.flows.scan()["timeInserted"])
    boundary = int(np.quantile(t, 0.5))
    deleted = parts.delete_flows_older_than(boundary)
    assert deleted > 0
    st = parts.flows.parts_stats()
    assert st["hot"] == 0, "survivors of cold parts must stay cold"
    assert parts.flows.nbytes == 0   # nothing resident
    assert len(parts.flows) == len(t) - deleted


def test_unpublished_table_maintenance_gcs_files(tmp_path):
    """Sharded/replicated part tables never publish a manifest, so
    their retired part files (and pending-fsync entries) must be
    collected by the maintenance pass instead of accumulating
    forever."""
    sh = ShardedFlowDatabase(
        n_shards=2, seed=3, engine="parts",
        parts_dir=str(tmp_path),
        parts_config={"memtable_rows": 50, "part_rows": 10000})
    for i in range(6):
        sh.insert_flows(_batch(n_series=10, seed=i))
    sh.maintenance_tick()           # merges retire pre-merge files
    sh.delete_flows_older_than(10**12)   # retire everything else
    # the unpublished GC is TWO-PHASE: pass 1 marks unreferenced
    # files, pass 2 unlinks them — in-flight readers that snapshotted
    # the part list get one maintenance interval of grace
    sh.maintenance_tick()
    sh.maintenance_tick()
    leftovers = [n for d in os.listdir(tmp_path)
                 for n in os.listdir(os.path.join(tmp_path, d))
                 if n.endswith(".tprt")]
    assert leftovers == []
    for shard in sh.shards:
        assert shard.flows._pending_fsync == []


def test_maintenance_materializes_rewritten_parts(tmp_path):
    """Hot parts rewritten by a delete are fileless (no disk I/O under
    the table lock); the maintenance pass must materialize their files
    so they stay demotable."""
    _, parts = _pair(tmp_path, memtable_rows=64)
    for i in range(3):
        parts.insert_flows(_batch(seed=i, shift=i * 3600))
    parts.flows.seal()
    t = np.asarray(parts.flows.scan()["timeInserted"])
    parts.delete_flows_older_than(int(np.quantile(t, 0.3)))
    with parts.flows._lock:
        assert any(p.path is None for p in parts.flows._parts)
    parts.maintenance_tick()
    with parts.flows._lock:
        assert all(p.path is not None for p in parts.flows._parts)
    assert parts.demote_cold(0) > 0   # now demotable again


def test_store_stats_shape():
    _, parts = _pair(None)
    parts.insert_flows(_batch())
    doc = parts.store_stats()
    assert doc["engine"] == "parts"
    for key in ("count", "hot", "cold", "hotBytes", "coldBytes",
                "memtableRows", "sealed", "merges", "demoted"):
        assert key in doc["parts"], key
    flat = FlowDatabase(engine="flat")
    assert flat.store_stats()["engine"] == "flat"
    assert "parts" not in flat.store_stats()


def test_healthz_and_metrics_surface_parts(tmp_path):
    import urllib.request

    from theia_tpu.manager.api import TheiaManagerServer
    _, parts = _pair(tmp_path)
    parts.insert_flows(_batch())
    parts.flows.seal()
    srv = TheiaManagerServer(parts, port=0, workers=1)
    srv.start_background()
    try:
        addr = f"http://127.0.0.1:{srv.port}"
        doc = json.load(urllib.request.urlopen(addr + "/healthz",
                                               timeout=10))
        assert doc["store"]["engine"] == "parts"
        assert doc["store"]["parts"]["count"] >= 1
        assert "maintenance" in doc["store"]
        text = urllib.request.urlopen(addr + "/metrics",
                                      timeout=10).read().decode()
        assert "theia_store_parts " in text
        assert 'theia_store_part_bytes{tier="hot"}' in text
    finally:
        srv.shutdown()
