"""Runner CLI contract: tad/npr subcommands, progress file, db roundtrip."""

import json

import pytest

from theia_tpu.data.synth import SynthConfig, generate_flows
from theia_tpu.runner.__main__ import build_parser, main, parse_time
from theia_tpu.store import FlowDatabase


@pytest.fixture()
def db_path(tmp_path):
    db = FlowDatabase()
    db.insert_flows(generate_flows(SynthConfig(
        n_series=12, points_per_series=20, anomaly_fraction=0.3,
        anomaly_magnitude=60.0, seed=4)))
    path = str(tmp_path / "flows.npz")
    db.save(path)
    return path


def test_parse_time_utc():
    assert parse_time("2021-01-01 00:00:00") == 1609459200
    assert parse_time("") is None


def test_tad_job_writes_results_and_progress(db_path, tmp_path, capsys):
    progress_path = str(tmp_path / "progress.json")
    main(["tad", "--db", db_path, "--algo", "EWMA", "--id", "job-1",
          "--progress-file", progress_path])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out) == {"id": "job-1", "state": "COMPLETED"}
    progress = json.load(open(progress_path))
    assert progress["state"] == "COMPLETED"
    assert progress["completedStages"] == progress["totalStages"] == 4

    db = FlowDatabase.load(db_path)
    rows = db.tadetector.scan().to_rows()
    assert any(r["id"] == "job-1" and r["anomaly"] == "true" for r in rows)


def test_tad_agg_flow_args(db_path, capsys):
    main(["tad", "--db", db_path, "--algo", "EWMA", "--agg-flow", "pod",
          "--id", "job-pod",
          "--ns-ignore-list", '["kube-system"]'])
    db = FlowDatabase.load(db_path)
    rows = [r for r in db.tadetector.scan().to_rows()
            if r["id"] == "job-pod"]
    assert rows and all(r["aggType"] == "pod" for r in rows)


def test_tad_pod_namespace_alone_rejected(db_path):
    with pytest.raises(SystemExit):
        main(["tad", "--db", db_path, "--algo", "EWMA",
              "--agg-flow", "pod", "--pod-namespace", "ns-1"])


def test_tad_time_window_args(db_path, capsys):
    main(["tad", "--db", db_path, "--algo", "EWMA", "--id", "job-t",
          "--start_time", "2020-01-01 00:00:00",
          "--end_time", "2020-01-02 00:00:00"])
    # window before all synth data → no anomalies → filler row
    db = FlowDatabase.load(db_path)
    rows = [r for r in db.tadetector.scan().to_rows()
            if r["id"] == "job-t"]
    assert len(rows) == 1 and rows[0]["anomaly"] == "NO ANOMALY DETECTED"


def test_npr_job(db_path, tmp_path, capsys):
    progress_path = str(tmp_path / "p.json")
    main(["npr", "--db", db_path, "--type", "initial", "-o", "1",
          "--id", "rec-1", "--progress-file", progress_path])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["id"] == "rec-1"
    db = FlowDatabase.load(db_path)
    rows = db.recommendations.scan().to_rows()
    assert any(r["id"] == "rec-1" and r["kind"] == "anp" for r in rows)
    assert json.load(open(progress_path))["state"] == "COMPLETED"


def test_npr_failure_marks_progress(tmp_path):
    progress_path = str(tmp_path / "p.json")
    with pytest.raises(BaseException):
        main(["npr", "--db", str(tmp_path / "missing.npz"),
              "--id", "rec-x", "--progress-file", progress_path])
    assert json.load(open(progress_path))["state"] == "FAILED"


def test_parser_rejects_bad_algo(db_path):
    with pytest.raises(SystemExit):
        build_parser().parse_args(["tad", "--db", db_path,
                                   "--algo", "LSTM"])
